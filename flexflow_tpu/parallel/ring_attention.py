"""Ring attention: sequence/context parallelism over the ICI ring.

The reference has **no long-context support** (SURVEY §5: "no ring attention,
no Ulysses"); its only sequence notion is a seq_length iteration config. This
module provides the TPU-native capability the reference lacks: queries stay
resident on their sequence shard while K/V blocks rotate around the `seq`
mesh axis via `jax.lax.ppermute` (Ring Attention, Liu et al. 2023).

Round-7 roofline rewrite — the ring body is an explicitly DOUBLE-BUFFERED
ppermute pipeline:

  - the hop delivering block k+1 is issued BEFORE block k's attention
    compute, so the collective-permute has no data dependence on the
    compute and XLA's latency-hiding scheduler overlaps the two (the
    decomposition schedule of Wang et al., ASPLOS '23, expressed at the
    shard_map level). `overlap=False` restores the serial
    compute-then-hop order for ablation (bench.py's ring legs).
  - per-block attention routes through the flash/online-softmax kernel
    (`flash_attention_with_lse`) instead of a full materialized
    (b, h, s_loc, s_loc) f32 einsum — HBM traffic per block drops from
    O(s_loc²) to O(s_loc·d), the difference between roofline-bound and
    memory-bound at seq 4096.
  - block contributions merge by (out, lse) pairs:
    lse = logaddexp(lse, lse_blk), out = Σ out_blk·exp(lse_blk − lse) —
    the same online-softmax algebra the in-kernel accumulator uses,
    lifted to block granularity.
  - under a causal mask, ring blocks that originated on a LATER shard
    (src > idx ⇔ step > idx) are fully masked; their attention compute is
    skipped via `lax.cond` instead of masked to zero after the einsum —
    shard idx computes only idx+1 of the n blocks (~2× less work on
    average). The hop itself still runs every non-final step (it is a
    lockstep collective: later shards still need the block), and the
    final rotation — whose result no shard consumes — is skipped
    entirely.

Used by MultiHeadAttention(impl="ring") together with the
`sequence_parallel_attention` strategy (seq dim sharded over AXIS_SEQ).
The Unity cost model prices this op's ring traffic on an `overlappable`
comm channel — max(compute, comm) instead of compute + comm — so the
search sees the same overlap the schedule delivers (search/cost_model.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..machine import AXIS_DATA, AXIS_MODEL, AXIS_SEQ
from .smap import shard_map


def _block_attention(q, k_blk, v_blk, *, causal: bool, scale: float):
    """One ring block's attention: (out f32, lse f32) via the flash
    online-softmax kernel (Pallas on TPU, its einsum-lse fallback at
    shapes the kernel can't tile — including the small CPU test shards)."""
    from ..kernels.flash_attention import flash_attention_with_lse

    out, lse = flash_attention_with_lse(q, k_blk, v_blk, causal=causal,
                                        scale=scale)
    return out.astype(jnp.float32), lse


def _merge_block(o, lse, o_blk, lse_blk):
    """Online merge of a new block's (out, lse) into the running pair.
    With lse initialized to -inf the first merge reduces to (o_blk,
    lse_blk) exactly (exp(-inf − finite) == 0)."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    o_new = (o * jnp.exp(lse - lse_new)[..., None]
             + o_blk * jnp.exp(lse_blk - lse_new)[..., None])
    return o_new, lse_new


def _ring_local(q, k, v, *, axis_name: str, n: int, causal: bool,
                scale: float, overlap: bool):
    """Per-shard body (inside shard_map). q,k,v: (b, h, s_loc, d) local.

    Unrolled over the `n` ring steps (n = seq-axis size, small and
    static). Double-buffered: the step-k hop is in flight while block k's
    flash attention runs (see module docstring)."""
    idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    from .ops import ring_permutation

    o = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    perm = ring_permutation(n)
    k_blk, v_blk = k, v

    for step in range(n):
        k_nxt = v_nxt = None
        if overlap and step < n - 1:
            # issue the hop for block step+1 BEFORE computing block step:
            # the permute has no dependence on the compute below, so the
            # scheduler can run them concurrently (double buffering)
            k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        if not causal:
            o, lse = _merge_block(
                o, lse, *_block_attention(q, k_blk, v_blk, causal=False,
                                          scale=scale))
        elif step == 0:
            # the resident block (src == idx): the diagonal — the only
            # block that needs an in-block causal mask
            o, lse = _merge_block(
                o, lse, *_block_attention(q, k_blk, v_blk, causal=True,
                                          scale=scale))
        else:
            # block from src = (idx - step) mod n: fully live iff
            # src < idx ⇔ step <= idx, fully masked otherwise — skip the
            # compute entirely instead of masking it to zero afterwards
            def _live(o, lse, kb, vb):
                return _merge_block(
                    o, lse, *_block_attention(q, kb, vb, causal=False,
                                              scale=scale))

            def _dead(o, lse, kb, vb):
                return o, lse

            o, lse = jax.lax.cond(step <= idx, _live, _dead,
                                  o, lse, k_blk, v_blk)
        if step < n - 1:
            if not overlap:
                # serial ablation baseline: hop only after the compute
                k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
                v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
            k_blk, v_blk = k_nxt, v_nxt
        # the final rotation (step == n-1) — whose result no shard would
        # consume — is never issued

    return o.astype(q.dtype)


def ring_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    mesh: Mesh | None = None, axis_name: str = AXIS_SEQ,
    batch_axis: str = AXIS_DATA, head_axis: str = AXIS_MODEL,
    overlap: bool = True,
):
    """Exact attention with the seq dim sharded over `axis_name`.

    q,k,v: (batch, heads, seq, head_dim) global arrays (call under jit).
    Falls back to single-shard attention when no mesh / seq axis size 1.
    `overlap=False` disables the double-buffered hop issue (ablation)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        from ..ops.attention import sdpa_xla

        return sdpa_xla(q, k, v, causal=causal, scale=scale)

    from .. import telemetry

    n = mesh.shape[axis_name]
    # trace-time breadcrumb: one event per compiled ring-attention op, so
    # telemetry shows which compiles carry the overlapped schedule (the
    # long-context CI smoke asserts on it)
    telemetry.event("ring.attention", steps=n, overlap=bool(overlap),
                    causal=bool(causal), seq=int(q.shape[2]))

    spec = P(
        batch_axis if mesh.shape.get(batch_axis, 1) > 1 else None,
        head_axis if mesh.shape.get(head_axis, 1) > 1 else None,
        axis_name,
        None,
    )
    fn = shard_map(
        functools.partial(
            _ring_local, axis_name=axis_name, n=n,
            causal=causal, scale=scale, overlap=overlap,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
