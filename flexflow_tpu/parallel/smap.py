"""shard_map compatibility shim.

`jax.shard_map` (with the `check_vma` kwarg) is the current spelling;
older jax (the pinned test container's 0.4.x) only ships
`jax.experimental.shard_map.shard_map` with the same semantics under the
`check_rep` kwarg. Every shard_map user in this package routes through
this wrapper so the ring-attention / pipeline suites run on both — the
per-shard bodies are identical either way.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
