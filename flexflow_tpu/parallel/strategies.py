"""Parallelization strategies: per-node mesh-axis assignments.

The reference expresses a strategy as one `MachineView` per PCG node, found by
Unity search or imported from a file (SURVEY §2.1, §2.3). Here a `Strategy` is
the TPU-native equivalent: a map

    node name → {"outputs": {out_idx: axis_assignment},
                 "weights": {weight_name: PartitionSpec}}

where axis_assignment is a tuple (one entry per tensor dim) of tuples of mesh
axis names. `FFModel.compile` applies it on top of the data-parallel default
(model.cc:get_basic_data_parallel_config analog), and the executor pins every
tensor with `with_sharding_constraint`, so the strategy is exactly what XLA
runs (GSPMD cannot silently re-propagate it away).

The hand-written generators below mirror the reference's substitution
families (substitution.cc:1726-1868):
  - megatron_transformer = create_replicate_linear_combine +
    create_partition_attention_combine applied model-wide (column→row
    parallel Linear pairs, head-parallel attention).
  - sequence_parallel_attention = the seq-dim sharding the reference lacks
    (SURVEY §5 "long-context: absent") — ring attention over the `seq` axis.
Unity search (search/) produces Strategy objects automatically; these
generators are the `--import-strategy` analog and the search's seeds.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from jax.sharding import PartitionSpec

from ..fftype import OperatorType as OT
from ..machine import AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_SEQ


@dataclass
class Strategy:
    """Per-node placement overrides, mergeable; applied at compile."""

    overrides: dict = field(default_factory=dict)

    def node(self, name: str) -> dict:
        return self.overrides.setdefault(name, {"outputs": {}, "weights": {}})

    def set_output(self, name: str, out_idx: int, assignment):
        self.node(name)["outputs"][out_idx] = tuple(tuple(a) for a in assignment)

    def set_weight(self, name: str, weight_name: str, spec: PartitionSpec):
        self.node(name)["weights"][weight_name] = spec

    def merge(self, other: "Strategy") -> "Strategy":
        out = Strategy({k: {"outputs": dict(v["outputs"]),
                            "weights": dict(v["weights"])}
                        for k, v in self.overrides.items()})
        for k, v in other.overrides.items():
            n = out.node(k)
            n["outputs"].update(v["outputs"])
            n["weights"].update(v["weights"])
        return out

    def __bool__(self):
        return bool(self.overrides)

    # -------------------------------------------------- JSON (de)serialization
    # The --export-strategy / --import-strategy file format
    # (model.cc:3599-3608 analog; the reference's protobuf strategy file
    # becomes JSON here). A searched plan can be saved once and replayed
    # without re-searching — the AE two-run pattern re-uses one search.

    def to_json(self) -> dict:
        def spec_entry(e):
            if e is None:
                return None
            if isinstance(e, (tuple, list)):
                return list(e)
            return e

        out = {"version": 1, "nodes": {}}
        for name, ov in self.overrides.items():
            out["nodes"][name] = {
                "outputs": {
                    str(idx): [list(axes) for axes in assignment]
                    for idx, assignment in ov.get("outputs", {}).items()
                },
                "weights": {
                    wname: [spec_entry(spec[i]) for i in range(len(spec))]
                    for wname, spec in ov.get("weights", {}).items()
                },
            }
        return out

    @staticmethod
    def from_json(data: dict) -> "Strategy":
        if data.get("version") != 1:
            raise ValueError(
                f"unsupported strategy file version {data.get('version')!r}")
        s = Strategy()
        for name, ov in data.get("nodes", {}).items():
            for idx, assignment in ov.get("outputs", {}).items():
                s.set_output(name, int(idx),
                             tuple(tuple(a) for a in assignment))
            for wname, entries in ov.get("weights", {}).items():
                s.set_weight(name, wname, PartitionSpec(*[
                    tuple(e) if isinstance(e, list) else e for e in entries
                ]))
        return s

    def validate(self, graph, mesh) -> None:
        """Check this strategy can apply to (graph, mesh); raise ValueError
        listing every problem otherwise.

        `Strategy.load` / `--import-strategy` historically checked only the
        file `version`, so a plan exported from a different model or mesh
        silently degraded to data parallel node by node. Delegates to the
        ffcheck sharding verifier (analysis/sharding.py) — the ONE shared
        gate — so the import path, the warm-start plan cache, and
        checkpoint plan adoption inherit every verifier check, including
        the one this method historically MISSED: the same mesh axis used
        on two different dims of one assignment (an invalid NamedSharding
        that only exploded at device_put time). Checks: unknown node
        names, out-of-range output indices / rank mismatches, unknown
        weight names, mesh axes absent from the mesh, per-assignment axis
        reuse, oversharded dims, and sharded dims not divisible by their
        axes' total degree."""
        from ..analysis import verify_strategy

        verify_strategy(self.overrides, graph, mesh)

    def save(self, path: str):
        import json

        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)

    @staticmethod
    def load(path: str) -> "Strategy":
        import json

        with open(path) as f:
            return Strategy.from_json(json.load(f))


def _act_assignment(ndims: int, batch_axes=(AXIS_DATA,), last_axes=()):
    """Assignment for an activation: batch dim over data, last dim optionally
    over model, middle dims replicated."""
    a = [()] * ndims
    if ndims > 0:
        a[0] = tuple(batch_axes)
    if last_axes and ndims > 1:
        a[-1] = tuple(last_axes)
    return tuple(a)


def megatron_transformer(model, model_axis: str = AXIS_MODEL) -> Strategy:
    """Column→row parallel Linear pairs + head-parallel attention.

    Equivalent PCG rewrite in the reference: Replicate → {partitioned-weight
    Linear/Attention} → Reduction (create_replicate_linear_combine,
    substitution.cc:71-76; create_replicate_attention_reduce:91). Under GSPMD
    the Replicate/Reduction endpoints become implicit: the column-parallel
    weight shards the activation's feature dim, the row-parallel weight's
    contraction over a sharded dim makes XLA insert the psum over ICI.
    """
    s = Strategy()
    layers = getattr(model, "layers", model)
    # map tensor guid -> producing layer, for chain detection
    producer = {}
    for l in layers:
        for t in l.outputs:
            producer[t.tensor_guid] = l

    def upstream(layer):
        t = layer.inputs[0]
        return producer.get(t.tensor_guid)

    paired_row: set[int] = set()   # layer guids already made row-parallel
    paired_col: set[int] = set()

    for l in layers:
        if l.op_type == OT.OP_MULTIHEAD_ATTENTION:
            # QKV column-parallel (heads split over model axis), O row-parallel
            for w in ("wq", "wk", "wv"):
                s.set_weight(l.name, w, PartitionSpec(None, model_axis))
            for b in ("bq", "bk", "bv"):
                s.set_weight(l.name, b, PartitionSpec(model_axis))
            s.set_weight(l.name, "wo", PartitionSpec(model_axis, None))
            s.set_weight(l.name, "bo", PartitionSpec())
            # output fully materialized (psum) with batch sharded
            nd = len(l.outputs[0].dims)
            s.set_output(l.name, 0, _act_assignment(nd))
        elif l.op_type == OT.OP_LINEAR and l.layer_guid not in paired_row:
            # find Linear → [elementwise activation] → Linear chains
            nxt = _linear_consumer(l, layers)
            if nxt is None or nxt.layer_guid in paired_col:
                continue
            # l = column parallel
            s.set_weight(l.name, "kernel", PartitionSpec(None, model_axis))
            if any(ws.name == "bias" for ws in _weight_specs(l)):
                s.set_weight(l.name, "bias", PartitionSpec(model_axis))
            nd = len(l.outputs[0].dims)
            s.set_output(l.name, 0, _act_assignment(nd, last_axes=(model_axis,)))
            paired_col.add(l.layer_guid)
            # activations in between stay sharded on the feature dim
            chain = _chain_between(l, nxt, producer)
            for mid in chain:
                ndm = len(mid.outputs[0].dims)
                s.set_output(mid.name, 0,
                             _act_assignment(ndm, last_axes=(model_axis,)))
            # nxt = row parallel
            s.set_weight(nxt.name, "kernel", PartitionSpec(model_axis, None))
            s.set_weight(nxt.name, "bias", PartitionSpec())
            ndn = len(nxt.outputs[0].dims)
            s.set_output(nxt.name, 0, _act_assignment(ndn))
            paired_row.add(nxt.layer_guid)
        elif l.op_type == OT.OP_EMBEDDING:
            # column-parallel table: shard the embedding dim
            s.set_weight(l.name, "kernel", PartitionSpec(None, model_axis))
    return s


def _weight_specs(layer):
    from ..ops.base import get_op_def

    in_shapes = [t.dims for t in layer.inputs]
    return get_op_def(layer.op_type).weights(layer.params, in_shapes)


_ELEMENTWISE_CHAIN_OPS = frozenset(
    {
        OT.OP_RELU, OT.OP_GELU, OT.OP_SIGMOID, OT.OP_TANH, OT.OP_ELU,
        OT.OP_IDENTITY, OT.OP_DROPOUT, OT.OP_SCALAR_MULTIPLY,
        OT.OP_SCALAR_ADD, OT.OP_SCALAR_SUB, OT.OP_SCALAR_TRUE_DIV,
    }
)


def _linear_consumer(layer, layers):
    """Return the Linear fed (possibly through elementwise ops) by `layer`."""
    out_guids = {t.tensor_guid for t in layer.outputs}
    for l in layers:
        if not l.inputs:
            continue
        if l.inputs[0].tensor_guid in out_guids:
            if l.op_type == OT.OP_LINEAR:
                return l
            if l.op_type in _ELEMENTWISE_CHAIN_OPS:
                return _linear_consumer(l, layers)
    return None


def _chain_between(src, dst, producer):
    """Elementwise layers strictly between src and dst (walk back from dst)."""
    chain = []
    cur = producer.get(dst.inputs[0].tensor_guid)
    while cur is not None and cur.layer_guid != src.layer_guid:
        chain.append(cur)
        if not cur.inputs:
            break
        cur = producer.get(cur.inputs[0].tensor_guid)
    return chain


def sequence_parallel_attention(model, seq_axis: str = AXIS_SEQ) -> Strategy:
    """Shard the sequence dim of 3D activations over `seq_axis`.

    The attention op must use impl="ring" (ring attention over ICI,
    parallel/ring_attention.py) — set via FFModel.multihead_attention(impl=
    "ring") — so KV blocks rotate through the ring while queries stay
    resident. This is the long-context capability the reference lacks
    (SURVEY §5).

    Tensors whose seq dim does not divide by the configured seq-axis
    degree are left alone (they would fail Strategy.validate / GSPMD
    lowering); with no mesh information on `model` every 3D output is
    sharded, matching the historical behavior."""
    seq_deg = 0
    cfg = getattr(model, "config", None)
    if cfg is not None:
        try:
            ms = cfg.mesh_shape()
            seq_deg = dict(zip(ms.axis_names, ms.axis_sizes)).get(seq_axis, 0)
        except Exception:
            seq_deg = 0
    s = Strategy()
    layers = getattr(model, "layers", model)
    for l in layers:
        for i, t in enumerate(l.outputs):
            if len(t.dims) == 3:
                if seq_deg > 1 and int(t.dims[1]) % seq_deg != 0:
                    continue  # indivisible seq dim: keep the default
                # (batch, seq, hidden): batch over data, seq over seq axis
                s.set_output(l.name, i, ((AXIS_DATA,), (seq_axis,), ()))
    return s


def expert_parallel_moe(model, expert_axis: str = AXIS_MODEL) -> Strategy:
    """Shard the stacked-experts weight dim of Experts ops over the expert
    axis (reference analog: attribute-parallel machine views over the MoE
    expert ops, examples/cpp/mixture_of_experts).

    Defaults to the `model` mesh axis (AXIS_EXPERT is an alias used when the
    mesh names an axis "expert" explicitly — it is not in DEFAULT_AXES)."""
    s = Strategy()
    layers = getattr(model, "layers", model)
    for l in layers:
        if l.op_type == OT.OP_EXPERTS:
            for ws in _weight_specs(l):
                nd = len(ws.shape)
                s.set_weight(
                    l.name, ws.name,
                    PartitionSpec(expert_axis, *([None] * (nd - 1))),
                )
    return s
