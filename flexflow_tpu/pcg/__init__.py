from .graph import Edge, Graph, OpNode
