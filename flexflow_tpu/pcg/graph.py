"""Parallel Computation Graph (PCG).

Reference: include/flexflow/graph.h — `Graph` of `Node{guid, Op*}` with
multi-edges carrying (srcOp, dstOp, srcIdx, dstIdx); the IR on which both
Unity search (substitutions + DP) and compile-time op reconstruction operate.
Compute ops and parallelization ops are both first-class nodes.

This module is pure data + graph algorithms (topo order, hashing, transitive
reduction); execution is in executor.py, search in search/.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Optional

from ..fftype import OperatorType, PARALLEL_OP_TYPES
from ..machine import MachineView
from ..ops.base import OpDef, WeightSpec, get_op_def
from ..tensor import ParallelTensor, ParallelTensorShape

_node_guid = itertools.count(5000000)  # NODE_GUID_FIRST_VALID


@dataclass(frozen=True)
class Edge:
    """src node guid, dst node guid, src output idx, dst input idx."""

    src: int
    dst: int
    src_idx: int = 0
    dst_idx: int = 0


class OpNode:
    """One PCG node: operator instance with parallel tensors attached."""

    def __init__(
        self,
        op_type: OperatorType,
        params: Any,
        name: str = "",
        layer_guid: int = -1,
        initializers: Optional[dict] = None,
    ):
        self.guid = next(_node_guid)
        self.op_type = op_type
        self.params = params
        self.name = name or f"{op_type.name.lower()}_{self.guid}"
        self.layer_guid = layer_guid
        self.initializers = initializers or {}
        self.inputs: list[ParallelTensor] = []
        self.outputs: list[ParallelTensor] = []
        self.weight_specs: list[WeightSpec] = []
        self.machine_view: Optional[MachineView] = None
        # weight name → PartitionSpec (placement of the parameter itself);
        # default replicated — the reference's weight regions mapped by
        # map_weight (model.cc)
        self.weight_axes: dict[str, Any] = {}

    @property
    def op_def(self) -> OpDef:
        return get_op_def(self.op_type)

    @property
    def is_parallel_op(self) -> bool:
        return self.op_type in PARALLEL_OP_TYPES

    def __repr__(self):
        return f"OpNode({self.name})"


class Graph:
    """PCG: nodes + explicit edges. Node identity is the guid."""

    def __init__(self):
        self.nodes: dict[int, OpNode] = {}
        self.in_edges: dict[int, list[Edge]] = {}
        self.out_edges: dict[int, list[Edge]] = {}

    def add_node(self, node: OpNode) -> OpNode:
        self.nodes[node.guid] = node
        self.in_edges.setdefault(node.guid, [])
        self.out_edges.setdefault(node.guid, [])
        return node

    def add_edge(self, src: OpNode, dst: OpNode, src_idx: int = 0, dst_idx: int = 0):
        e = Edge(src.guid, dst.guid, src_idx, dst_idx)
        self.in_edges[dst.guid].append(e)
        self.out_edges[src.guid].append(e)

    def remove_node(self, node: OpNode):
        for e in list(self.in_edges.get(node.guid, [])):
            self.out_edges[e.src].remove(e)
        for e in list(self.out_edges.get(node.guid, [])):
            self.in_edges[e.dst].remove(e)
        self.in_edges.pop(node.guid, None)
        self.out_edges.pop(node.guid, None)
        self.nodes.pop(node.guid, None)

    def sources(self) -> list[OpNode]:
        return [n for g, n in self.nodes.items() if not self.in_edges[g]]

    def sinks(self) -> list[OpNode]:
        return [n for g, n in self.nodes.items() if not self.out_edges[g]]

    def topo_order(self) -> list[OpNode]:
        indeg = {g: len(es) for g, es in self.in_edges.items()}
        # deterministic: process in guid order among ready nodes
        ready = sorted(g for g, d in indeg.items() if d == 0)
        order = []
        while ready:
            g = ready.pop(0)
            order.append(self.nodes[g])
            for e in self.out_edges[g]:
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    # insert keeping ready sorted
                    import bisect

                    bisect.insort(ready, e.dst)
        if len(order) != len(self.nodes):
            raise ValueError("graph has a cycle")
        return order

    def producer(self, node: OpNode, dst_idx: int) -> tuple[OpNode, int]:
        for e in self.in_edges[node.guid]:
            if e.dst_idx == dst_idx:
                return self.nodes[e.src], e.src_idx
        raise KeyError(f"{node} has no producer for input {dst_idx}")

    def hash(self) -> int:
        """Structural hash for search dedup (reference Graph::hash)."""
        h = 0
        node_hash = {}
        for n in self.topo_order():
            nh = hash((n.op_type, repr(n.params)))
            for e in sorted(
                self.in_edges[n.guid], key=lambda e: (e.dst_idx, e.src_idx)
            ):
                nh = nh * 31 + node_hash[e.src] * 7 + e.src_idx + e.dst_idx * 131
                nh &= 0xFFFFFFFFFFFFFFFF
            node_hash[n.guid] = nh
            h = (h * 17 + nh) & 0xFFFFFFFFFFFFFFFF
        return h

    def __len__(self):
        return len(self.nodes)


def find_bottlenecks(graph: "Graph", order=None) -> list:
    """Nodes every source→sink path crosses (the sequence-split points,
    graph.cc find_bottleneck_node). Uses the native C++ core when available;
    pure-Python open-edges scan otherwise. Shared by the Unity placement
    DP's segmenter and the joint search's sequence splitter."""
    order = order if order is not None else graph.topo_order()
    from .. import native

    if native.available():
        idx = {n.guid: i for i, n in enumerate(order)}
        src, dst = [], []
        for edges in graph.out_edges.values():
            for e in edges:
                src.append(idx[e.src])
                dst.append(idx[e.dst])
        mask = native.bottlenecks(len(order), src, dst)
        if mask is not None:
            return [n for i, n in enumerate(order) if mask[i]]
    out = []
    open_edges = 0
    for i, n in enumerate(order):
        open_edges -= len(graph.in_edges[n.guid])
        if open_edges == 0 and i < len(order) - 1:
            out.append(n)
        open_edges += len(graph.out_edges[n.guid])
    return out


def is_expert_buffer(node: OpNode) -> bool:
    """Expert-capacity buffers (outputs of group_by and expert branches) have
    no batch dim; the data-parallel fallback must not shard their dim 0.
    Shared by the default strategy assignment (model._assign_strategy) and
    the substitution path (search.substitution.assign_axes_from_degrees)."""
    return node.op_type in (OperatorType.OP_GROUP_BY,)


def export_dot(graph: "Graph", path: str | None = None) -> str:
    """DOT export of the PCG with placements (reference print_dot /
    export_strategy_computation_graph_file, utils/dot/*)."""
    lines = ["digraph PCG {", '  rankdir="TB";']
    for n in graph.topo_order():
        spec = n.outputs[0].partition_spec() if n.outputs else ""
        shape = n.outputs[0].shape if n.outputs else ""
        color = "lightblue" if n.is_parallel_op else (
            "gray90" if n.op_type.name in ("OP_INPUT", "OP_NOOP")
            else "white")
        lines.append(
            f'  n{n.guid} [label="{n.name}\\n{n.op_type.name}\\n'
            f'{shape}\\n{spec}", style=filled, fillcolor={color}];'
        )
    for guid, edges in graph.out_edges.items():
        for e in edges:
            lines.append(f"  n{e.src} -> n{e.dst};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
