"""Per-operator profiling: the --profiling flag's output.

Reference: every kernel wrapper prints per-op forward/backward times under
`m->profiling` (src/ops/kernels/linear_kernels.cu:95-117, enabled by
--profiling → FFConfig.profiling). The TPU recast times each PCG op's
jitted forward and backward standalone on the local device (the same
harness the cost-model calibration uses) and prints one reference-style
table per compile.

Caveat printed with the table: inside the real training step XLA fuses
across op boundaries, so the end-to-end step is FASTER than the sum of
these standalone kernels — the table is for finding hot ops, exactly what
the reference's per-kernel prints are for. (For whole-step timelines, wrap
training in jax.profiler.trace and load the dump in TensorBoard/XProf.)
"""

from __future__ import annotations


def profile_operators(graph) -> list[tuple[str, str, float, float]]:
    """Measure every compute op of a PCG standalone. Returns
    [(op name, op type, forward seconds, backward seconds), ...] in topo
    order; ops whose harness can't run (e.g. exotic input generation) are
    skipped, like the reference skips kernels without profiling hooks."""
    from .search.cost_model import CostModel, _NON_COMPUTE, _op_harness
    from .search.machine_model import detect_chip, TPUMachineModel

    cm = CostModel(TPUMachineModel(detect_chip(), {}))
    rows = []
    for node in graph.topo_order():
        if (node.op_type in _NON_COMPUTE or not node.outputs
                or not node.inputs):
            continue
        try:
            fn, args = _op_harness(node)
            fwd_t, bwd_t = cm.calibrate(node, fn, args)
        except Exception:
            continue
        rows.append((node.name, node.op_type.name, fwd_t, bwd_t))
    return rows


def profile_operators_json(graph, rows=None) -> list[dict]:
    """Machine-readable per-op profile: one dict per op with forward/
    backward/total seconds, sorted by total descending (hot ops first —
    the question the table exists to answer). Pass pre-measured `rows`
    (profile_operators output) to avoid re-benchmarking."""
    rows = profile_operators(graph) if rows is None else rows
    out = [
        {
            "name": name,
            "op_type": op_type,
            "forward_s": fwd,
            "backward_s": bwd,
            "total_s": fwd + bwd,
        }
        for name, op_type, fwd, bwd in rows
    ]
    out.sort(key=lambda r: r["total_s"], reverse=True)
    return out


def profile_section_from_rows(rows) -> dict:
    """Shape standalone per-op measurements into the SAME report
    `profile` section schema ffscope's xplane attribution produces
    (scope/attribution.py), so --profiling numbers land in
    strategy_report.json / the ffpulse registry / the doctor's one
    measured-vs-predicted table instead of a parallel one-off format.
    `source: "standalone"` marks that these are unfused kernels timed
    in isolation — the attribution identity (bounded by step device
    time) applies only to `source: "xplane"` sections."""
    from .scope.attribution import build_profile_section

    ops = {name: {"measured_s": fwd + bwd, "fwd_s": fwd, "bwd_s": bwd,
                  "events": 1}
           for name, _op_type, fwd, bwd in rows}
    attr = {"ops": ops, "extras": {},
            "attributed_s": sum(o["measured_s"] for o in ops.values()),
            "unattributed_s": 0.0, "parallelism": 1, "devices": 1}
    return build_profile_section(
        attr, step=-1, device_time_s=attr["attributed_s"],
        source="standalone")


def print_operator_profile(graph, file=None, sort_by_total=False):
    """Reference-format per-op table (linear_kernels.cu:95-117 prints
    '%s [Linear] forward time = %.2lfms'; this is the whole-graph sweep).
    `sort_by_total=True` orders hot ops first instead of topo order.

    Each row is also emitted as a tracer counter event ("op_profile.<name>")
    when a telemetry session is active, so the per-op table lands in the
    same Perfetto file as the run timeline."""
    import sys

    from . import telemetry

    out = file or sys.stdout
    rows = profile_operators(graph)
    if sort_by_total:
        rows = sorted(rows, key=lambda r: r[2] + r[3], reverse=True)
    print("per-operator profile (standalone kernels; the fused training "
          "step overlaps/fuses across ops):", file=out)
    for name, op_type, fwd, bwd in rows:
        print(f"{name} [{op_type}] forward time = {fwd * 1e3:.4f}ms, "
              f"backward time = {bwd * 1e3:.4f}ms", file=out)
        telemetry.counter(f"op_profile.{name}", {
            "forward_ms": fwd * 1e3, "backward_ms": bwd * 1e3})
        # ffpulse: the same op_time_s{op=...} series the ffscope
        # attribution feeds — one registry for both profile sources
        telemetry.observe("op_time_s", fwd + bwd, op=name)
    total_f = sum(r[2] for r in rows)
    total_b = sum(r[3] for r in rows)
    print(f"TOTAL (sum of standalone kernels) forward = "
          f"{total_f * 1e3:.4f}ms, backward = {total_b * 1e3:.4f}ms",
          file=out)
    return rows
