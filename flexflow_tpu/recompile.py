"""RecompileState: dynamic re-optimization hooks.

Reference: include/flexflow/recompile.h:26-41 + recompile_state.cc:22-40 —
a (trigger, alter) callback pair checked each iteration so a model can be
rewritten mid-training (the MoE expert-scaling experiment, moe.cc:180-204).
Here `alter` may change the FFModel's strategy or layer params; FFModel then
recompiles the jitted step, which on TPU is just a new jit trace.
"""

from __future__ import annotations

from typing import Callable


class RecompileState:
    def __init__(self, trigger_func: Callable[..., bool],
                 alter_func: Callable[..., None], ffmodel):
        self.trigger_func = trigger_func
        self.alter_func = alter_func
        self.ffmodel = ffmodel
        self.recompilations = 0

    def trigger(self) -> bool:
        return bool(self.trigger_func(self.ffmodel))

    def alter(self):
        self.alter_func(self.ffmodel)
        # invalidate the compiled step so the next fit() retraces
        ex = self.ffmodel.executor
        if ex is not None:
            ex._train_step = None
            ex._eval_step = None
            ex._forward_fn = None
            ex._chunk_steps.clear()
        self.recompilations += 1
