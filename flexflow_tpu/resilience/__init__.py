"""Resilience subsystem: async sharded checkpointing, cross-mesh elastic
resume, and preemption-safe training.

The reference has no checkpointing at all (SURVEY §5: weights move only via
get/set_tensor). This package makes the framework survive real pods:

- `checkpointer`: copy-on-snapshot to host + background writer thread +
  atomic commit (tmp-dir → fsync → rename → manifest), so saving never
  blocks the step loop and a killed save never corrupts the latest-good
  checkpoint (CheckFreq, FAST'21).
- `reshard`: restore a checkpoint saved under one searched Strategy/mesh
  onto a *different* mesh — every leaf is re-placed via `device_put` with
  the new compile's NamedSharding (reshard-aware recovery, Gemini SOSP'23).
- `policy`: CheckpointPolicy (every-N-steps / every-T-seconds / on-signal)
  and the SIGTERM PreemptionHandler that drains the in-flight save and
  writes a final snapshot.
- `fault`: deterministic kill-after-step-K injection for tests.
- `manager`: ResilienceManager gluing the above into FFModel.fit, plus the
  `auto_resume` entry point.
- `migrate`: in-process live-state migration between two compiled plans
  (`migrate_state`) — the fftrans apply path (analysis/transition.py):
  the transition is statically verified and priced before any leaf
  moves, and no checkpoint-restart round trip is paid.

Every restore and migration is gated by the fftrans transition verifier
(`reshard.verify_restore_transition` / `analysis.transition`): an
incompatible mapping raises PlanVerificationError naming the leaf and
finding class instead of shape-crashing mid-restore.
"""

from .checkpointer import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
)
from .fault import FaultInjector, SimulatedPreemption
from .manager import ResilienceManager, auto_resume
from .migrate import migrate_state
from .policy import CheckpointPolicy, PreemptionHandler
from .reshard import restore_model, restore_tree, verify_restore_transition

__all__ = [
    "AsyncCheckpointer",
    "CheckpointCorruptError",
    "CheckpointPolicy",
    "FaultInjector",
    "PreemptionHandler",
    "ResilienceManager",
    "SimulatedPreemption",
    "auto_resume",
    "latest_checkpoint",
    "list_checkpoints",
    "load_checkpoint",
    "migrate_state",
    "restore_model",
    "restore_tree",
    "verify_restore_transition",
]
