"""Async atomic checkpoint writer.

Layout under a checkpoint root directory:

    <root>/
      step_00000012/            # one committed checkpoint
        arrays.npz              # flat keystr path -> host array bytes
        manifest.json           # {"committed": true, "step": ..., "leaves":
                                #  {path: {dtype, shape}}, "extras": {...}}
      .tmp-step_00000024-<pid>/ # in-flight write, never read by restore
      LATEST                    # convenience pointer (informational)

Commit protocol (CheckFreq-style decoupled persistence):

1. the train loop snapshots device state to host (`jax.device_get` — a copy,
   so donated/overwritten device buffers can't corrupt it) and hands the
   host tree to a background writer thread;
2. the writer serializes everything into a `.tmp-*` directory, fsyncs the
   files and the directory;
3. multi-host: every process reaches a barrier, then **host 0 alone**
   renames the tmp dir to its final `step_*` name (`os.replace` — atomic on
   POSIX) and rewrites LATEST. The rename is the commit point: a kill at
   any earlier moment leaves only a `.tmp-*` dir that discovery ignores.

`manifest.json` is written *last* inside the tmp dir, so even a torn rename
implementation (non-POSIX filesystems) cannot surface a half-written
checkpoint: discovery requires a parseable manifest with "committed": true.

npz preserves raw bytes but degrades non-native dtypes (bfloat16) to void;
the manifest records each leaf's true dtype and restore re-views the bytes.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from .. import telemetry

_STEP_DIR = re.compile(r"^step_(\d{8,})$")  # %08d grows past 8 digits ≥1e8
_TMP_PREFIX = ".tmp-"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed integrity checks on load."""


def _step_dirname(step: int) -> str:
    return f"step_{int(step):08d}"


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - fsync of dirs unsupported somewhere
        pass


def flatten_tree(tree) -> dict[str, Any]:
    """Flatten a pytree into {keystr path: leaf}. The keystr form (e.g.
    "['params']['fc1']['kernel']") is the stable on-disk naming — restore
    matches against the target model's identically-flattened template, so
    resharding never needs to parse paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def to_host(leaf) -> np.ndarray:
    """Fetch one (possibly sharded) array fully to host as a detached numpy
    copy. Multi-process: non-addressable shards are gathered over the fleet
    (every process ends up with the full logical array)."""
    if jax.process_count() > 1 and hasattr(leaf, "sharding"):
        from jax.experimental import multihost_utils

        leaf = multihost_utils.process_allgather(leaf, tiled=True)
    return np.array(jax.device_get(leaf))


def snapshot_to_host(tree) -> dict[str, np.ndarray]:
    """Copy-on-snapshot: the device→host copy happens here, synchronously,
    so the step loop may donate/overwrite the device buffers immediately
    after; serialization cost stays on the writer thread. Single-process,
    the whole tree goes through ONE batched `jax.device_get` (per-leaf
    fetches pay per-call dispatch on every shard); multi-process falls
    back to the per-leaf gather path."""
    flat = flatten_tree(tree)
    if jax.process_count() > 1:
        return {k: to_host(v) for k, v in flat.items()}
    fetched = jax.device_get(flat)
    # device_get returns fresh host copies for jax Arrays but passes
    # through pre-existing numpy leaves by reference — detach those
    return {
        k: v if v is not flat[k] else np.array(v)
        for k, v in fetched.items()
    }


def _encode_leaves(flat: dict[str, np.ndarray]):
    """npz-safe arrays + true-dtype manifest entries."""
    arrays, leaves = {}, {}
    for i, (path, arr) in enumerate(sorted(flat.items())):
        arr = np.asarray(arr)
        key = f"a{i}"
        leaves[path] = {
            "key": key,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
        arrays[key] = arr
    return arrays, leaves


def _decode_leaf(raw: np.ndarray, meta: dict) -> np.ndarray:
    dtype = np.dtype(meta["dtype"])  # ml_dtypes registers bf16 by name
    shape = tuple(meta["shape"])
    if raw.dtype == dtype:
        return raw.reshape(shape)
    # npz degraded a non-native dtype to void bytes: re-view
    return np.frombuffer(raw.tobytes(), dtype=dtype).reshape(shape)


def list_checkpoints(root: str) -> list[str]:
    """Committed checkpoint paths under `root`, oldest first. A step dir
    only counts when its manifest parses and says committed."""
    if not os.path.isdir(root):
        return []
    found = []
    for name in os.listdir(root):
        m = _STEP_DIR.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
            if manifest.get("committed"):
                found.append((int(m.group(1)), path))
        except (OSError, ValueError):
            continue
    return [p for _, p in sorted(found)]


def latest_checkpoint(root: str) -> Optional[str]:
    """Newest committed checkpoint under `root`, or None."""
    ckpts = list_checkpoints(root)
    return ckpts[-1] if ckpts else None


def load_checkpoint(path: str) -> tuple[dict[str, np.ndarray], dict]:
    """Read one committed checkpoint dir → (flat {path: host array},
    manifest). Raises CheckpointCorruptError on integrity failures."""
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e}")
    if not manifest.get("committed"):
        raise CheckpointCorruptError(f"{path}: manifest not committed")
    try:
        with np.load(os.path.join(path, "arrays.npz")) as z:
            flat = {
                p: _decode_leaf(z[meta["key"]], meta)
                for p, meta in manifest["leaves"].items()
            }
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable arrays: {e}")
    return flat, manifest


class AsyncCheckpointer:
    """Background checkpoint writer with atomic commit.

    At most one save is in flight; a new save first drains the previous one
    (bounded memory: one host snapshot alive at a time). `wait()` re-raises
    any writer-thread failure — a silent failed save must not masquerade as
    durability."""

    def __init__(self, root: str, keep: int = 3,
                 barrier_fn: Optional[Callable[[str], None]] = None,
                 is_committer: Optional[Callable[[], bool]] = None):
        self.root = os.path.abspath(root)
        self.keep = int(keep)
        from ..distributed import barrier, is_coordinator

        self._barrier = barrier_fn or barrier
        self._is_committer = is_committer or is_coordinator
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._aborted = threading.Event()
        self.last_committed: Optional[str] = None
        # test hook: called between serialization and commit (fault point)
        self._pre_commit_hook: Optional[Callable[[str], None]] = None
        # telemetry: blocking-snapshot latency of the save in flight, and
        # the previous commit's wall time (checkpoint staleness — the data
        # loss window a kill right now would open)
        self._snapshot_s = 0.0
        self._last_commit_t: Optional[float] = None

    # ------------------------------------------------------------ save

    def save(self, step: int, tree, extras: Optional[dict] = None,
             blocking: bool = False) -> None:
        """Snapshot `tree` (device state) and persist it as step `step`.
        The device→host copy is synchronous (and, multi-process, a
        fleet-wide gather — every process must call save at the same
        step); the write + commit happen on a background thread unless
        `blocking`. Multi-process saves are forced blocking: the commit
        barrier is a collective, and issuing it from a writer thread while
        the main thread runs train-step collectives would interleave
        collectives in different orders across hosts (deadlock)."""
        self.wait()  # drain previous save; raises its error if any
        t_snap0 = time.perf_counter()
        with telemetry.span("ckpt.snapshot", step=int(step)):
            flat = snapshot_to_host(tree)
        self._snapshot_s = time.perf_counter() - t_snap0
        extras = dict(extras or {})
        if blocking or jax.process_count() > 1:
            self._write(step, flat, extras)
            return
        self._thread = threading.Thread(
            target=self._write_guarded, args=(step, flat, extras),
            name=f"ckpt-writer-{step}", daemon=True)
        self._thread.start()

    def _write_guarded(self, step, flat, extras):
        try:
            self._write(step, flat, extras)
        except BaseException as e:  # surfaced by wait()
            self._error = e

    def _write(self, step: int, flat: dict[str, np.ndarray], extras: dict):
        final = os.path.join(self.root, _step_dirname(step))
        # only the committer serializes: every process holds the identical
        # full logical arrays (the snapshot gathered them), so N-1 extra
        # copies on a shared filesystem would be pure wasted bandwidth —
        # the other processes just join the commit barriers.
        # A serialization failure (ENOSPC...) must NOT raise before the
        # barriers: the other hosts are already waiting in the collective
        # and would hang the pod — record it, join the barriers, skip the
        # commit, raise after.
        tmp = None
        error: Optional[BaseException] = None
        t_ser0 = time.perf_counter()
        if self._is_committer():
            try:
                with telemetry.span("ckpt.serialize", step=int(step)):
                    os.makedirs(self.root, exist_ok=True)
                    tmp = os.path.join(
                        self.root,
                        f"{_TMP_PREFIX}{_step_dirname(step)}-{os.getpid()}")
                    if os.path.exists(tmp):
                        shutil.rmtree(tmp)
                    os.makedirs(tmp)
                    arrays, leaves = _encode_leaves(flat)
                    arrays_path = os.path.join(tmp, "arrays.npz")
                    with open(arrays_path, "wb") as f:
                        np.savez(f, **arrays)
                        f.flush()
                        os.fsync(f.fileno())
                    manifest = {
                        "committed": True,
                        "step": int(step),
                        "leaves": leaves,
                        "extras": extras,
                        "format_version": 1,
                    }
                    # manifest last: its presence marks a complete
                    # serialization
                    man_path = os.path.join(tmp, "manifest.json")
                    with open(man_path, "w") as f:
                        json.dump(manifest, f)
                        f.flush()
                        os.fsync(f.fileno())
                    _fsync_dir(tmp)
                if self._pre_commit_hook is not None:
                    self._pre_commit_hook(tmp)
            except BaseException as e:
                error = e
        serialize_s = time.perf_counter() - t_ser0
        # serialization done before any process may treat the checkpoint
        # as durable; host 0 alone renames (concurrent renames on a shared
        # filesystem must not collide)
        t_commit0 = time.perf_counter()
        with telemetry.span("ckpt.commit", step=int(step)):
            self._barrier("ckpt-precommit")
            skip = error is not None or self._aborted.is_set()
            if self._is_committer() and not skip:
                displaced = None
                if os.path.exists(final):
                    # re-saving an existing step: move the old committed dir
                    # aside with an atomic rename FIRST — an rmtree+rename
                    # pair would open a window where a kill leaves no
                    # committed checkpoint at this step at all. .old-* names
                    # never match discovery, so a crash mid-swap still shows
                    # exactly one committed state.
                    displaced = os.path.join(
                        self.root,
                        f".old-{_step_dirname(step)}-{os.getpid()}")
                    if os.path.exists(displaced):
                        shutil.rmtree(displaced)
                    os.replace(final, displaced)
                os.replace(tmp, final)  # THE commit point
                _fsync_dir(self.root)
                if displaced is not None:
                    shutil.rmtree(displaced, ignore_errors=True)
                self._write_latest(final)
                self._prune()
            elif skip and tmp is not None:
                # failed or aborted (simulated death): never commit; leave
                # no half-written state behind
                shutil.rmtree(tmp, ignore_errors=True)
            self._barrier("ckpt-postcommit")
        if error is not None:
            raise error
        if not skip:
            self.last_committed = final
            now = time.monotonic()
            staleness = (now - self._last_commit_t
                         if self._last_commit_t is not None else 0.0)
            self._last_commit_t = now
            if telemetry.active_session() is not None:
                # guarded: the bytes sum walks every state leaf — wasted
                # work on the (default) telemetry-off path
                commit_s = time.perf_counter() - t_commit0
                telemetry.inc("checkpoints_total")
                telemetry.observe("checkpoint_commit_s", commit_s)
                telemetry.event(
                    "checkpoint", step=int(step),
                    snapshot_s=self._snapshot_s, serialize_s=serialize_s,
                    commit_s=commit_s,
                    bytes=int(sum(np.asarray(v).nbytes
                                  for v in flat.values())),
                    staleness_s=staleness)

    def _write_latest(self, final: str):
        tmp = os.path.join(self.root, ".LATEST.tmp")
        with open(tmp, "w") as f:
            f.write(os.path.basename(final))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, "LATEST"))

    def _prune(self):
        if self.keep <= 0:
            return
        ckpts = list_checkpoints(self.root)
        for path in ckpts[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------ drain

    def wait(self):
        """Join the in-flight save (if any); re-raise its failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def abort(self):
        """Discard the in-flight save as if the process had died: the
        writer must not commit after a (simulated) kill. An already-
        committed write stays committed — exactly like a real kill landing
        a moment later. The checkpointer is reusable afterwards."""
        self._aborted.set()
        try:
            t, self._thread = self._thread, None
            if t is not None:
                t.join()
            self._error = None
        finally:
            self._aborted.clear()

    def close(self):
        self.wait()
