"""Deterministic failure injection for resilience tests.

A FaultInjector installed via `FFModel.set_fault_hook` is called after
every optimizer step with the global step number; at step K it raises
SimulatedPreemption — the mid-run death the test suite uses to prove
kill → auto-resume (onto a different mesh) → identical final metrics.
"""

from __future__ import annotations


class SimulatedPreemption(RuntimeError):
    """Raised by FaultInjector to simulate the process dying mid-fit."""

    def __init__(self, step: int):
        super().__init__(f"simulated preemption after step {step}")
        self.step = step


class FaultInjector:
    """kill_after_step=K → raise on the K-th completed optimizer step.
    `fired` records whether the fault triggered (a test that configured a
    kill which never fired is itself broken)."""

    def __init__(self, kill_after_step: int):
        if kill_after_step <= 0:
            raise ValueError("kill_after_step must be positive")
        self.kill_after_step = int(kill_after_step)
        self.fired = False

    def __call__(self, step: int):
        if step >= self.kill_after_step and not self.fired:
            self.fired = True
            raise SimulatedPreemption(step)
