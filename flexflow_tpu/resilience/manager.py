"""ResilienceManager: glue between FFModel.fit and the checkpoint stack.

Owns one AsyncCheckpointer + CheckpointPolicy for a compiled model, knows
how to snapshot the model's full training state (reshard.model_state_tree)
with the fit loop's cursor, and restores the newest committed checkpoint
(`auto_resume`) before training.
"""

from __future__ import annotations

from typing import Optional

from .checkpointer import AsyncCheckpointer, latest_checkpoint
from .policy import CheckpointPolicy
from .reshard import model_state_tree, restore_model


class ResilienceManager:
    def __init__(self, ffmodel, directory: str,
                 policy: Optional[CheckpointPolicy] = None, keep: int = 3):
        self.ffmodel = ffmodel
        self.directory = directory
        self.policy = policy or CheckpointPolicy()
        self.checkpointer = AsyncCheckpointer(directory, keep=keep)

    @classmethod
    def from_config(cls, ffmodel) -> Optional["ResilienceManager"]:
        """Build from FFConfig's --checkpoint-* flags; None when
        checkpointing is not configured."""
        cfg = ffmodel.config
        if not cfg.checkpoint_dir:
            return None
        policy = CheckpointPolicy(
            every_n_steps=cfg.checkpoint_every,
            every_t_seconds=cfg.checkpoint_every_seconds,
        )
        return cls(ffmodel, cfg.checkpoint_dir, policy,
                   keep=cfg.checkpoint_keep)

    # ------------------------------------------------------------ saving

    def _extras(self, step: int, cursor: Optional[dict]) -> dict:
        mesh = self.ffmodel.mesh
        extras = {
            # cursor epochs are ABSOLUTE (epochs completed since compile):
            # model.fit maps them back onto its within-call loop index and
            # keys the deterministic shuffle order on them
            "cursor": dict(cursor or {}),
            "py_step": int(step),
            "mesh_axes": {k: int(v) for k, v in mesh.shape.items()}
            if mesh is not None else {},
        }
        upd = getattr(self.ffmodel, "_update_sharding", None)
        if upd is not None:
            # how the saving run ran its weight update (ZeRO-sharded vs
            # replicated, shard count/axes): informational for elastic
            # resume — checkpoints always hold FULL logical arrays (the
            # snapshot gathers shards), so a resume re-places them under
            # the RESTORING compile's update mode bit-exactly in either
            # direction and across dp degrees
            extras["update_sharding"] = {
                "enabled": bool(upd.get("enabled")),
                # the running ZeRO stage (0 replicated | 2 sharded
                # optimizer | 3 params sharded at rest): elastic resume
                # re-places the full logical arrays under the RESTORING
                # compile's stage, so toggles across saves are safe —
                # the record is for post-mortems and audits
                "stage": int(upd.get("stage", 0)),
                "shards": int(upd.get("shards", 1)),
                "axes": list(upd.get("axes", [])),
            }
        plan = getattr(self.ffmodel, "_plan_record", None)
        if plan:
            # the applied parallelization plan + structural fingerprint:
            # --auto-resume restores the plan from this manifest at
            # compile (warmstart/), so recovery skips the search — the
            # Gemini (SOSP'23) point that RECOVERY time, not checkpoint
            # time, bounds effective goodput
            extras["plan"] = plan
        return extras

    def maybe_save(self, step: int, cursor: Optional[dict] = None) -> bool:
        """Policy-gated async save after optimizer step `step`."""
        if not self.policy.should_save(step):
            return False
        self.save(step, cursor, blocking=False)
        return True

    def save(self, step: int, cursor: Optional[dict] = None,
             blocking: bool = False):
        self.checkpointer.save(
            step, model_state_tree(self.ffmodel),
            extras=self._extras(step, cursor), blocking=blocking)
        self.policy.notify_saved()

    def last_commit_walltime(self) -> Optional[float]:
        """Wall-clock time of the newest committed checkpoint, or None
        before the first commit. The checkpointer stamps commits on the
        monotonic clock; the diagnostics staleness rule runs on wall
        time — this is the ONE conversion point (eager fit loop and the
        pipelined engine both feed `note_checkpoint_commit` from here)."""
        import time

        lc = self.checkpointer._last_commit_t
        if lc is None:
            return None
        return time.time() - (time.monotonic() - lc)

    def finalize(self, step: Optional[int] = None,
                 cursor: Optional[dict] = None, final_save: bool = False):
        """Drain the in-flight async save; optionally write one last
        synchronous snapshot (the preemption path)."""
        self.checkpointer.wait()
        if final_save and step is not None:
            self.save(step, cursor, blocking=True)

    # ------------------------------------------------------------ restore

    def peek_latest(self) -> Optional[tuple]:
        """(path, extras) of the newest committed checkpoint WITHOUT
        restoring it — fit uses this to judge cursor staleness before
        rewinding any live state. None when no committed checkpoint
        exists."""
        import json
        import os

        path = latest_checkpoint(self.directory)
        if path is None:
            return None
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        return path, dict(manifest.get("extras") or {})

    def restore_path(self, path: str) -> dict:
        """Restore one committed checkpoint dir (resharding onto this
        model's mesh/Strategy); returns its extras."""
        import time

        from .. import telemetry

        t0 = time.perf_counter()
        with telemetry.span("ckpt.restore", path=path):
            extras = restore_model(self.ffmodel, path)
        telemetry.event("restore", path=path,
                        duration_s=time.perf_counter() - t0)
        # the fftrans gate stashed the verified TransitionPlan on the
        # model — land it in strategy_report.json (the compile-time
        # report predates the restore) so run_doctor sees the
        # transition section on elastic-resume runs too
        from .migrate import _rewrite_report

        _rewrite_report(self.ffmodel)
        return extras

    def restore_latest(self) -> Optional[dict]:
        """Restore the newest committed checkpoint (resharding onto this
        model's mesh/Strategy). Returns the saved extras (cursor...) or
        None when no committed checkpoint exists."""
        path = latest_checkpoint(self.directory)
        if path is None:
            return None
        return restore_model(self.ffmodel, path)


def auto_resume(ffmodel, directory: Optional[str] = None) -> Optional[dict]:
    """Discover the newest committed checkpoint under `directory` (default:
    the model's --checkpoint-dir) and restore it into the compiled model.
    Returns the saved extras dict, or None when starting fresh."""
    directory = directory or ffmodel.config.checkpoint_dir
    if not directory:
        return None
    path = latest_checkpoint(directory)
    if path is None:
        return None
    return restore_model(ffmodel, path)
