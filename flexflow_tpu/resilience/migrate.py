"""In-process live-state migration between two compiled plans.

The apply half of fftrans (analysis/transition.py): `migrate_state(old,
new)` moves a compiled FFModel's FULL training state — params, fp32
masters, optimizer slots, step/counters, RNG, serving KV state — onto a
second compiled model of the same logical PCG whose Strategy, mesh
factorization, and/or ZeRO update stage differ, WITHOUT a
checkpoint-restart round trip (Gemini, SOSP '23: recovery time, not
checkpoint time, bounds effective goodput — the same argument applies to
re-planning). The transition is first built and VERIFIED statically
(gate_transition — state-mapping completeness, dtype/shape preservation,
gather paths, transition-time memory, ring bijectivity, schedule
uniformity); only a verified plan touches live state, and
--no-verify-plan downgrades to warnings exactly like the compile gate.

Each transfer is one `jax.device_put` of the live (possibly sharded)
array onto the destination leaf's NamedSharding — XLA owns lowering that
to the gather/slice program the TransitionPlan derived statically; a
put the backend cannot express cross-mesh falls back to the host hop
the plan priced. Values are moved bit-exactly (dtype changes are
verification ERRORS, never silent casts), so a migrated run's
trajectory is bit-identical to a checkpoint-restart of the same state —
the acceptance property tests/test_transition.py and
scripts/migrate_smoke.py pin.

The executed plan (with measured seconds next to the prediction — the
fidelity datapoint the future re-planner's pay-off rule needs) lands on
`new._transition`, and strategy_report.json gains a `transition` section
whose predicted_s reproduces from the JSON alone
(transition.verify_transition_total)."""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.tree_util as jtu
import numpy as np


def _move_leaf(arr, template_leaf):
    """Move one live array onto the destination leaf's placement.
    In-process first (device_put reshards on-device); host hop as the
    fallback when the backend refuses the cross-mesh put. No dtype
    casts — the verifier already guaranteed dtype equality."""
    sharding = getattr(template_leaf, "sharding", None)
    if sharding is None:
        return jax.numpy.asarray(arr)
    try:
        return jax.device_put(arr, sharding)
    except (ValueError, TypeError):
        # one-off fallback per leaf, not a hot loop
        host = np.asarray(jax.device_get(arr))  # fflint: ok host_sync_in_loop
        return jax.device_put(host, sharding)


def migrate_state(old, new, *, plan=None, donate: bool = False) -> dict:
    """Migrate `old`'s live training state onto `new` in-process.

    Both models must be compiled over the same logical PCG (same layer
    names/shapes); Strategy, mesh factorization, and update stage may
    all differ. Builds + verifies the TransitionPlan (raises
    PlanVerificationError naming the leaf and finding class on an
    unverifiable mapping unless --no-verify-plan), executes it, and
    returns the plan JSON with `measured_s` filled in. `donate=True`
    additionally deletes each source buffer once its transfer lands —
    the donation schedule the transition_memory pass accounts for.
    """
    from .. import telemetry

    assert getattr(old, "_compiled", False), "compile() old before migrating"
    assert getattr(new, "_compiled", False), "compile() new before migrating"

    # the destination model's telemetry session becomes the sink for the
    # migration's spans/events, exactly as compile/fit scope theirs
    session = getattr(new, "_telemetry", None)
    if session is not None:
        telemetry.activate(session)
    try:
        return _migrate_impl(old, new, plan=plan, donate=donate)
    finally:
        if session is not None:
            telemetry.deactivate(session)


def _migrate_impl(old, new, *, plan, donate: bool) -> dict:
    from .. import telemetry
    from ..analysis import transition as fftrans
    from .reshard import model_state_tree

    if plan is None:
        plan = fftrans.plan_model_transition(old, new)
    with telemetry.span("migrate.verify"):
        result = fftrans.gate_transition(plan, new.config,
                                         label="migrate_state")
    plan_json = plan.to_json(analysis=result)

    src_flat = {
        jtu.keystr(path): leaf
        for path, leaf in jtu.tree_flatten_with_path(
            model_state_tree(old))[0]}
    template = model_state_tree(new)
    flat_t, treedef = jtu.tree_flatten_with_path(template)

    t0 = time.perf_counter()
    moved = []
    leaves = []
    with telemetry.span("migrate.apply"):
        for path, tleaf in flat_t:
            key = jtu.keystr(path)
            src = src_flat.get(key)
            if src is None:
                # only reachable under --no-verify-plan (unmapped_state
                # was downgraded): keep the new model's fresh leaf
                leaves.append(tleaf)
                continue
            out = _move_leaf(src, tleaf)
            moved.append(out)
            leaves.append(out)
            if donate and hasattr(src, "delete") and out is not src:
                src.delete()
        restored = jtu.tree_unflatten(treedef, leaves)
        for leaf in moved:
            # one drain at the end of the migration — the measurement IS
            # the migration wall time, not a hot loop
            jax.block_until_ready(leaf)
    measured_s = time.perf_counter() - t0

    new._params = restored["params"]
    new._state = restored["state"] if restored["state"] else new._state
    new._opt_slots = restored["opt_slots"]
    new._step = restored["step"]
    new._counters = restored["counters"]
    new._rng = jax.random.wrap_key_data(
        jax.device_get(restored["rng"]).astype(np.uint32))
    if donate:
        old._compiled = False  # the old model's state buffers are dead

    plan_json["measured_s"] = measured_s
    if plan.predicted_s > 0 and measured_s > 0:
        # fidelity datapoint for the elastic payoff rule: fold this
        # migration's measured/predicted ratio into the per-device-kind
        # calibration entry (elastic/payoff.py — persisted via the
        # warm-start DB so it survives restarts)
        from ..elastic.payoff import record_fidelity

        record_fidelity(new, measured_s / plan.predicted_s)
    new._transition = plan_json
    telemetry.inc("migrations_total")
    telemetry.observe("migration_s", measured_s)
    telemetry.event(
        "migrate", predicted_s=plan.predicted_s, measured_s=measured_s,
        transfers=len(plan.transfers),
        bytes_on_wire=sum(plan.bytes_on_wire.values()),
        errors=len(result.errors()))
    _rewrite_report(new)
    return plan_json


def _rewrite_report(model) -> Optional[dict]:
    """Re-write strategy_report.json after a migration so the
    `transition` section lands next to the compile-time attribution
    (the diagnostics manager wrote the report before the migration
    existed). No-op without a telemetry session."""
    session = getattr(model, "_telemetry", None)
    if session is None:
        return None
    from ..diagnostics.explain import write_strategy_report

    try:
        return write_strategy_report(model, session.directory)
    except Exception:  # pragma: no cover - report must not fail a migrate
        return None
