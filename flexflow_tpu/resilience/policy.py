"""When to checkpoint, and how to die gracefully.

CheckpointPolicy decides *when* a snapshot is taken (every N steps, every T
seconds, or both — whichever fires first). PreemptionHandler turns SIGTERM
(the cloud preemption notice on TPU spot/preemptible VMs) into a flag the
fit loop polls between steps: on notice, the loop drains the in-flight
async save, writes one final snapshot, and returns — the CheckFreq
decoupling means the final save is the only synchronous one.
"""

from __future__ import annotations

import signal
import threading
import time
from dataclasses import dataclass


@dataclass
class CheckpointPolicy:
    """every_n_steps=0 and every_t_seconds=0 → only explicit/final saves."""

    every_n_steps: int = 0
    every_t_seconds: float = 0.0

    def __post_init__(self):
        self._last_save_time = time.monotonic()
        if self.every_t_seconds > 0:
            import jax

            if jax.process_count() > 1:
                # wall-clock triggers read each host's own clock: skew
                # would make hosts decide to save at different steps, and
                # the snapshot gather is a fleet-wide collective — a
                # divergent decision hangs the pod. Only the step-count
                # trigger is deterministic across hosts.
                import warnings

                warnings.warn(
                    "every_t_seconds is not multi-host safe (clock skew "
                    "diverges the save decision across processes); "
                    "disabled — use every_n_steps", stacklevel=2)
                self.every_t_seconds = 0.0

    def should_save(self, step: int) -> bool:
        if self.every_n_steps > 0 and step % self.every_n_steps == 0:
            return True
        if (self.every_t_seconds > 0
                and time.monotonic() - self._last_save_time
                >= self.every_t_seconds):
            return True
        return False

    def should_save_range(self, start_step: int, end_step: int) -> bool:
        """True when ANY step in (start_step, end_step] triggers the
        policy — the pipelined engine's chunk-boundary form: a chunk that
        ran steps 5..8 with every_n_steps=4 must still save, even though
        the boundary step 8's modulus is the only one it could test."""
        if end_step <= start_step:
            return False
        if (self.every_n_steps > 0
                and end_step // self.every_n_steps
                > start_step // self.every_n_steps):
            return True
        if (self.every_t_seconds > 0
                and time.monotonic() - self._last_save_time
                >= self.every_t_seconds):
            return True
        return False

    def notify_saved(self):
        self._last_save_time = time.monotonic()


class PreemptionHandler:
    """Context manager installing a SIGTERM (and optionally SIGINT) handler
    that records the preemption instead of killing the process mid-save.
    The previous handler is chained on exit; installation is skipped off the
    main thread (signal module restriction) — `preempted` then only reflects
    `request()` calls (the test hook)."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._previous: dict = {}

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def request(self):
        """Programmatic preemption notice (tests / external schedulers)."""
        self._flag.set()

    def _handle(self, signum, frame):
        self._flag.set()

    def __enter__(self):
        for s in self.signals:
            try:
                self._previous[s] = signal.signal(s, self._handle)
            except ValueError:  # not on the main thread
                pass
        return self

    def __exit__(self, *exc):
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:  # pragma: no cover - defensive
                pass
        self._previous.clear()
        return False
