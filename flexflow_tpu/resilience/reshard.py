"""Cross-mesh elastic resume: re-place checkpointed leaves under a new
mesh/Strategy.

Checkpoints store each leaf as its full *logical* array on host (the
snapshot gathers shards), which makes them mesh-independent by
construction: restoring onto a different searched Strategy is a
`device_put` of the logical array with the *target* compile's
NamedSharding — GSPMD then owns slicing it onto the new mesh (e.g. save
under dp=8, resume under dp=4×tp=2). This is the reshard-aware recovery
path of Gemini (SOSP'23) recast onto JAX shardings.

The same contract carries elastic resume across weight-update-sharding
stages (off ↔ stage 2 ↔ stage 3 / ZeRO-3): a stage-3 compile's param
templates carry the at-rest `update_specs` NamedSharding, so
`place_like` re-places the full logical array 1/shards-sharded — and a
replicated compile restoring a stage-3 run's checkpoint re-places the
same logical values replicated. No stage-specific code here, by
design; the manifest's `extras.update_sharding.stage` records how the
WRITER ran (tests: kill→resume across stage toggles in
tests/test_weight_update.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .checkpointer import CheckpointCorruptError, load_checkpoint


def verify_restore_transition(ffmodel, flat: dict, manifest: dict,
                              label: str = "checkpoint"):
    """The fftrans verify-before-apply gate (analysis/transition.py):
    build the checkpoint→model TransitionPlan from the manifest + flat
    arrays + the restoring compile's materialized placements, verify it
    (state-mapping completeness, dtype/shape preservation, gather paths,
    transition-time memory, schedule uniformity), and refuse an
    unverifiable mapping with a PlanVerificationError NAMING the leaf
    and finding class — instead of the shape crash or silent dtype
    drift mid-restore it used to be. --no-verify-plan downgrades to
    warnings (the strict restore_tree checks below remain the
    backstop). The verified plan lands on `ffmodel._transition` so the
    strategy report of the restoring run carries the `transition`
    section."""
    from ..analysis import transition as fftrans
    from ..search.machine_model import machine_model_for_mesh

    machine = machine_model_for_mesh(
        ffmodel.mesh, num_hosts=ffmodel.config.num_nodes)
    cap = (ffmodel.config.device_mem if ffmodel.config.device_mem > 0
           else machine.chip.hbm_bytes)
    plan = fftrans.build_transition_plan(
        fftrans.PlanSide.from_checkpoint(flat, manifest, label=label),
        fftrans.PlanSide.from_model(ffmodel, label="restoring-model"),
        machine=machine, hbm_cap_bytes=cap)
    result = fftrans.gate_transition(plan, ffmodel.config, label=label)
    ffmodel._transition = plan.to_json(analysis=result)
    return plan, result


def place_like(host_arr: np.ndarray, template_leaf):
    """Place one host array like `template_leaf`: same dtype, and the
    template's NamedSharding when it has one (the cross-mesh re-placement).
    The host numpy array goes straight into device_put — materializing the
    full logical array on one device first would OOM exactly the models
    that are sharded because they don't fit on one device."""
    dtype = getattr(template_leaf, "dtype", None)
    sharding = getattr(template_leaf, "sharding", None)
    if sharding is not None:
        arr = np.asarray(host_arr)
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        return jax.device_put(arr, sharding)
    return jnp.asarray(host_arr, dtype)


def restore_tree(template, flat_arrays: dict[str, np.ndarray], prefix: str = "",
                 label: str = "checkpoint"):
    """Rebuild `template`'s pytree from saved flat arrays, re-placing every
    leaf with the template leaf's sharding. Path mismatches raise — a
    silently dropped leaf (the old `_state or {}` failure mode) would train
    from stale values with no sign anything was lost."""
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    missing = []
    leaves = []
    for path, leaf in flat_t:
        key = prefix + jax.tree_util.keystr(path)
        if key not in flat_arrays:
            missing.append(key)
            continue
        saved = flat_arrays[key]
        want = tuple(getattr(leaf, "shape", np.shape(leaf)))
        if tuple(saved.shape) != want:
            raise CheckpointCorruptError(
                f"{label}: leaf {key} has shape {tuple(saved.shape)} but the "
                f"compiled model expects {want} — architecture mismatch")
        # the fftrans gate runs one level up (restore_model calls
        # verify_restore_transition before any leaf is re-placed)
        leaves.append(place_like(saved, leaf))  # fflint: ok unverified_transition
    if missing:
        raise CheckpointCorruptError(
            f"{label}: {len(missing)} leaves absent from checkpoint "
            f"(architecture mismatch?): {missing[:5]}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


_SECTIONS = ("params", "state", "opt_slots", "step", "counters")


def model_state_tree(ffmodel) -> dict:
    """The full training state persisted per checkpoint. `state` may be
    None/{} (no stateful ops) — normalized to {} so save/restore treat both
    spellings identically."""
    return {
        "params": ffmodel._params,
        "state": ffmodel._state if ffmodel._state is not None else {},
        "opt_slots": ffmodel._opt_slots,
        "step": ffmodel._step,
        "counters": ffmodel._counters,
        "rng": jax.random.key_data(ffmodel._rng),
    }


def restore_model(ffmodel, path: str) -> dict:
    """Restore a committed checkpoint dir into a *compiled* FFModel whose
    mesh/Strategy may differ from the saving run's. Returns the manifest's
    extras dict (train-loop cursor, wallclock, saving mesh...)."""
    assert ffmodel._compiled, "compile() before restoring a checkpoint"
    flat, manifest = load_checkpoint(path)

    # fftrans verify-before-apply: cross-mesh / update-stage-toggle
    # restores are plan transitions — statically verify the mapping
    # BEFORE any leaf is re-placed (PlanVerificationError names the leaf
    # and finding class; --no-verify-plan downgrades to warnings and the
    # strict checks below stay as the backstop)
    verify_restore_transition(ffmodel, flat, manifest, label=path)

    saved_state_keys = [k for k in flat if k.startswith("['state']")]
    template = model_state_tree(ffmodel)
    if not template["state"] and saved_state_keys:
        # the checkpoint carries op state this compile has no home for —
        # the exact case checkpoint.py's `_state or {}` used to drop
        raise CheckpointCorruptError(
            f"{path}: checkpoint has op state {saved_state_keys[:3]} but the "
            "compiled model has none — architecture mismatch")

    restored = restore_tree(template, flat, label=path)
    ffmodel._params = restored["params"]
    ffmodel._state = restored["state"] if restored["state"] else ffmodel._state
    ffmodel._opt_slots = restored["opt_slots"]
    ffmodel._step = restored["step"]
    ffmodel._counters = restored["counters"]
    ffmodel._rng = jax.random.wrap_key_data(
        jax.device_get(restored["rng"]).astype(np.uint32))
    return dict(manifest.get("extras") or {})
