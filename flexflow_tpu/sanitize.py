"""Runtime NaN-provenance sanitizer (`--sanitize-numerics`, ffsan's
runtime half).

The `nan_loss` health rule can only say "the run is dead"; this module
says WHICH op killed it. With the flag on, the executor wraps every op
output in a probe pair:

  - a `jax.debug.callback` on the forward value's finiteness, and
  - a `custom_vjp` identity whose backward runs the same callback on the
    output's cotangent

so the instrumented step reports, per step, every (op, fwd|bwd) whose
tensor went non-finite — the callbacks carry the traced `step` value, so
localization works inside the pipelined engine's `lax.scan` chunks
exactly as in the eager loop. The host side keeps only NON-finite
reports (the callback payload is two scalars; a healthy run crosses the
host boundary with nothing).

Localization semantics (`NumericsMonitor.first_nonfinite`):

  fwd — the FIRST op in topo order whose output is non-finite at the
        earliest affected step (NaN propagates downstream; the minimum
        is the origin).
  bwd — the op with the LARGEST topo index whose output cotangent is
        non-finite (the backward pass runs in reverse topo order, so
        cotangent NaN propagates toward smaller indices; the maximum is
        where the gradient first went bad).

Zero-cost when off: the executor inserts no probes, so the traced step
is byte-identical to the uninstrumented one. With the flag on the probes
are value-preserving identities — outputs stay bit-identical; only
effects are added.

`inject_nonfinite` / the grad twin are the matching fault injectors
(tests and scripts/ffsan_smoke.py poison exactly one op at one step and
assert the monitor names it).
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


class NumericsMonitor:
    """Host-side collector of non-finite reports. One per process
    (module singleton via get_monitor()); callbacks may fire from XLA's
    callback threads, hence the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: list[dict] = []

    def reset(self):
        with self._lock:
            self.events = []

    def report(self, op: str, phase: str, topo: int, step: int):
        with self._lock:
            self.events.append(
                {"op": op, "phase": phase, "topo": int(topo),
                 "step": int(step)})

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self.events)

    def localize(self, step=None) -> dict | None:
        """The origin (op, phase, step) record — at `step` when given,
        else at the earliest affected step. None when nothing non-finite
        was ever reported. Call jax.effects_barrier() first when the
        step that produced the NaN may still be in flight. Step-less
        reports (eval/forward/decode dispatches record step -1) only
        win when NO stepped event exists — an interleaved eval NaN must
        not outrank the training-step origin the nan_loss alert is
        attributing."""
        events = self.snapshot()
        if step is not None:
            events = [e for e in events if e["step"] == int(step)]
        stepped = [e for e in events if e["step"] >= 0]
        events = stepped or events
        if not events:
            return None
        s0 = min(e["step"] for e in events)
        at = [e for e in events if e["step"] == s0]
        fwd = [e for e in at if e["phase"] == "fwd"]
        if fwd:
            return min(fwd, key=lambda e: e["topo"])
        return max(at, key=lambda e: e["topo"])

    def first_nonfinite(self) -> dict | None:
        return self.localize()


_MONITOR = NumericsMonitor()


def get_monitor() -> NumericsMonitor:
    return _MONITOR


# ---------------------------------------------------------------- probes


def _report_cb(op: str, phase: str, topo: int, finite, step):
    # host side of the probe: drop finite reports on the floor
    if not bool(finite):
        _MONITOR.report(op, phase, topo, int(np.asarray(step)))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _grad_probe(x, step, op, topo):
    return x


def _grad_probe_fwd(x, step, op, topo):
    return x, step


def _grad_probe_bwd(op, topo, step, g):
    jax.debug.callback(partial(_report_cb, op, "bwd", topo),
                       jnp.isfinite(g).all(), step)
    # step is an integer primal: its cotangent type is float0
    return g, np.zeros((), dtype=jax.dtypes.float0)


_grad_probe.defvjp(_grad_probe_fwd, _grad_probe_bwd)


def _step_val(step):
    # eval/forward/decode paths carry no step counter: report as -1
    return jnp.int32(-1) if step is None else step


def probe(x, step, op: str, topo: int):
    """Instrument one op output: finiteness callback on the forward
    value, custom_vjp twin on its cotangent. Identity on the value."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return x
    s = _step_val(step)
    jax.debug.callback(partial(_report_cb, op, "fwd", topo),
                       jnp.isfinite(x).all(), s)
    return _grad_probe(x, s, op, topo)


# ------------------------------------------------------- fault injection


def inject_nonfinite(x, step, at_step: int):
    """Forward fault injector: the tensor becomes NaN from `at_step` on
    (always, when no step counter is threaded — eval/decode paths)."""
    if step is None:
        return jnp.full_like(x, jnp.nan)
    return jnp.where(step >= jnp.int32(at_step),
                     jnp.asarray(jnp.nan, x.dtype), x)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def inject_grad_nonfinite(x, step, at_step: int):
    """Backward fault injector: identity forward; the output's cotangent
    is multiplied into NaN from `at_step` on."""
    return x


def _inject_grad_fwd(x, step, at_step):
    return x, _step_val(step)


def _inject_grad_bwd(at_step, step, g):
    bad = jnp.where(step >= jnp.int32(at_step),
                    jnp.asarray(jnp.nan, g.dtype),
                    jnp.asarray(1, g.dtype))
    return g * bad, np.zeros((), dtype=jax.dtypes.float0)


inject_grad_nonfinite.defvjp(_inject_grad_fwd, _inject_grad_bwd)
