"""ffscope — op-grain profiling, always-on flight recorder, hang watchdog.

The op-grain runtime half of the observability stack.  Where ffpulse
(telemetry/metrics.py) answers *how is the run doing* at step grain,
ffscope answers *where did the time go and what happened last*:

1. **Op-grain profiling** (profile.py, attribution.py, xplane.py) — a
   sampled capture (``--profile-every K`` / ``model.profile_step()``)
   wraps one step in ``jax.profiler`` tracing and maps measured device
   time back to PCG nodes via the ``jax.named_scope(node.name)`` labels
   the executor emits, producing per-op ``measured_s`` / fidelity next
   to the strategy report's ``predicted_s`` — the attribution layer
   Daydream (Zhu et al., USENIX ATC '20; see PAPERS.md) argues is what
   makes a cost-model-driven system debuggable, here feeding op-grain
   drift advisories so recalibration refreshes only the drifted ops.
2. **Flight recorder** (flightrec.py) — an always-on bounded ring of
   the last N telemetry events, dumped atomically as ``flight.json``
   on crash, SIGTERM, or watchdog firing.
3. **Hang watchdog** (watchdog.py) — a named daemon thread that
   detects a stuck step, names the lagging host from a file-channel
   heartbeat (never collectives), and optionally aborts.

Import discipline: this package must stay importable without jax —
the flight recorder hooks live inside ``telemetry`` dispatchers that
run in every process; jax is imported lazily where tracing starts.
"""

from . import flightrec  # noqa: F401  (stdlib-only; safe eagerly)

__all__ = ["flightrec", "attribution", "profile", "watchdog", "xplane"]


def __getattr__(name):
    if name in __all__:
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
