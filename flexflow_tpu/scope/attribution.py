"""Attribute profiled device time back to PCG ops.

The executor wraps every op's forward dispatch in
``jax.named_scope(node.name)`` (plus the ``grad_sync`` /
``param_gather`` / ``weight_update_shard`` / ``weight_update`` runtime
scopes), so each HLO instruction's ``OpMetadata.op_name`` carries a
path like ``jit(train_step)/.../dense1/dot_general`` — or, for the
backward pass, a path containing ``transpose(...)`` wrappers.  This
module joins the two halves of an xplane capture:

  * per-instruction device durations (``/host:CPU`` or device planes),
  * per-instruction named-scope paths (``hlo_scope_map``),

into the report's ``profile`` section: per-op ``measured_s`` next to
the plan's ``predicted_s``, fidelity ratios, and the attribution
identity the doctor re-verifies from the JSON alone:

    attributed_s + unattributed_s == device_time_s * parallelism

within a stated ``slop`` — where ``parallelism`` is the number of
distinct trace lines that carried attributed events (a multi-threaded
CPU backend or a multi-device mesh legitimately stacks more than one
second of op time into one wall second).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from . import xplane

__all__ = [
    "attribute_trace",
    "build_profile_section",
    "annotate_with_predictions",
    "verify_profile_section",
    "RUNTIME_LABELS",
]

# Runtime scopes the executor emits that are not PCG node names.  They
# are attributed into the section's ``extras`` map instead of ``ops``.
RUNTIME_LABELS = ("grad_sync", "param_gather", "weight_update_shard",
                  "weight_update", "metrics")

# Identity slop: trace rounding is picosecond-exact but the step window
# is measured with a host clock around dispatch; keep a generous but
# stated tolerance so the identity is meaningful yet robust.
DEFAULT_SLOP = 0.25


def _match_label(path: str, op_names: Set[str]) -> Tuple[str, bool]:
    """Map a named-scope path to (label, is_backward).

    Walks path components from the end so the innermost matching scope
    wins (an op nested under ``grad_sync`` attributes to the op).  The
    backward pass shows up as ``transpose(...)`` wrappers in the path.
    """
    is_bwd = "transpose(" in path
    # a component may be wrapped by tracer transforms — jit(f),
    # jvp(dense1), transpose(jvp(dense1)) — so the label is the
    # innermost piece: split on "(" and strip the closing parens
    parts = [comp.split("(")[-1].rstrip(")")
             for comp in path.split("/")]
    for comp in reversed(parts):
        if comp in op_names:
            return comp, is_bwd
    for comp in reversed(parts):
        if comp in RUNTIME_LABELS:
            return comp, is_bwd
    return "", is_bwd


def attribute_trace(trace_dir: str, op_names: Iterable[str],
                    ) -> Dict[str, Any]:
    """Parse every xplane file under ``trace_dir`` and attribute device
    time to ``op_names`` + runtime labels.

    Returns ``{"ops": {name: {"measured_s", "fwd_s", "bwd_s",
    "events"}}, "extras": {label: seconds}, "attributed_s",
    "unattributed_s", "trace_device_s", "parallelism", "devices"}``.
    """
    names = set(op_names)
    ops: Dict[str, Dict[str, float]] = {}
    extras: Dict[str, float] = {}
    attributed = 0.0
    unattributed = 0.0
    lines_with_events: Set[Tuple[str, int]] = set()
    device_planes = 0

    for path in xplane.find_xplane_files(trace_dir):
        space = xplane.parse_xspace(path)
        scope_maps = xplane.hlo_scope_map(space)
        for plane in space["planes"]:
            pname = plane.get("name", "")
            if "metadata" in pname or pname == "Task Environment":
                continue
            device_planes += 1
            stat_names = plane.get("stat_metadata", {})
            for line in plane.get("lines", []):
                line_key = (pname, line.get("id", 0))
                for ev in line.get("events", []):
                    md = plane["event_metadata"].get(
                        ev["metadata_id"], {})
                    instr = md.get("name", "")
                    stats = {}
                    for st in ev.get("stats", []):
                        key = stat_names.get(
                            st.get("ref", st.get("metadata_id")))
                        if key:
                            stats[key] = st.get("value")
                    pid = stats.get("program_id")
                    dur_s = ev.get("duration_ps", 0) * 1e-12
                    scope = None
                    if pid is not None and pid in scope_maps:
                        scope = scope_maps[pid].get(instr)
                    elif len(scope_maps) == 1:
                        scope = next(iter(scope_maps.values())).get(instr)
                    if scope is None:
                        # not an HLO-instruction event (runtime noise)
                        continue
                    lines_with_events.add(line_key)
                    label, is_bwd = _match_label(scope, names)
                    if not label:
                        unattributed += dur_s
                        continue
                    attributed += dur_s
                    if label in names:
                        rec = ops.setdefault(label, {
                            "measured_s": 0.0, "fwd_s": 0.0,
                            "bwd_s": 0.0, "events": 0})
                        rec["measured_s"] += dur_s
                        rec["bwd_s" if is_bwd else "fwd_s"] += dur_s
                        rec["events"] += 1
                    else:
                        extras[label] = extras.get(label, 0.0) + dur_s

    return {
        "ops": ops,
        "extras": extras,
        "attributed_s": attributed,
        "unattributed_s": unattributed,
        "parallelism": max(1, len(lines_with_events)),
        "devices": max(1, device_planes),
    }


def build_profile_section(attr: Dict[str, Any], *, step: int,
                          device_time_s: float,
                          source: str = "xplane",
                          all_op_names: Optional[Iterable[str]] = None,
                          slop: float = DEFAULT_SLOP) -> Dict[str, Any]:
    """Shape an :func:`attribute_trace` result (or standalone profiler
    numbers in the same layout) into the report ``profile`` section.

    Every name in ``all_op_names`` gets a row even when no event was
    attributed to it (``measured_s == 0.0`` — e.g. fused away), so
    downstream gates can rely on a measured column for every report op.
    """
    rows: List[Dict[str, Any]] = []
    seen = set()
    for name, rec in sorted(attr["ops"].items()):
        rows.append({"name": name,
                     "measured_s": rec["measured_s"],
                     "fwd_s": rec.get("fwd_s", 0.0),
                     "bwd_s": rec.get("bwd_s", 0.0),
                     "events": rec.get("events", 0)})
        seen.add(name)
    for name in (all_op_names or ()):
        if name not in seen:
            rows.append({"name": name, "measured_s": 0.0, "fwd_s": 0.0,
                         "bwd_s": 0.0, "events": 0})
            seen.add(name)
    return {
        "source": source,
        "step": step,
        "device_time_s": device_time_s,
        "devices": attr.get("devices", 1),
        "parallelism": attr.get("parallelism", 1),
        "slop": slop,
        "attributed_s": attr.get("attributed_s", 0.0),
        "unattributed_s": attr.get("unattributed_s", 0.0),
        "ops": rows,
        "extras": dict(attr.get("extras", {})),
    }


def annotate_with_predictions(section: Dict[str, Any],
                              report: Dict[str, Any]) -> Dict[str, Any]:
    """Attach per-op ``predicted_s`` and ``fidelity`` from a strategy
    report's ``ops`` table.  ``fidelity = measured_s / predicted_s`` —
    recomputable from the JSON alone, which is what run_doctor checks.
    """
    predicted = {o["name"]: float(o.get("compute_s", 0.0))
                 + float(o.get("comm_s", 0.0))
                 for o in report.get("ops", [])}
    for row in section.get("ops", []):
        p = predicted.get(row["name"])
        if p is None:
            continue
        row["predicted_s"] = p
        row["fidelity"] = (row["measured_s"] / p) if p > 0 else None
    return section


def verify_profile_section(section: Dict[str, Any]) -> List[str]:
    """Re-verify the attribution identity from the JSON alone.

    Returns a list of problem strings (empty == green).  Shared by
    ``run_doctor --check`` and the tests.
    """
    problems: List[str] = []
    ops = section.get("ops", [])
    attributed = sum(float(o.get("measured_s", 0.0)) for o in ops)
    attributed += sum(float(v) for v in
                      section.get("extras", {}).values())
    stated = (float(section.get("attributed_s", 0.0)))
    tol = 1e-9 + 1e-6 * abs(stated)
    if abs(attributed - stated) > tol:
        problems.append(
            "profile: sum of per-op measured_s %.9f != stated "
            "attributed_s %.9f" % (attributed, stated))
    budget = (float(section.get("device_time_s", 0.0))
              * float(section.get("parallelism", 1))
              * (1.0 + float(section.get("slop", DEFAULT_SLOP))))
    total = attributed + float(section.get("unattributed_s", 0.0))
    if section.get("source") == "xplane" and total > budget + 1e-9:
        problems.append(
            "profile: attributed+unattributed %.6fs exceeds device "
            "budget %.6fs (device_time_s x parallelism x (1+slop))"
            % (total, budget))
    for o in ops:
        p = o.get("predicted_s")
        f = o.get("fidelity")
        if p and f is not None:
            want = float(o.get("measured_s", 0.0)) / float(p)
            if abs(want - float(f)) > 1e-9 + 1e-6 * abs(want):
                problems.append(
                    "profile: op %s fidelity %.9f not recomputable "
                    "(measured/predicted = %.9f)"
                    % (o.get("name"), float(f), want))
    return problems
