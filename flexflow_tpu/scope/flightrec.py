"""Always-on bounded flight recorder for post-mortem diagnosis.

A fixed-capacity in-memory ring of the last N telemetry events (spans,
instants, counters, step boundaries).  Recording follows the same
one-global-read no-op discipline as ``telemetry.span``: when disabled
(``FF_FLIGHT_RECORDER=0``) every hook is a single global load plus an
``is None`` test.  When enabled, a record is index assignments into
preallocated mutable slots — no objects are allocated per event in the
steady state (the zero-alloc guard test pins slot identity), so the
recorder is safe to leave on in production step loops.

On crash (executor exception, ``HealthAbort``, ``SPMDDivergenceError``),
SIGTERM/preemption, or watchdog firing, :func:`dump` writes the ring
atomically as ``flight.json`` next to the run's telemetry artifacts —
the "what were the last 256 things this process did" artifact a hung
multihost collective otherwise never leaves behind.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "get_recorder", "configure", "record",
           "note_step", "dump", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 256

# Slot layout (mutated in place, never reallocated):
#   [seq, t_monotonic, kind, name, value]
_SEQ, _T, _KIND, _NAME, _VALUE = range(5)


class FlightRecorder:
    """Bounded ring of telemetry events with atomic JSON dump."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(8, int(capacity))
        # Preallocated slots; record() only index-assigns into them.
        self._ring: List[List[Any]] = [
            [0, 0.0, "", "", None] for _ in range(self.capacity)]
        self._seq = 0
        self.last_step = -1
        self.last_step_t = 0.0

    # ------------------------------------------------------------ hot

    def record(self, kind: str, name: str, value: Any = None) -> None:
        # Index assignment only — no allocation in the steady state.
        self._seq += 1
        s = self._seq
        slot = self._ring[s % self.capacity]
        slot[_SEQ] = s
        slot[_T] = time.monotonic()
        slot[_KIND] = kind
        slot[_NAME] = name
        slot[_VALUE] = value

    def note_step(self, step: int) -> None:
        self.last_step = step
        self.last_step_t = time.monotonic()
        self.record("step", "step", step)

    # ----------------------------------------------------------- cold

    def snapshot(self) -> List[Dict[str, Any]]:
        """Ordered copy of the ring's live events (oldest first).

        A slot whose seq doesn't match its expected position is torn
        (written concurrently) or never written; both are skipped.
        """
        out: List[Dict[str, Any]] = []
        hi = self._seq
        lo = max(1, hi - self.capacity + 1)
        for s in range(lo, hi + 1):
            slot = self._ring[s % self.capacity]
            if slot[_SEQ] != s:
                continue
            val = slot[_VALUE]
            if val is not None and not isinstance(
                    val, (int, float, str, bool)):
                val = repr(val)
            out.append({"seq": s, "t": slot[_T], "kind": slot[_KIND],
                        "name": slot[_NAME], "value": val})
        return out

    def dump(self, directory: str, reason: str,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Atomically write ``flight.json`` into ``directory``."""
        os.makedirs(directory, exist_ok=True)
        doc: Dict[str, Any] = {
            "kind": "flight_record",
            "reason": reason,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "time_unix": time.time(),
            "capacity": self.capacity,
            "total_recorded": self._seq,
            "last_step": self.last_step,
            "events": self.snapshot(),
        }
        if extra:
            doc.update(extra)
        path = os.path.join(directory, "flight.json")
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path


# -------------------------------------------------- module-global plane

def _default_recorder() -> Optional[FlightRecorder]:
    if os.environ.get("FF_FLIGHT_RECORDER", "1").lower() in (
            "0", "off", "false", "no"):
        return None
    try:
        cap = int(os.environ.get("FF_FLIGHT_EVENTS", DEFAULT_CAPACITY))
    except ValueError:
        cap = DEFAULT_CAPACITY
    return FlightRecorder(cap)


_recorder: Optional[FlightRecorder] = _default_recorder()


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def configure(capacity: Optional[int] = None,
              enabled: bool = True) -> Optional[FlightRecorder]:
    """(Re)configure the global recorder; used by --flight-events."""
    global _recorder
    if not enabled:
        _recorder = None
    elif capacity is not None and (
            _recorder is None or _recorder.capacity != int(capacity)):
        _recorder = FlightRecorder(int(capacity))
    elif _recorder is None:
        _recorder = FlightRecorder()
    return _recorder


def record(kind: str, name: str, value: Any = None) -> None:
    """One-global-read hook used by the telemetry dispatchers."""
    rec = _recorder
    if rec is None:
        return
    rec.record(kind, name, value)


def note_step(step: int) -> None:
    rec = _recorder
    if rec is None:
        return
    rec.note_step(step)


def dump(reason: str, directory: Optional[str] = None,
         extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump the global ring if a destination directory can be found.

    Destination resolution: explicit ``directory`` → the active
    telemetry session's directory → ``FF_FLIGHT_DIR``.  Without any of
    those the dump is skipped (never litter the CWD).
    """
    rec = _recorder
    if rec is None:
        return None
    if directory is None:
        try:
            from flexflow_tpu import telemetry as _tel
            sess = _tel.active_session()
            if sess is not None and getattr(sess, "directory", None):
                directory = str(sess.directory)
        except Exception:
            directory = None
    if directory is None:
        directory = os.environ.get("FF_FLIGHT_DIR") or None
    if directory is None:
        return None
    try:
        return rec.dump(directory, reason, extra)
    except OSError:
        return None
