"""Sampled op-grain step capture (``--profile-every K`` /
``model.profile_step()``).

One profiled step wraps the step dispatch in
``jax.profiler.start_trace``/``stop_trace``, then feeds the resulting
``xplane.pb`` through :mod:`flexflow_tpu.scope.attribution` to produce
the report ``profile`` section: per-op ``measured_s`` next to the
plan's ``predicted_s``.  Captures are sampled (every K steps, or a
one-shot armed by ``model.profile_step()``) because tracing a step is
not free — the always-on layer is the flight recorder, not this.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
from typing import Any, Dict, Iterable, Optional

from . import attribution

__all__ = ["StepProfiler"]


class StepProfiler:
    """Owns capture cadence + trace dirs for op-grain profiling."""

    def __init__(self, every: int = 0, trace_root: Optional[str] = None,
                 keep_traces: bool = False):
        self.every = int(every)
        self.trace_root = trace_root
        self.keep_traces = bool(keep_traces)
        self._armed = False           # one-shot via model.profile_step()
        self._capturing: Optional[str] = None
        self._t0 = 0.0
        self._owns_root = False
        self.last_section: Optional[Dict[str, Any]] = None

    @property
    def enabled(self) -> bool:
        return self.every > 0 or self._armed

    def arm(self) -> None:
        """Request a one-shot capture of the next step."""
        self._armed = True

    def should_capture(self, step: int) -> bool:
        if self._armed:
            return True
        # Skip step 0: it folds compile/warmup time into the capture.
        return self.every > 0 and step > 0 and step % self.every == 0

    # ---------------------------------------------------------- capture

    def _root(self) -> str:
        if self.trace_root is None:
            self.trace_root = tempfile.mkdtemp(prefix="ffscope-")
            self._owns_root = True
        os.makedirs(self.trace_root, exist_ok=True)
        return self.trace_root

    def begin(self, step: int) -> bool:
        """Start tracing one step.  Returns False when a trace is
        already active (e.g. ``--xprof-dir`` wraps the whole fit) —
        nested captures are not supported by the profiler."""
        import jax

        trace_dir = os.path.join(self._root(), "step%06d" % step)
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception:
            self._armed = False
            return False
        self._capturing = trace_dir
        self._t0 = time.perf_counter()
        return True

    def end(self, step: int, op_names: Iterable[str],
            device_time_s: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Stop tracing, attribute, and return the profile section.

        ``device_time_s`` defaults to the wall-clock dispatch→blocked
        window measured around the step (the caller blocked before
        calling this).
        """
        import jax

        trace_dir, self._capturing = self._capturing, None
        self._armed = False
        if trace_dir is None:
            return None
        wall_s = time.perf_counter() - self._t0
        try:
            jax.profiler.stop_trace()
        except Exception:
            return None
        if device_time_s is None:
            device_time_s = wall_s
        names = list(op_names)
        try:
            attr = attribution.attribute_trace(trace_dir, names)
        finally:
            if not self.keep_traces:
                shutil.rmtree(trace_dir, ignore_errors=True)
        section = attribution.build_profile_section(
            attr, step=step, device_time_s=float(device_time_s),
            source="xplane", all_op_names=names)
        self.last_section = section
        return section

    def abandon(self) -> None:
        """Stop a capture without attribution (step raised)."""
        import jax

        if self._capturing is None:
            return
        trace_dir, self._capturing = self._capturing, None
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        shutil.rmtree(trace_dir, ignore_errors=True)

    def close(self) -> None:
        self.abandon()
        if self._owns_root and self.trace_root and not self.keep_traces:
            shutil.rmtree(self.trace_root, ignore_errors=True)
