"""Multihost hang watchdog: detect a stuck step and leave an artifact.

A hung collective is the worst multihost failure mode: every process
blocks inside XLA, no Python exception fires, and the job dies by
external timeout with no artifact.  The watchdog is a named daemon
thread (``ffscope-watchdog``) that watches *step-boundary progress*:
the fit/serving loop calls :meth:`HangWatchdog.beat` once per step; if
no beat arrives within ``max(timeout_s, step_EMA x multiplier)`` the
watchdog fires — dumps the flight record plus per-host last-heartbeat
state, names the lagging host, and optionally aborts the main thread.

Heartbeats ride a file/dir channel (one small JSON per host under
``<dir>/heartbeats/``), never collectives: a hung collective must not
hang the watchdog.  The lagging host is simply the one whose heartbeat
file is stalest — in a gang-scheduled SPMD program the host that
stopped beating first is the one the others are blocked on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import flightrec

__all__ = ["HangWatchdog"]

THREAD_NAME = "ffscope-watchdog"
# Rate limit for heartbeat-file writes; beats themselves are in-memory.
_HEARTBEAT_WRITE_INTERVAL_S = 0.5


class HangWatchdog:
    """Detect a stuck step from the absence of step-boundary beats."""

    def __init__(self, timeout_s: float = 60.0, multiplier: float = 10.0,
                 directory: Optional[str] = None,
                 host_index: int = 0, abort: bool = False,
                 on_fire=None, poll_interval_s: float = 0.25):
        self.timeout_s = float(timeout_s)
        self.multiplier = float(multiplier)
        self.directory = directory
        self.host_index = int(host_index)
        self.abort = bool(abort)
        self.on_fire = on_fire
        self.poll_interval_s = float(poll_interval_s)
        self.fired = 0
        self.last_fire: Optional[Dict[str, Any]] = None
        self._ema_s: Optional[float] = None
        self._last_beat_t: Optional[float] = None
        self._last_beat_step = -1
        self._last_hb_write = 0.0
        self._armed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- control

    def start(self) -> "HangWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=THREAD_NAME, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # ------------------------------------------------------------ beats

    def beat(self, step: int) -> None:
        """Mark step-boundary progress (called from the step loop)."""
        now = time.monotonic()
        prev = self._last_beat_t
        if prev is not None and step > self._last_beat_step:
            dt = now - prev
            self._ema_s = dt if self._ema_s is None else (
                0.8 * self._ema_s + 0.2 * dt)
        self._last_beat_step = step
        self._last_beat_t = now
        self._armed = True  # a beat (re-)arms after a firing
        if (self.directory is not None
                and now - self._last_hb_write >= _HEARTBEAT_WRITE_INTERVAL_S):
            self._last_hb_write = now
            self._write_heartbeat(step)

    def deadline_s(self) -> float:
        """Current stall deadline: max(timeout, EMA x multiplier)."""
        if self._ema_s is None:
            return self.timeout_s
        return max(self.timeout_s, self._ema_s * self.multiplier)

    # ------------------------------------------------------- heartbeats

    def _heartbeat_dir(self) -> Optional[str]:
        if self.directory is None:
            return None
        return os.path.join(self.directory, "heartbeats")

    def _write_heartbeat(self, step: int) -> None:
        hb_dir = self._heartbeat_dir()
        if hb_dir is None:
            return
        try:
            os.makedirs(hb_dir, exist_ok=True)
            path = os.path.join(hb_dir, "host-%d.json" % self.host_index)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"host": self.host_index, "step": step,
                           "time_unix": time.time()}, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def read_heartbeats(self) -> List[Dict[str, Any]]:
        """All hosts' last-heartbeat records (file channel only)."""
        hb_dir = self._heartbeat_dir()
        out: List[Dict[str, Any]] = []
        if hb_dir is None or not os.path.isdir(hb_dir):
            return out
        for name in sorted(os.listdir(hb_dir)):
            if not (name.startswith("host-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(hb_dir, name)) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        return out

    @staticmethod
    def lagging_host(heartbeats: List[Dict[str, Any]]) -> Optional[int]:
        """The host whose heartbeat is stalest (lowest step, then oldest
        time) — the one the gang is most plausibly blocked on."""
        if not heartbeats:
            return None
        worst = min(heartbeats, key=lambda h: (
            h.get("step", -1), h.get("time_unix", 0.0)))
        return worst.get("host")

    # ----------------------------------------------------------- firing

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            last = self._last_beat_t
            if last is None or not self._armed:
                continue
            stalled_s = time.monotonic() - last
            if stalled_s <= self.deadline_s():
                continue
            self._armed = False  # fire once; next beat re-arms
            self._fire(stalled_s)

    def _fire(self, stalled_s: float) -> None:
        heartbeats = self.read_heartbeats()
        lagging = self.lagging_host(heartbeats)
        info: Dict[str, Any] = {
            "watchdog": {
                "stalled_s": stalled_s,
                "deadline_s": self.deadline_s(),
                "step_ema_s": self._ema_s,
                "last_step": self._last_beat_step,
                "host": self.host_index,
                "lagging_host": lagging,
                "hosts": heartbeats,
            },
        }
        self.fired += 1
        self.last_fire = info["watchdog"]
        flightrec.record("watchdog", "fire", stalled_s)
        flightrec.dump("watchdog", directory=self.directory, extra=info)
        cb = self.on_fire
        if cb is not None:
            try:
                cb(info["watchdog"])
            except Exception:
                pass
        if self.abort:
            # Best effort: raises KeyboardInterrupt in the main thread
            # at its next bytecode boundary.  A step truly hung inside a
            # native collective won't see it — external supervision must
            # still kill the process; the artifact above is the point.
            import _thread

            _thread.interrupt_main()
