"""Minimal stdlib decoder for XLA profiler ``xplane.pb`` captures.

``jax.profiler.start_trace``/``stop_trace`` write one
``<host>.xplane.pb`` per capture: a serialized ``XSpace`` protobuf.  We
need two things out of it and nothing else, so rather than depending on
TensorFlow (which owns the generated proto classes) this module
hand-decodes the protobuf *wire format* — varints, length-delimited
fields, and the two fixed widths — with ~60 lines of stdlib code:

  * the ``/host:CPU`` (or ``/device:TPU:*``) planes' per-instruction
    event durations, keyed by HLO instruction name + program id, and
  * the ``/host:metadata`` plane's per-program ``Hlo Proto`` stat,
    whose per-instruction ``OpMetadata.op_name`` carries the
    ``jax.named_scope`` path (``jit(f)/jit(main)/dense1/dot_general``)
    that attribution joins back to PCG nodes.

Field numbers below follow tsl/profiler/protobuf/xplane.proto and
xla/service/hlo.proto; they are stable wire contracts.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["parse_xspace", "hlo_scope_map", "find_xplane_files"]


# ------------------------------------------------------------------ wire

def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield ``(field_number, wire_type, value)`` triples from ``buf``.

    Wire types: 0 varint (int), 1 fixed64 (bytes, 8), 2 length-delimited
    (bytes), 5 fixed32 (bytes, 4).  Unknown/truncated data ends the
    iteration rather than raising — profiler output sometimes trails
    padding and we only ever need a known subset of fields.
    """
    i, n = 0, len(buf)
    while i < n:
        # key varint
        key = 0
        shift = 0
        while True:
            if i >= n:
                return
            b = buf[i]
            i += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            val = 0
            shift = 0
            while True:
                if i >= n:
                    return
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
        elif wt == 1:
            val = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                if i >= n:
                    return
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:
            val = buf[i:i + 4]
            i += 4
        else:  # group / reserved: cannot skip safely
            return
        if i > n:
            return
        yield fnum, wt, val


def _utf8(b: bytes) -> str:
    return b.decode("utf-8", "replace")


# ---------------------------------------------------------------- xplane

def _parse_stat(buf: bytes) -> Dict[str, Any]:
    # XStat: 1 metadata_id, 2 double, 3 uint64, 4 int64, 5 str, 6 bytes,
    # 7 ref (index into plane stat_metadata)
    st: Dict[str, Any] = {}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            st["metadata_id"] = v
        elif f == 2 and wt == 1:
            st["value"] = struct.unpack("<d", v)[0]
        elif f == 3 and wt == 0:
            st["value"] = v
        elif f == 4 and wt == 0:
            # zigzag not used here (int64 plain)
            st["value"] = v
        elif f == 5 and wt == 2:
            st["value"] = _utf8(v)
        elif f == 6 and wt == 2:
            st["value"] = v
        elif f == 7 and wt == 0:
            st["ref"] = v
    return st


def _parse_event(buf: bytes) -> Dict[str, Any]:
    # XEvent: 1 metadata_id, 2 offset_ps, 3 duration_ps, 4 stats
    ev: Dict[str, Any] = {"metadata_id": 0, "offset_ps": 0,
                          "duration_ps": 0, "stats": []}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            ev["metadata_id"] = v
        elif f == 2 and wt == 0:
            ev["offset_ps"] = v
        elif f == 3 and wt == 0:
            ev["duration_ps"] = v
        elif f == 4 and wt == 2:
            ev["stats"].append(_parse_stat(v))
    return ev


def _parse_line(buf: bytes) -> Dict[str, Any]:
    # XLine: 1 id, 2 name, 3 timestamp_ns, 4 events, 11 display_name
    line: Dict[str, Any] = {"id": 0, "name": "", "events": []}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            line["id"] = v
        elif f == 2 and wt == 2:
            line["name"] = _utf8(v)
        elif f == 11 and wt == 2:
            line["display_name"] = _utf8(v)
        elif f == 4 and wt == 2:
            line["events"].append(_parse_event(v))
    return line


def _parse_event_metadata(buf: bytes) -> Dict[str, Any]:
    # XEventMetadata: 1 id, 2 name, 3 metadata (bytes), 4 display_name,
    # 5 stats
    md: Dict[str, Any] = {"id": 0, "name": "", "stats": []}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            md["id"] = v
        elif f == 2 and wt == 2:
            md["name"] = _utf8(v)
        elif f == 3 and wt == 2:
            md["metadata"] = v
        elif f == 4 and wt == 2:
            md["display_name"] = _utf8(v)
        elif f == 5 and wt == 2:
            md["stats"].append(_parse_stat(v))
    return md


def _parse_plane(buf: bytes) -> Dict[str, Any]:
    # XPlane: 1 id, 2 name, 3 lines, 4 event_metadata map,
    # 5 stat_metadata map, 6 stats
    plane: Dict[str, Any] = {"id": 0, "name": "", "lines": [],
                             "event_metadata": {}, "stat_metadata": {}}
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            plane["id"] = v
        elif f == 2 and wt == 2:
            plane["name"] = _utf8(v)
        elif f == 3 and wt == 2:
            plane["lines"].append(_parse_line(v))
        elif f == 4 and wt == 2:
            # map<int64, XEventMetadata>: entry {1: key, 2: value}
            key, val = 0, None
            for ef, ewt, ev in _fields(v):
                if ef == 1 and ewt == 0:
                    key = ev
                elif ef == 2 and ewt == 2:
                    val = _parse_event_metadata(ev)
            if val is not None:
                plane["event_metadata"][key or val["id"]] = val
        elif f == 5 and wt == 2:
            # map<int64, XStatMetadata>: value {1: id, 2: name}
            key, name = 0, ""
            for ef, ewt, ev in _fields(v):
                if ef == 1 and ewt == 0:
                    key = ev
                elif ef == 2 and ewt == 2:
                    for sf, swt, sv in _fields(ev):
                        if sf == 1 and swt == 0:
                            key = key or sv
                        elif sf == 2 and swt == 2:
                            name = _utf8(sv)
            plane["stat_metadata"][key] = name
    return plane


def parse_xspace(path: str) -> Dict[str, Any]:
    """Parse an ``xplane.pb`` file into ``{"planes": [...]}``.

    Each plane dict carries ``name``, ``lines`` (with resolved
    ``events``: ``metadata_id``/``duration_ps``/``stats``),
    ``event_metadata`` (id → {name, ...}) and ``stat_metadata``
    (id → name).  Durations are picoseconds, per the xplane schema.
    """
    with open(path, "rb") as f:
        buf = f.read()
    planes: List[Dict[str, Any]] = []
    for f_, wt, v in _fields(buf):
        if f_ == 1 and wt == 2:
            planes.append(_parse_plane(v))
    return {"planes": planes}


def find_xplane_files(trace_dir: str) -> List[str]:
    """Locate ``*.xplane.pb`` files under a profiler trace directory."""
    import os

    out: List[str] = []
    for root, _dirs, files in os.walk(trace_dir):
        for name in files:
            if name.endswith(".xplane.pb"):
                out.append(os.path.join(root, name))
    return sorted(out)


# ------------------------------------------------------------- hlo proto

def _parse_hlo_proto(buf: bytes) -> Dict[str, str]:
    """Decode an ``HloProto`` blob into ``{instruction_name: op_name}``.

    HloProto.f1 = HloModuleProto; HloModuleProto.f3 = repeated
    HloComputationProto; HloComputationProto.f2 = repeated
    HloInstructionProto {f1 name, f2 opcode, f7 OpMetadata{f2 op_name}}.
    ``op_name`` is the ``jax.named_scope`` path XLA recorded for the
    instruction (e.g. ``jit(f)/jit(main)/dense1/dot_general``).
    """
    scopes: Dict[str, str] = {}
    for f, wt, module in _fields(buf):
        if f != 1 or wt != 2:
            continue
        for mf, mwt, comp in _fields(module):
            if mf != 3 or mwt != 2:
                continue
            for cf, cwt, instr in _fields(comp):
                if cf != 2 or cwt != 2:
                    continue
                name, op_name = "", ""
                for inf, inwt, iv in _fields(instr):
                    if inf == 1 and inwt == 2:
                        name = _utf8(iv)
                    elif inf == 7 and inwt == 2:
                        for of, owt, ov in _fields(iv):
                            if of == 2 and owt == 2:
                                op_name = _utf8(ov)
                if name and op_name:
                    scopes[name] = op_name
    return scopes


def hlo_scope_map(space: Dict[str, Any]) -> Dict[int, Dict[str, str]]:
    """Extract ``{program_id: {instruction_name: named_scope_path}}``.

    The ``/host:metadata`` plane stores one ``XEventMetadata`` per
    compiled program, named ``<module>(<program_id>)``, whose stat
    named ``Hlo Proto`` holds the serialized HloProto with per-
    instruction OpMetadata.op_name scope paths.
    """
    out: Dict[int, Dict[str, str]] = {}
    for plane in space.get("planes", []):
        if "metadata" not in plane.get("name", ""):
            continue
        stat_names = plane.get("stat_metadata", {})
        for md in plane.get("event_metadata", {}).values():
            pid = _program_id_from_name(md.get("name", ""))
            blob: Optional[bytes] = None
            for st in md.get("stats", []):
                ref = st.get("ref", st.get("metadata_id"))
                if stat_names.get(ref) == "Hlo Proto" and isinstance(
                        st.get("value"), bytes):
                    blob = st["value"]
            if blob is None:
                continue
            scopes = _parse_hlo_proto(blob)
            if scopes:
                out.setdefault(pid, {}).update(scopes)
    return out


def _program_id_from_name(name: str) -> int:
    """``jit_f(5)`` → 5; names without an id map to 0."""
    if name.endswith(")") and "(" in name:
        inner = name[name.rfind("(") + 1:-1]
        try:
            return int(inner)
        except ValueError:
            return 0
    return 0
