"""Unity-style parallelization search (SURVEY §2.1 L4/L4').

The reference jointly optimizes algebraic substitutions + parallelization via
GraphXfer rewrites, a DP over graph decompositions, and a measured+analytic
simulator (graph.cc, substitution.cc, simulator.cc). The TPU-native recast:

- the strategy space is per-node mesh-axis assignments (MachineView analog)
  rather than device lists — XLA/GSPMD executes whatever assignment we pick,
  so the search's job is purely to pick minimum-makespan assignments;
- the simulator's measured kernels become an MXU/VPU roofline (optionally
  calibrated by one-chip microbenchmarks), and its network model becomes an
  ICI torus model (machine_model.py);
- GraphXfer parallelization rewrites (partition/replicate/combine families,
  substitution.cc:1726-1868) become per-node candidate configs; algebraic
  fusion rewrites are unnecessary (XLA fuses);
- the DP over sequence splits (SearchHelper::graph_cost) survives as-is, and
  base_optimize's budget/alpha best-first loop drives config moves.
"""

from .cost_model import CostMetrics, CostModel, classify_reshard
from .machine_model import (
    AxisTopology,
    TorusMachineModel,
    TPUMachineModel,
    machine_model_for_mesh,
)
from .unity import UnitySearch, mcmc_search_strategy, search_strategy
