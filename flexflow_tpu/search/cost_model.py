"""Cost model: per-op compute cost + inter-op resharding cost.

Reference: Simulator::measure_operator_cost (real kernel timing cached by
(OperatorParameters, MachineView), simulator.h:691-783) + the task-graph
makespan simulation with communication edges. TPU recast:

- compute: analytic MXU/HBM roofline on the *per-shard* tensor shapes (the
  shapes a chip actually sees under the candidate assignment), optionally
  calibrated by timing jitted ops on the real chip (`calibrate`, the
  inner_measure_operator_cost analog — model.cu:38-75);
- communication: classify the (producer spec → consumer spec) transition
  into the XLA collective GSPMD will insert and price it with the machine
  model. This is exactly the role of the reference's parallel ops: a
  Combine node priced as partition copies becomes an all_gather here;
- weight sync: a weight replicated across `data` with its op's inputs
  sharded over `data` incurs a gradient all_reduce per step (the NCCL
  optimizer allreduce, optimizer_kernel.cu:78-110);
- memory: per-chip bytes of weights + activations under the assignment
  (MemoryUsage analog, memory_optimization.h:44-105).

CostMetrics mirrors the reference struct (simulator.h:54-88).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from ..fftype import DataType, OperatorType as OT
from .machine_model import TPUMachineModel

_DTYPE_BYTES = {
    DataType.DT_FLOAT: 4, DataType.DT_DOUBLE: 8, DataType.DT_HALF: 2,
    DataType.DT_INT32: 4, DataType.DT_INT64: 8, DataType.DT_BOOLEAN: 1,
}


def dtype_bytes(dt) -> int:
    return _DTYPE_BYTES.get(dt, 4)


@dataclass
class CostMetrics:
    """Parity with simulator.h:54-88."""

    forward_time: float = 0.0
    backward_time: float = 0.0
    sync_time: float = 0.0       # serial gradient allreduce (incl., under
    #                              a sharded update, any co-located weight
    #                              choose_update_dim could NOT shard)
    comm_time: float = 0.0       # input resharding
    memory: float = 0.0          # per-chip bytes
    # weight-update sharding: the sharded weights' RS+AG pair (the
    # allreduce's exact ring bytes, separated so the evaluators can route
    # it onto the overlappable channel while sync_time stays serial), the
    # pair's ring-hop count, the summed per-hop issue latency priced at
    # each axis's own latency (DCN hops cost ~10× ICI), and the 1/dp
    # optimizer-state shards — all zero under the replicated update
    update_sync_time: float = 0.0
    update_hops: float = 0.0
    update_hop_s: float = 0.0
    update_shards: int = 1
    # ZeRO-3 / FSDP (stage 3, param_gather): the just-in-time all-gather
    # of this node's sharded-at-rest weights — one AG on the forward, one
    # re-gather on the backward (the gathered copy is dropped after last
    # use) — plus its summed per-hop issue latency, and the FULL gathered
    # bytes of the node's stage-3 weights (the evaluators charge at most
    # two gathered layers in flight, not one per weight). All zero below
    # stage 3.
    param_gather_time: float = 0.0
    param_gather_hop_s: float = 0.0
    gather_bytes: float = 0.0

    @property
    def total(self) -> float:
        return (self.forward_time + self.backward_time + self.sync_time
                + self.update_sync_time + self.param_gather_time
                + self.comm_time)


def price_grad_sync(cm: "CostMetrics", update_sharding: bool,
                    overlap_update: bool
                    ) -> tuple[float, float, float, float]:
    """(serial_sync_s, overlappable_comm_s, overlap_overhead_s,
    grad_sync_s) of one node's gradient sync under the given update mode
    — the ONE pricing rule both evaluators (UnitySearch.evaluate and
    substitution.evaluate_assigned_graph) apply, so the update-sharding
    decision can never disagree with the reported makespan. Replicated:
    the allreduce rides sync serially. Sharded: the sharded weights'
    RS+AG pair (update_sync_time — the allreduce's exact ring bytes)
    plus the pair's fixed per-hop issue latency (update_hop_s, priced at
    each axis's own latency) ride the overlappable channel when
    overlapped (the RS hides behind the backward producing each
    layer-order bucket, the deferred AG behind the next step's first
    consumer), or sync serially under --no-overlap-collectives — so
    serial-sharded prices strictly above replicated (the auto decision's
    tie-breaker). Any co-located weight choose_update_dim could not
    shard stays in sync_time and always prices serial, matching the
    runtime. grad_sync_s names the sharded pair's share for the strategy
    report."""
    pair = cm.update_sync_time
    if not (update_sharding and pair > 0.0):
        return cm.sync_time + pair, 0.0, 0.0, 0.0
    if overlap_update:
        return cm.sync_time, pair, cm.update_hop_s, pair
    return cm.sync_time + pair + cm.update_hop_s, 0.0, 0.0, pair


def price_param_gather(cm: "CostMetrics", overlap_update: bool
                       ) -> tuple[float, float, float, float]:
    """(serial_s, overlappable_comm_s, overlap_overhead_s, param_gather_s)
    of one node's stage-3 just-in-time weight gathers — the
    `price_grad_sync` sibling, applied by BOTH evaluators so the stage-3
    decision can never disagree with the reported makespan. The fwd
    gather is issued one layer ahead on the overlappable channel (it
    hides behind the previous layer's compute) and the bwd re-gather
    behind the next layer's backward; only the fixed per-hop issue
    latency never hides. Under --no-overlap-collectives the pair
    serializes on the node's critical path — so serial stage 3 prices
    strictly above stage 2 (the auto decision's tie-breaker).
    param_gather_time is only populated when the cost model prices
    stage 3 (CostModel.param_gather), so no flag argument is needed."""
    pg = cm.param_gather_time
    if pg <= 0.0:
        return 0.0, 0.0, 0.0, 0.0
    if overlap_update:
        return 0.0, pg, cm.param_gather_hop_s, pg
    return pg + cm.param_gather_hop_s, 0.0, 0.0, pg


def price_transfer_collective(kind: str, wire_bytes: float,
                              out_bytes: float, axis: str,
                              machine: "TPUMachineModel | None") -> float:
    """Seconds of ONE migration transfer collective (fftrans,
    analysis/transition.py) — the pricing rule the TransitionPlan's
    predicted_s is built from, kept here so migration is priced by the
    same machine-model oracle as every other collective the search
    prices. Kinds: all_gather / all_to_all (the GSPMD-derived unwinds,
    priced per axis), host_hop (a full logical array through the host
    NIC at DCN bandwidth), slice (free local dynamic-slice). With no
    machine model (pricing a checkpoint side standalone), falls back to
    the conservative dcn figure of the detected chip."""
    if kind == "slice" or wire_bytes <= 0:
        return 0.0
    if machine is None:
        from .machine_model import detect_chip

        chip = detect_chip()
        return wire_bytes / chip.dcn_bandwidth + chip.dcn_latency
    if kind == "host_hop":
        return (wire_bytes / machine.chip.dcn_bandwidth
                + machine.chip.dcn_latency)
    if kind == "all_gather":
        return machine.all_gather(out_bytes, axis)
    if kind == "all_to_all":
        # out_bytes is the per-chip send size; the oracle applies the
        # (n-1)/n wire fraction itself
        return machine.all_to_all(out_bytes, axis)
    return wire_bytes / machine.chip.dcn_bandwidth


def price_verify_scale(q: int) -> float:
    """Relative cost of a q-token speculative VERIFY call vs the q=1
    decode step (serving/speculative.py) — the assumed prior the payoff
    gate uses for a verify bucket it has never run. Decode-grain calls
    are launch/weight-read dominated, not FLOP dominated, so widening
    the query dim from 1 to q costs far less than qx: a conservative
    linear tail (quarter-slope) over the fixed launch cost. The first
    real call replaces this with the measured per-bucket EMA; decisions
    record which source priced them (`verify_cost_source`)."""
    return 1.0 + 0.25 * (max(1, int(q)) - 1)


def _shard_elems(shape: tuple[int, ...], assignment, axis_sizes) -> float:
    """Per-chip element count of a tensor under an axis assignment."""
    n = 1.0
    for i, dim in enumerate(shape):
        deg = 1
        if assignment and i < len(assignment):
            for ax in assignment[i]:
                deg *= axis_sizes.get(ax, 1)
        n *= max(1, math.ceil(dim / deg))
    return n


def _axes_of(assignment) -> set:
    out = set()
    for entry in assignment or ():
        out.update(entry)
    return out


def classify_reshard(shape, from_assign, to_assign, dtype, machine:
                     TPUMachineModel) -> float:
    """Price the collective GSPMD inserts for producer spec → consumer spec.

    Per-dim transitions:
      axis removed from a dim          → all_gather over that axis
      axis added to a dim              → local slice (free)
      axis moved between dims          → all_to_all
    (the Combine / Repartition / FusedParallelOp runtime costs, SURVEY §2.3)
    """
    if from_assign == to_assign:
        return 0.0
    bytes_el = dtype_bytes(dtype)
    cost = 0.0
    ndim = len(shape)
    from_assign = tuple(from_assign or ((),) * ndim)
    to_assign = tuple(to_assign or ((),) * ndim)
    removed, added = [], []
    for i in range(ndim):
        f = set(from_assign[i]) if i < len(from_assign) else set()
        t = set(to_assign[i]) if i < len(to_assign) else set()
        removed += [(i, ax) for ax in f - t]
        added += [(i, ax) for ax in t - f]
    moved = {ax for _, ax in removed} & {ax for _, ax in added}
    # bytes of the local shard *before* the transition
    local_bytes = _shard_elems(shape, from_assign, machine.axis_sizes) * bytes_el
    for _, ax in removed:
        if ax in moved:
            cost += machine.all_to_all(local_bytes, ax)
        else:
            n = machine.axis_size(ax)
            cost += machine.all_gather(local_bytes * n, ax)
    # additions alone are local dynamic-slices: free
    return cost


def price_parallel_node(node, machine) -> tuple[float, tuple]:
    """(comm seconds, ICI axes) of one explicit parallel-op node — the
    collective its Repartition/Combine/Replicate/Reduction semantics lower
    to (the reference prices these as partition-copy tasks via the
    simulator; SURVEY §2.3 maps them to all_to_all/all_gather/psum). A
    FusedParallelOp pays for each member transform so fused rewrites don't
    look artificially free."""
    pt = node.inputs[0]
    local_bytes = pt.shape.piece_elements() * dtype_bytes(pt.dtype)
    if node.op_type == OT.OP_FUSED_PARALLEL:
        subs = [(i.op_type, i) for i in node.params.ops]
    else:
        subs = [(node.op_type, node.params)]
    comm = 0.0
    comm_axes = []

    def _degree_axis(degree: int) -> str:
        from ..machine import AXIS_MODEL

        # several mesh axes can share a size (dcn=2, model=2 on a 2-host
        # mesh); an explicit parallel op's collective rides ICI, so prefer
        # non-DCN axes — matching on the leading `dcn` axis would price a
        # tensor-parallel Combine at DCN bandwidth (~10× slow) and make the
        # search systematically reject model-parallel rewrites multi-host
        fallback = None
        for ax, size in machine.axis_sizes.items():
            if size == degree:
                if ax not in machine.axis_over_dcn:
                    return ax
                fallback = fallback or ax
        return fallback or AXIS_MODEL

    for st, sp in subs:
        # rewrites thread the mesh axes they bind onto the params (the
        # durable fix for degree→axis ambiguity: a declared axis is priced
        # as itself, DCN or not); legacy degree-only params fall back to
        # _degree_axis inference
        declared = tuple(getattr(sp, "axes", ()))
        if st == OT.OP_COMBINE:
            axes = declared or (_degree_axis(sp.degree),)
            # multi-axis combine gathers axis by axis; the gathered shard
            # grows by each axis's size before the next gather
            grown = local_bytes
            for ax in axes:
                grown *= machine.axis_size(ax)
                comm += machine.all_gather(grown, ax)
                comm_axes.append(ax)
        elif st == OT.OP_REPARTITION:
            if pt.shape.total_degree > 1:
                axes = declared or (_degree_axis(sp.degree),)
                # each split shrinks the shard the next all_to_all moves
                # (mirror of the combine path, which grows it per gather)
                shrink = local_bytes
                for ax in axes:
                    comm += machine.all_to_all(shrink, ax)
                    comm_axes.append(ax)
                    shrink /= max(1, machine.axis_size(ax))
            # from fully-replicated: local slice, free
        elif st == OT.OP_REDUCTION:
            axes = declared or (_degree_axis(sp.degree),)
            for ax in axes:
                comm += machine.all_reduce(local_bytes, ax)
                comm_axes.append(ax)
        # Replicate: broadcast of an already-replicated tensor and Pipeline
        # stage markers are free
    return comm, tuple(comm_axes)


def graph_makespan(compute, comm, src, dst, axis=None) -> float:
    """Makespan of a strategy's task graph: max(sum of compute, critical
    path of compute+comm) — concurrent branches (DLRM towers, Inception)
    cost max(paths), not sum (the simulate_runtime analog,
    simulator.h:691-783). When `axis` is given (int id per node, -1 =
    none), adds per-ICI-axis link-occupancy bounds — comm on the same mesh
    axis serializes while disjoint axes overlap, the TPU recast of the
    reference's horizontal machine-resource splits (graph.cc:267-321).
    Native ff_eval_makespan[_axes] when the toolchain is available;
    identical pure-Python fallback otherwise. Raises ValueError on a
    cyclic graph."""
    from .. import native

    if axis is not None:
        res = native.eval_makespan_axes(compute, comm, axis, src, dst)
    else:
        res = native.eval_makespan(compute, comm, src, dst)
    if res is not None:
        return res
    n = len(compute)
    preds: list[list[int]] = [[] for _ in range(n)]
    succs: list[list[int]] = [[] for _ in range(n)]
    indeg = [0] * n
    for s, d in zip(src, dst):
        preds[d].append(s)
        succs[s].append(d)
        indeg[d] += 1
    ready = [v for v in range(n) if indeg[v] == 0]
    finish = [0.0] * n
    critical = 0.0
    done = 0
    while ready:
        v = ready.pop()
        done += 1
        start = max((finish[p] for p in preds[v]), default=0.0)
        finish[v] = start + compute[v] + comm[v]
        critical = max(critical, finish[v])
        for w in succs[v]:
            indeg[w] -= 1
            if indeg[w] == 0:
                ready.append(w)
    if done != n:
        raise ValueError("graph_makespan: graph has a cycle")
    out = max(float(sum(compute)), critical)
    if axis is not None:
        per_axis: dict[int, float] = {}
        for v in range(n):
            if axis[v] >= 0:
                per_axis[axis[v]] = per_axis.get(axis[v], 0.0) + comm[v]
        for c in per_axis.values():
            out = max(out, c)
    return out


class _MakespanAccum:
    """Collects per-node (compute, comm) costs + dependency edges during a
    strategy evaluation, then evaluates the makespan. Shared by both search
    evaluators so neither prices a branchy graph as a serial sum. Each
    node's comm is tagged with the ICI axis it occupies so same-axis comm
    serializes (see graph_makespan).

    `overlap_sync` implements --search-overlap-backward-update (reference
    config.h:search_overlap_backward_update): gradient-allreduce time
    (passed via `sync=`) then overlaps other nodes' compute instead of
    serializing on its own node's critical path — it still occupies its ICI
    axis, so the per-axis link-occupancy bound keeps it honest.

    `overlappable_comm` is the round-7 channel for ops whose OWN collective
    runs concurrently with their own compute (ring attention's
    double-buffered ppermute pipeline, the decomposed collective matmul):
    the node's critical-path contribution becomes
    max(compute, overlappable_comm) + overlap_overhead instead of
    compute + comm — the roofline of a perfectly pipelined schedule, plus
    the fixed per-hop issue cost that never hides. The overlapped traffic
    still occupies its ICI axis, so the per-axis link-occupancy bound in
    `makespan` keeps concurrent same-axis collectives honest."""

    def __init__(self, overlap_sync: bool = False):
        self.compute: list[float] = []
        self.comm: list[float] = []
        self.axis: list[int] = []
        self.idx: dict[int, int] = {}  # node guid -> task index
        self._axis_ids: dict[str, int] = {}
        self.overlap_sync = overlap_sync
        self._sync_by_axis: dict[int, float] = {}
        self._overlap_by_axis: dict[int, float] = {}

    def add(self, guid: int, compute: float, comm: float, comm_axes=(),
            sync: float = 0.0, overlappable_comm: float = 0.0,
            overlap_overhead: float = 0.0):
        self.idx[guid] = len(self.compute)
        ax = -1
        for name in comm_axes:
            ax = self._axis_ids.setdefault(name, len(self._axis_ids))
            break  # attribute to the first (dominant) axis
        self.axis.append(ax)
        if overlappable_comm > 0.0:
            # overlap-capable op: comm hides behind (or extends past) the
            # op's own compute; only the fixed issue overhead serializes
            self._overlap_by_axis[ax] = (
                self._overlap_by_axis.get(ax, 0.0) + overlappable_comm)
            compute = max(compute, overlappable_comm) + overlap_overhead
        self.compute.append(compute)
        if self.overlap_sync and sync > 0.0:
            self._sync_by_axis[ax] = self._sync_by_axis.get(ax, 0.0) + sync
            self.comm.append(comm)
        else:
            self.comm.append(comm + sync)

    def makespan(self, in_edges) -> float:
        src, dst = [], []
        for guid, i in self.idx.items():
            for e in in_edges[guid]:
                j = self.idx.get(e.src)
                if j is not None:
                    src.append(j)
                    dst.append(i)
        if not self.compute:
            return 0.0
        out = graph_makespan(self.compute, self.comm, src, dst,
                             axis=self.axis)
        if self._sync_by_axis or self._overlap_by_axis:
            # per-axis link occupancy including the OVERLAPPED traffic:
            # hiding comm behind compute does not add link capacity, so
            # same-axis serial + overlapped + sync bytes still serialize
            # against each other
            per_axis_comm: dict[int, float] = {}
            for ax, c in zip(self.axis, self.comm):
                if ax >= 0:
                    per_axis_comm[ax] = per_axis_comm.get(ax, 0.0) + c
            for ax, c in self._overlap_by_axis.items():
                if ax >= 0:
                    per_axis_comm[ax] = per_axis_comm.get(ax, 0.0) + c
            if self._overlap_by_axis:
                # the plain per-axis occupancy bound only exists to keep
                # OVERLAPPED bytes honest; sync-only plans keep the
                # pre-overlap pricing (and diagnostics/explain.py
                # verify_report_total applies the same gate)
                for ax, c in per_axis_comm.items():
                    out = max(out, c)
            for ax, s in self._sync_by_axis.items():
                out = max(out, s + per_axis_comm.get(ax, 0.0))
        return out


class CostModel:
    """Costs one node / one whole strategy; memoized like the reference's
    (params, view) cache (simulator.h strict/relaxed hash caches)."""

    def __init__(self, machine: TPUMachineModel, mfu: float = 0.4,
                 opt_slots: int = 1):
        self.machine = machine
        # achievable fraction of peak (calibration refines per-op)
        self.mfu = mfu
        # optimizer state entries per weight (SGD momentum 1, Adam 2) for
        # the memory model
        self.opt_slots = opt_slots
        # weight-update sharding (ZeRO / Xu et al.): price the gradient
        # sync as a reduce-scatter + all-gather pair (same ring bytes as
        # the allreduce) and the masters/grads/slots at 1/shards per chip
        # plus one gathered compute copy. overlap_update additionally
        # routes the pair onto the overlappable channel in the evaluators
        # (max(compute, comm) + hop latency). Toggled by
        # unity.choose_update_sharding / --weight-update-sharding.
        self.update_sharding = False
        self.overlap_update = False
        # ZeRO-3 / FSDP (stage 3): additionally price the trainable
        # weights SHARDED AT REST — per-chip memory drops the always-live
        # gathered compute copy (the evaluators charge at most two
        # gathered layers in flight instead), the grad sync becomes the
        # RS alone, and the fwd gather + bwd re-gather pair is priced by
        # price_param_gather on the overlappable channel. Implies
        # update_sharding.
        self.param_gather = False
        self._cache: dict = {}
        self._calibration: dict = {}

    # -------------------------------------------------------------- op cost

    def op_cost(self, node, out_assigns, weight_specs_assigns,
                in_shapes, in_assigns) -> CostMetrics:
        key = (node.guid,
               tuple(tuple(a) for a in out_assigns or ()),
               tuple(sorted((k, str(v)) for k, v in
                            (weight_specs_assigns or {}).items())),
               tuple(tuple(tuple(e) for e in (a or ())) for a in in_assigns),
               self.update_sharding, self.param_gather)
        if key in self._cache:
            return self._cache[key]

        axis_sizes = self.machine.axis_sizes
        op_def = node.op_def
        # shard the op: flops scale by the product of degrees over sharded
        # dims of the OUTPUT (each chip computes its shard)
        out_shapes = [tuple(d.size for d in pt.shape.dims
                            if not d.is_replica_dim) for pt in node.outputs]
        full_flops = op_def.flops(node.params, list(in_shapes), out_shapes)
        # per-chip flops shrink by every axis the computation is split over:
        # output sharding AND reduction-dim (weight) sharding — a tp_row
        # Linear with its kernel sharded over `model` does 1/model_deg of
        # the contraction per chip even though its output is replicated
        parallel_axes = set()
        if out_assigns:
            parallel_axes |= _axes_of(out_assigns[0])
        for spec in (weight_specs_assigns or {}).values():
            if spec is not None:
                for entry in spec:
                    if entry is None:
                        continue
                    axes = entry if isinstance(entry, tuple) else (entry,)
                    parallel_axes.update(axes)
        degree = 1
        for ax in parallel_axes:
            degree *= axis_sizes.get(ax, 1)
        shard_flops = full_flops / max(1, degree)

        # bytes touched: inputs + outputs + weights per chip (the output
        # bytes double as the activation-memory term below)
        bytes_touched = 0.0
        for shape, assign in zip(in_shapes, in_assigns):
            bytes_touched += _shard_elems(shape, assign, axis_sizes) * 4
        act_bytes = 0.0
        for i, pt in enumerate(node.outputs):
            a = out_assigns[i] if out_assigns and i < len(out_assigns) else ()
            act_bytes += _shard_elems(
                tuple(d.size for d in pt.shape.dims if not d.is_replica_dim),
                a, axis_sizes) * dtype_bytes(pt.dtype)
        bytes_touched += act_bytes

        # tied-weight nodes (shared_op) read another node's parameters: the
        # bytes are still touched each step, but the weight/grad/optimizer
        # memory and the gradient allreduce are owned (and already counted)
        # by the source node
        from ..parallel.ops import choose_update_dim, grad_sync_axes

        tied = bool(getattr(node, "weight_source", None))
        weight_mem = 0.0
        sync = 0.0
        update_sync = 0.0
        update_hops = 0.0
        update_hop_s = 0.0
        update_shards = 1
        param_gather_t = 0.0
        param_gather_hop_s = 0.0
        gather_bytes = 0.0
        for ws in node.weight_specs:
            spec = (weight_specs_assigns or {}).get(ws.name)
            w_assign = _spec_to_assignment(spec, len(ws.shape))
            wb = _shard_elems(ws.shape, w_assign, axis_sizes) * dtype_bytes(ws.dtype)
            bytes_touched += wb
            if tied:
                continue
            # gradient sync over every data-ish axis the weight is NOT
            # sharded over but its consumers' activations are; resolved
            # through the SAME helpers the executor places with
            # (parallel/ops), so runtime and pricing cannot disagree
            sync_axes = ()
            if ws.trainable:
                w_axes = _axes_of(w_assign)
                act_axes = _axes_of(out_assigns[0] if out_assigns else ())
                sync_axes = grad_sync_axes(act_axes, w_axes)
            sharded = (
                self.update_sharding and sync_axes
                and choose_update_dim(ws.shape, w_assign, sync_axes,
                                      axis_sizes) is not None)
            if sharded:
                shards = 1
                for ax in sync_axes:
                    # RS + AG together move the allreduce's exact ring
                    # bytes; the win is the overlappable channel (the
                    # evaluators route update_sync there — a co-located
                    # non-shardable weight's allreduce stays in `sync`
                    # and keeps pricing serial, matching the runtime) +
                    # the 1/dp state below. Hop issue latency priced at
                    # the axis's own latency (DCN hops cost ~10× ICI)
                    rs_t = self.machine.reduce_scatter(wb, ax)
                    ag_t = self.machine.all_gather(wb, ax)
                    n = self.machine.axis_size(ax)
                    lat = (n - 1) * self.machine._lat(ax)
                    if self.param_gather:
                        # stage 3: the grad sync is the RS alone (the
                        # cotangent of the gathered copy scatters to the
                        # owner shard); the deferred AG moves into the
                        # explicit gather pair — fwd just-in-time + bwd
                        # re-gather — priced by price_param_gather
                        update_sync += rs_t
                        update_hops += n - 1
                        update_hop_s += lat
                        param_gather_t += 2.0 * ag_t
                        param_gather_hop_s += 2.0 * lat
                    else:
                        update_sync += rs_t + ag_t
                        update_hops += 2.0 * (n - 1)
                        update_hop_s += 2.0 * lat
                    shards *= n
                update_shards = max(update_shards, shards)
                if self.param_gather:
                    # stage 3 per-chip memory: master/grad/slots sharded
                    # 1/shards with NO resident gathered copy — the
                    # transient two-layers-in-flight gather working set
                    # is charged once per plan by the evaluators
                    # (gather_bytes below), not once per weight
                    weight_mem += wb * (2 + self.opt_slots) / shards
                    gather_bytes += wb
                else:
                    # per-chip memory: one gathered compute copy +
                    # master/grad/slots sharded 1/shards (the ZeRO
                    # stage-2 saving)
                    weight_mem += wb + wb * (2 + self.opt_slots) / shards
            else:
                for ax in sync_axes:
                    sync += self.machine.all_reduce(wb, ax)
                weight_mem += wb * (2 + self.opt_slots)

        eff_peak_t = self.machine.compute_time(shard_flops / self.mfu,
                                               bytes_touched)
        # measured full-op (fwd, bwd) times (calibrate_graph) override the
        # fixed-mfu roofline; scale by the shard fraction since the
        # measurement is of the unsharded op on one chip
        calib = self._calibration.get(
            _params_key(node, tuple(tuple(s) for s in in_shapes)))
        if calib is not None:
            cal_fwd, cal_bwd = calib
            ratio = shard_flops / max(full_flops, 1.0)
            fwd = cal_fwd * ratio
            bwd = cal_bwd * ratio
        else:
            fwd = eff_peak_t
            # rule of thumb (also the reference simulator's default) when
            # unmeasured: bwd ≈ 2× fwd
            bwd = 2.0 * fwd
        # per-chip memory (MemoryUsage analog, memory_optimization.h:44-105):
        # master weight + gradient + optimizer slots (opt_slots: 1 for SGD
        # momentum, 2 for Adam) + every output activation at its dtype;
        # under weight-update sharding the master/grad/slot term shrank to
        # 1/shards per weight above (plus one gathered compute copy)
        cm = CostMetrics(
            forward_time=fwd,
            backward_time=bwd,
            sync_time=sync,
            update_sync_time=update_sync,
            memory=weight_mem + act_bytes,
            update_hops=update_hops,
            update_hop_s=update_hop_s,
            update_shards=update_shards,
            param_gather_time=param_gather_t,
            param_gather_hop_s=param_gather_hop_s,
            gather_bytes=gather_bytes,
        )
        self._cache[key] = cm
        return cm

    # -------------------------------------------------------- calibration

    def calibrate(self, node, fn, example_args) -> tuple[float, float]:
        """Measure a jitted op on the real chip and pin its (forward,
        backward) costs — the Op::inner_measure_operator_cost analog
        (warmup + timed repeats, model.cu:38-75). The reference times
        forward and backward kernels separately (linear.cc:792-925); here
        backward = (time of value+vjp w.r.t. every float operand incl.
        weights) − forward, so TP-vs-DP tradeoffs that hinge on backward
        cost use a measured ratio instead of the 2× rule of thumb.

        RELAY-IMMUNE two-point methodology (established empirically against
        the tunneled backend, scripts/debug_calibrate.py): timing separate
        calls measures ~ms dispatch; closure-captured constants re-stage
        through the tunnel per call (~100 ms for 12 MB); and
        block_until_ready does not reliably synchronize — only a
        device_get fetch does, which itself costs a large CONSTANT (~90 ms
        here). So: ONE jitted lax.fori_loop executable with a DYNAMIC trip
        count, synchronized by fetching its scalar result, timed at two
        trip counts — the slope (t(n2)−t(n1))/(n2−n1) is the true per-rep
        kernel time with every constant overhead cancelled. The loop body
        feeds a carry-derived epsilon into the first float operand so XLA
        can neither hoist the loop-invariant op nor DCE it; medians of 3
        guard against jitter."""
        import statistics
        import time

        import jax
        import jax.numpy as jnp

        dev_args = jax.device_put(example_args)

        def _timed(f):
            flat0, tree = jax.tree.flatten(dev_args)
            fidx = next((i for i, leaf in enumerate(flat0)
                         if jnp.issubdtype(jnp.asarray(leaf).dtype,
                                           jnp.floating)), None)

            @jax.jit
            def loop(flat, n):
                def body(_, carry):
                    cur = list(flat)
                    if fidx is not None:
                        # dynamic, numerically-negligible perturbation:
                        # defeats loop-invariant hoisting without changing
                        # the op's cost
                        cur[fidx] = cur[fidx] + (carry * 1e-30).astype(
                            cur[fidx].dtype)
                    out = f(*jax.tree.unflatten(tree, cur))
                    # FULLY reduce EVERY output leaf: an unused leaf (e.g.
                    # the dW of a multi-grad tuple) lets XLA DCE its
                    # producer, and consuming a single element lets the
                    # simplifier sink the slice INTO a producing dot —
                    # measured on-chip: [0]-consumption reads a ~zero
                    # slope while the full sum reads exactly the bytes
                    # roofline. The sum fuses into the producer's epilogue
                    # (no extra HBM pass), so it is both safe and free.
                    upd = jnp.float32(0)
                    for leaf in jax.tree.leaves(out):
                        upd += jnp.sum(leaf).astype(jnp.float32)
                    return carry + upd

                return jax.lax.fori_loop(0, n, body, jnp.float32(0))

            n1, n2 = 16, 272
            float(jax.device_get(loop(flat0, jnp.int32(n1))))  # compile+warm

            def t_of(n):
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    # the fetch IS the measurement (device_get is the
                    # only reliable sync on the tunneled backend, see
                    # module docstring)
                    float(jax.device_get(loop(flat0, jnp.int32(n))))  # fflint: ok host_sync_in_loop
                    ts.append(time.perf_counter() - t0)
                return statistics.median(ts)

            dt = (t_of(n2) - t_of(n1)) / (n2 - n1)
            return max(dt, 1e-7)

        fwd_t = _timed(fn)
        bwd_t = None
        diff_argnums = tuple(
            i for i, a in enumerate(example_args)
            if jax.tree.leaves(a)
            and all(jnp.issubdtype(leaf.dtype, jnp.floating)
                    for leaf in jax.tree.leaves(a))
        )
        if diff_argnums:
            def scalar_loss(*args):
                # squared loss, not a plain sum: a constant cotangent lets
                # XLA collapse the dW matmul into a row-sum reduction and
                # the "measured backward" reads near-zero; d(out²) = 2·out
                # keeps the cotangent dense like a real training backward
                return jnp.sum(jnp.square(fn(*args).astype(jnp.float32)))

            try:
                # _timed wraps the callable in its own jitted scan loop
                g = jax.grad(scalar_loss, argnums=diff_argnums)
                both_t = _timed(g)
                # grad re-runs the forward; keep a sane floor when timing
                # noise makes the subtraction go negative
                bwd_t = max(both_t - fwd_t, 0.25 * fwd_t)
            except Exception:
                bwd_t = None
        if bwd_t is None:
            bwd_t = 2.0 * fwd_t  # non-differentiable op: rule of thumb
        self._calibration[_params_key(node)] = (fwd_t, bwd_t)
        self._cache.clear()  # cached roofline entries are stale now
        return fwd_t, bwd_t

    def calibrate_graph(self, graph, top_k: int = 4,
                        remeasure: bool = False) -> int:
        """Measure the top-K most expensive distinct ops of a PCG on the
        local device and pin their costs — the reference measures *every*
        candidate op on GPU0 (simulator.h:691-783); we measure the K that
        dominate the roofline estimate. Returns the number of ops measured.
        Failures (unsupported harness shapes) are skipped, leaving the
        roofline estimate in place.

        The top-K set is ranked over ALL distinct compute ops; entries
        already calibrated (this run, or loaded from the warm-start
        calibration DB) count as cache hits and are skipped — NOT replaced
        by the next op down the ranking, so the measured set is a
        deterministic function of (graph, top_k) and a fully-warm DB
        measures zero (the plan fingerprint depends on this).
        `remeasure=True` re-measures the top-K even when cached — the
        drift-recalibration path, where stale measurements are exactly
        what needs refreshing; a successful re-measure overwrites the
        entry, a harness failure keeps the previous one."""
        candidates: dict = {}
        for node in graph.topo_order():
            if (node.op_type in _NON_COMPUTE or not node.outputs
                    or not node.inputs):
                continue
            key = _params_key(node)
            if key in candidates:
                continue
            try:
                in_shapes = [pt.shape.logical_shape for pt in node.inputs]
                out_shapes = [pt.shape.logical_shape for pt in node.outputs]
                est = node.op_def.flops(node.params, in_shapes, out_shapes)
            except Exception:
                continue
            candidates[key] = (est, node)
        measured = 0
        hits = 0
        ranked = sorted(candidates.items(),
                        key=lambda kv: -kv[1][0])[:top_k]
        for key, (_, node) in ranked:
            if key in self._calibration and not remeasure:
                hits += 1
                continue
            # remeasure overwrites on SUCCESS (calibrate stores the new
            # reading); a harness failure keeps the previous measurement
            # rather than discarding it for the roofline guess
            try:
                fn, args = _op_harness(node)
                self.calibrate(node, fn, args)
                measured += 1
            except Exception:
                continue
        # measured-vs-cache-hit counts for this pass (telemetry reads them
        # right after — the calibration twin of the search evals /
        # cache_hits counters, so calibration-reuse drift is observable)
        self.calib_stats = {
            "measured": measured,
            "cache_hits": hits,
            "candidates": len(candidates),
        }
        return measured

    def calibrate_nodes(self, graph, names, remeasure: bool = True
                        ) -> list:
        """Re-measure exactly the named PCG ops (ffscope's targeted
        drift response): an op-grain advisory knows WHICH op's
        measurement went stale, so only that op's calibration entry is
        refreshed — not the blanket top-K. Returns the `_params_key`s
        actually refreshed (the calibration-DB entries to persist);
        undrifted ops are never re-measured on this path."""
        wanted = set(names)
        refreshed: list = []
        done: set = set()
        for node in graph.topo_order():
            if node.name not in wanted or node.op_type in _NON_COMPUTE:
                continue
            key = _params_key(node)
            if key in done or (key in self._calibration
                               and not remeasure):
                continue
            done.add(key)
            try:
                fn, args = _op_harness(node)
                self.calibrate(node, fn, args)
                refreshed.append(key)
            except Exception:
                continue
        self.calib_stats = {
            "measured": len(refreshed),
            "cache_hits": 0,
            "candidates": len(done),
            "targeted": sorted(wanted),
        }
        return refreshed

    # ------------------------------------------- collective calibration
    # The ring/pipeline schedules are priced per ppermute hop; the analytic
    # machine model guesses that hop from datasheet ICI bandwidth. Like the
    # op measurements above, the real hop is measurable: a jitted
    # shard_map fori_loop of chained ppermutes, timed at two trip counts
    # (slope = true per-hop seconds, constants cancelled) and at two
    # payload sizes (slope over bytes = effective 1/bandwidth, intercept =
    # per-hop launch latency). Entries live in the same `_calibration`
    # dict under a reserved OP_NOOP key, so the warm-start calibration DB
    # persists them per device kind for free.

    _HOP_BYTES = (1 << 16, 1 << 22)  # 64 KiB / 4 MiB per-chip payloads

    def _collective_key(self, axis: str):
        return (OT.OP_NOOP, f"__collective_ppermute__:{axis}",
                ((self._HOP_BYTES[0],), (self._HOP_BYTES[1],)))

    def collective_rotate(self, bytes_per_chip: float, axis: str) -> float:
        """One ring-rotation hop for `bytes_per_chip`: the calibrated
        two-point fit when a measurement exists, else the machine model's
        analytic `rotate`."""
        cal = self._calibration.get(self._collective_key(axis))
        if cal is None:
            return self.machine.rotate(bytes_per_chip, axis)
        t_small, t_big = cal
        b0, b1 = self._HOP_BYTES
        slope = max((t_big - t_small) / (b1 - b0), 0.0)
        lat = max(t_small - slope * b0, 0.0)
        return lat + bytes_per_chip * slope

    def calibrate_collectives(self, mesh, axes) -> int:
        """Measure the ppermute hop on each of `axes` (mesh axes of size
        > 1) and pin it for `collective_rotate`. Cached entries (including
        warm-start DB loads) are kept; harness failures leave the analytic
        model in place. Returns the number of axes measured."""
        measured = 0
        for axis in axes:
            key = self._collective_key(axis)
            if key in self._calibration:
                continue
            try:
                ts = tuple(self._measure_hop(mesh, axis, nb)
                           for nb in self._HOP_BYTES)
            except Exception:
                continue
            self._calibration[key] = ts
            measured += 1
        if measured:
            self._cache.clear()
        return measured

    def _measure_hop(self, mesh, axis: str, nbytes: int) -> float:
        """Median per-hop seconds of a chained-ppermute loop at the given
        per-chip payload (two trip counts; the slope cancels dispatch and
        sync constants — the same relay-immune methodology as
        `calibrate`)."""
        import statistics
        import time

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..parallel.smap import shard_map

        n = dict(mesh.shape).get(axis, 1)
        if n <= 1:
            raise ValueError(f"axis {axis!r} has size {n}")
        from ..parallel.ops import ring_permutation

        perm = ring_permutation(n)
        elems = max(128, nbytes // 4)
        x = jnp.zeros((n * elems,), jnp.float32)
        spec = P(axis)

        def local(xs, reps):
            def body(_, carry):
                return jax.lax.ppermute(carry, axis, perm)

            return jax.lax.fori_loop(0, reps, body, xs)

        inner = shard_map(local, mesh=mesh, in_specs=(spec, P()),
                          out_specs=spec, check_vma=False)

        @jax.jit
        def run(xs, reps):
            return jnp.sum(inner(xs, reps))

        n1, n2 = 8, 40
        float(jax.device_get(run(x, jnp.int32(n1))))  # compile + warm

        def t_of(reps):
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                # the fetch IS the measurement (same rationale as
                # calibrate's timing loop above)
                float(jax.device_get(run(x, jnp.int32(reps))))  # fflint: ok host_sync_in_loop
                ts.append(time.perf_counter() - t0)
            return statistics.median(ts)

        dt = (t_of(n2) - t_of(n1)) / (n2 - n1)
        return max(dt, 1e-9)


_NON_COMPUTE = frozenset({
    OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP, OT.OP_REPARTITION, OT.OP_COMBINE,
    OT.OP_REPLICATE, OT.OP_REDUCTION, OT.OP_FUSED_PARALLEL, OT.OP_PIPELINE,
})


def _op_harness(node):
    """Build (fn, example_args) measuring one op's unsharded forward on the
    local device (the sub-tensor construction of measure_operator_cost,
    linear.cc:792-925, without the MachineView — sharding is applied as a
    flops ratio by op_cost)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..fftype import dtype_to_jnp
    from ..ops.base import OpContext

    rs = np.random.RandomState(0)

    def _make(shape, dtype):
        jt = dtype_to_jnp(dtype)
        if jnp.issubdtype(jt, jnp.integer):
            return jnp.zeros(shape, jt)
        return jnp.asarray(rs.randn(*shape), jt)

    ins = [_make(pt.shape.logical_shape, pt.dtype) for pt in node.inputs]
    weights = {ws.name: _make(ws.shape, ws.dtype)
               for ws in node.weight_specs}
    state = {ws.name: weights[ws.name] for ws in node.weight_specs
             if not ws.trainable}
    trainable = {ws.name: weights[ws.name] for ws in node.weight_specs
                 if ws.trainable}
    ctx = OpContext(training=False, rng=jax.random.key(0))
    params, op_def = node.params, node.op_def

    # trainable weights are the FIRST argument so calibrate can
    # differentiate the op w.r.t. them (dW time dominates many backwards)
    def fn(tw, *arrs):
        outs, _ = op_def.forward(params, list(arrs), {**weights, **tw},
                                 dict(state) if state else None, ctx)
        return outs[0]

    return fn, (trainable,) + tuple(ins)


def _params_key(node, in_shapes=None):
    """Calibration cache key: op params alone don't pin the cost (a
    64→4096 Linear and a 4096→4096 Linear share LinearParams fields), so
    the key includes the unsharded input shapes — the analog of the
    reference caching by (OperatorParameters, MachineView) where the view
    implies the sub-tensor shapes."""
    if in_shapes is None:
        in_shapes = (tuple(pt.shape.logical_shape for pt in node.inputs)
                     if node.inputs else ())
    return (node.op_type, repr(node.params),
            tuple(tuple(s) for s in in_shapes))


# PartitionSpec (or None) → per-dim axis tuples: ONE definition, shared
# with the executor's weight-update placement (parallel/ops) so pricing
# and runtime can never diverge on how a spec reads
from ..parallel.ops import _spec_assignment as _spec_to_assignment  # noqa: E402
