"""Joint Unity search: substitution rewrites × placement DP in ONE optimizer.

This is the reference's actual Unity architecture (the round-3 repo ran the
two halves as an either/or): `GraphSearchHelper::base_optimize`
(substitution.cc:2229-2311) pops candidate graphs from a priority queue,
applies GraphXfer rewrites, and costs every candidate with
`Graph::optimal_cost` (substitution.cc:2253 → graph.cc:1742-1843) — i.e. the
full placement DP runs inside the rewrite search, so rewrites and per-node
placements are optimized together.

TPU recast:
- a rewrite pins the placement of the nodes it touched (their tensors carry
  ParallelDim degrees; `derive_pinned_configs` turns those into pinned
  NodeConfigs, and explicit parallel-op nodes are priced as the collectives
  they lower to);
- every candidate graph is costed by `UnitySearch` over its FREE nodes (the
  placement-DP half), with one `segment_cache` shared across all candidates
  so structurally unchanged segments cost nothing to re-evaluate (the
  reference's memoized graph_cost plays the same role);
- large graphs recurse through sequence splits at central bottleneck nodes
  before the best-first search runs (generic_sequence_optimize,
  substitution.cc:2530+; find_split_node:2094), which bounds wall time on
  bench-scale LMs;
- the winner's placements (pinned + searched) are materialized onto the
  graph tensors, and the searched half is also returned as a Strategy for
  export (--export-strategy).
"""

from __future__ import annotations

import itertools
from typing import Optional

from ..fftype import OperatorType as OT
from ..pcg.graph import Graph, OpNode
from ..tensor import ParallelTensor
from .cost_model import CostModel
from .substitution import (
    _PARALLEL,
    assign_axes_from_degrees,
    _logical_assignment,
    best_first_search,
    generate_all_pcg_xfers,
    load_rule_collection,
    propagate_parallel_state,
)
from .unity import NodeConfig, UnitySearch

_SKIP = (OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP)


def derive_pinned_configs(graph: Graph, mesh) -> dict:
    """{guid -> NodeConfig} for nodes whose placement a rewrite decided.

    Runs assign_axes_from_degrees (the FFMapper analog) so every tensor
    carries its degree-derived axes, then pins:
    - explicit parallel ops ("xfer_comm": priced as collectives),
    - compute ops with any sharded output/weight ("xfer": the rewrite's
      placement, including implied weight PartitionSpecs).
    Nodes with no rewrite-imposed state stay free for the placement DP."""
    assign_axes_from_degrees(graph, mesh)
    pinned: dict = {}
    for node in graph.topo_order():
        if node.op_type in _SKIP:
            continue
        in_assigns = tuple(_logical_assignment(pt) for pt in node.inputs)
        if node.op_type in _PARALLEL:
            pinned[node.guid] = NodeConfig(
                "xfer_comm", _logical_assignment(node.outputs[0]),
                in_assigns=in_assigns)
            continue
        sharded = any(d.degree > 1 for pt in node.outputs
                      for d in pt.shape.dims)
        wp = getattr(node, "_weight_partition", None)
        if sharded or wp:
            pinned[node.guid] = NodeConfig(
                "xfer", _logical_assignment(node.outputs[0]),
                tuple(sorted(node.weight_axes.items(), key=lambda kv: kv[0])),
                in_assigns=in_assigns)
    return pinned


def _joint_cost(g: Graph, mesh, config, cm: CostModel,
                segment_cache: dict):
    """Cost one candidate graph with the placement DP over its free nodes
    (the Graph::optimal_cost call inside base_optimize). Returns
    (penalized cost, choice, UnitySearch)."""
    pinned = derive_pinned_configs(g, mesh)
    us = UnitySearch(g, mesh, config, cm, segment_cache=segment_cache,
                     pinned=pinned, refine=False)
    choice = us.run()
    t, mem = us.evaluate(choice)
    return us._memory_penalized(t, mem), choice, us


def apply_choice_to_graph(g: Graph, mesh, choice: dict):
    """Materialize the searched placements onto the graph tensors (on top
    of the rewrite-derived axes assign_axes_from_degrees already wrote) so
    the executor's with_sharding_constraint pins exactly what the joint
    search costed."""
    assign_axes_from_degrees(g, mesh)
    for node in g.topo_order():
        cfg = choice.get(node.guid)
        if cfg is None or cfg.name in ("xfer", "xfer_comm"):
            continue
        for pt in node.outputs:
            if len(cfg.out_assign) == len(pt.shape.dims):
                pt.assign_axes(cfg.out_assign)
        declared = {ws.name for ws in node.weight_specs}
        for wname, spec in cfg.weight_specs:
            if wname in declared:
                node.weight_axes[wname] = spec


def _compute_size(g: Graph) -> int:
    return sum(1 for n in g.topo_order() if n.op_type not in _SKIP)


def joint_base_optimize(
    graph: Graph,
    mesh,
    config,
    cm: CostModel,
    xfers,
    segment_cache: dict,
    budget: int,
    alpha: float,
):
    """Best-first search over rewritten graphs, each costed by the placement
    DP (base_optimize, substitution.cc:2229-2311, with optimal_cost inlined
    as UnitySearch). Returns (best graph, best choice, best cost)."""

    def cost_of(g: Graph):
        cost, choice, _ = _joint_cost(g, mesh, config, cm, segment_cache)
        return cost, choice

    best_g, best_cost, best_choice = best_first_search(
        graph, xfers, cost_of, budget, alpha)
    return best_g, best_choice, best_cost


# ------------------------------------------------------- sequence splitting

def _find_split_node(g: Graph) -> Optional[OpNode]:
    """Central bottleneck (find_split_node, substitution.cc:2094): the
    bottleneck node nearest the middle of the topo order, excluding the
    sink. Returns None when no usable bottleneck exists."""
    from ..pcg.graph import find_bottlenecks

    order = g.topo_order()
    pos = {n.guid: i for i, n in enumerate(order)}
    usable = [(pos[n.guid], n) for n in find_bottlenecks(g, order)
              if n.op_type not in _SKIP and len(n.outputs) == 1]
    if not usable:
        return None
    mid = len(order) / 2
    i, n = min(usable, key=lambda t: abs(t[0] - mid))
    # a split at the very edge gains nothing
    if i < 2 or i > len(order) - 3:
        return None
    return n


_boundary_counter = itertools.count()


def _clone_basic(graph: Graph, node: OpNode) -> OpNode:
    nn = OpNode(node.op_type, node.params, name=node.name,
                layer_guid=node.layer_guid,
                initializers=node.initializers)
    nn.weight_specs = list(node.weight_specs)
    nn.weight_axes = dict(node.weight_axes)
    src = getattr(node, "weight_source", None)
    if src:
        nn.weight_source = src  # tied weights survive splits by name
    if node.op_type == OT.OP_INPUT:
        nn.outputs = [ParallelTensor(pt.shape, name=pt.name)
                      for pt in node.outputs]
    if getattr(node, "_is_logits", False):
        nn._is_logits = True
    marks = getattr(node, "_markers", None)
    if marks:
        nn._markers = frozenset(marks)
    graph.add_node(nn)
    return nn


def _split_at(g: Graph, split: OpNode) -> tuple[Graph, Graph, OpNode, str]:
    """Cut g at a bottleneck node into (pre, post) subgraphs. `split` stays
    the sink of pre, tagged with a unique boundary token (tokens survive
    rewrites and nested splits, unlike a shared boolean); post gets a
    synthetic OP_INPUT standing in for split's output."""
    order = g.topo_order()
    cut = order.index(split)
    pre_nodes = order[:cut + 1]
    post_nodes = order[cut + 1:]
    token = f"boundary_{next(_boundary_counter)}"

    pre = Graph()
    pre_clone: dict[int, OpNode] = {}
    for n in pre_nodes:
        pre_clone[n.guid] = _clone_basic(pre, n)
    bn = pre_clone[split.guid]
    bn._markers = getattr(bn, "_markers", frozenset()) | {token}
    for n in pre_nodes:
        for e in g.in_edges[n.guid]:
            pre.add_edge(pre_clone[e.src], pre_clone[e.dst],
                         e.src_idx, e.dst_idx)

    post = Graph()
    boundary_in = OpNode(OT.OP_INPUT, None, name=f"{split.name}__boundary")
    boundary_in.outputs = [
        ParallelTensor(split.outputs[0].shape, name=f"{split.name}__b")]
    post.add_node(boundary_in)
    post_clone: dict[int, OpNode] = {split.guid: boundary_in}
    for n in post_nodes:
        post_clone[n.guid] = _clone_basic(post, n)
    for n in post_nodes:
        for e in g.in_edges[n.guid]:
            src = post_clone.get(e.src)
            if src is None:  # crosses the cut from deeper than split:
                # impossible for a bottleneck cut — every path crosses split
                raise ValueError("non-bottleneck split")
            src_idx = 0 if src is boundary_in else e.src_idx
            post.add_edge(src, post_clone[e.dst], src_idx, e.dst_idx)
    # compute-node clones carry no output tensors — rebuild parallel state
    propagate_parallel_state(pre)
    propagate_parallel_state(post)
    return pre, post, boundary_in, token


def _join(pre: Graph, post: Graph, boundary_in: OpNode, token: str) -> Graph:
    """Merge optimized halves back into one graph: post's synthetic input
    collapses onto pre's (possibly rewritten) boundary node, found by its
    token."""
    out = Graph()
    clone: dict[int, OpNode] = {}

    def copy_graph(g: Graph):
        for n in g.topo_order():
            if n is boundary_in:
                continue
            clone[n.guid] = _clone_basic(out, n)
        for n in g.topo_order():
            for e in g.in_edges[n.guid]:
                if g.nodes[e.src] is boundary_in:
                    continue  # rewired below
                out.add_edge(clone[e.src], clone[e.dst],
                             e.src_idx, e.dst_idx)

    copy_graph(pre)
    boundary = next(n for n in pre.topo_order()
                    if token in getattr(n, "_markers", ()))
    copy_graph(post)
    for n in post.topo_order():
        for e in post.in_edges[n.guid]:
            if post.nodes[e.src] is boundary_in:
                out.add_edge(clone[boundary.guid], clone[e.dst],
                             0, e.dst_idx)
    # this split's token is spent; nested splits' tokens stay intact
    bj = clone[boundary.guid]
    bj._markers = getattr(bj, "_markers", frozenset()) - {token}
    # cloned compute nodes carry no output tensors yet — rebuild the whole
    # graph's parallel state (clones of rewritten halves keep their
    # parallel ops, so degrees re-derive identically)
    propagate_parallel_state(out)
    return out


def joint_graph_optimize(
    graph: Graph,
    mesh,
    config,
    cost_model: Optional[CostModel] = None,
    _xfers=None,
    _segment_cache=None,
    _depth: int = 0,
):
    """Entry point: ONE search over rewrites × placements
    (GraphSearchHelper::graph_optimize + graph_optimize_task in one).

    Returns (graph, choice, UnitySearch) — the graph carries materialized
    placements; `us.to_strategy(choice)` gives the exportable searched half.
    Graphs larger than 4× base_optimize_threshold are sequence-split at a
    central bottleneck and the halves optimized independently (reference
    generic_sequence_optimize), with the boundary tensor materialized
    data-parallel — the same boundary-fixing the reference applies."""
    from .machine_model import machine_model_for_mesh

    cm = cost_model or CostModel(machine_model_for_mesh(mesh))
    if _xfers is None:
        if config.substitution_json_path:
            # external rules verify at load (the ffrules gate,
            # analysis/rules.py): an unsound JSON rule raises a
            # structured RuleVerificationError before it can reach the
            # search; --no-verify-rules downgrades to a warning
            _xfers = load_rule_collection(config.substitution_json_path,
                                          mesh, config=config)
        else:
            # built-in registry: swept by scripts/ffrules.py in CI
            _xfers = generate_all_pcg_xfers(mesh, config, graph)  # fflint: ok unverified_rule_load
    cache = _segment_cache if _segment_cache is not None else {}
    budget = config.search_budget or 16
    alpha = config.search_alpha

    split_threshold = max(16, 4 * config.base_optimize_threshold)
    split = (_find_split_node(graph)
             if _compute_size(graph) > split_threshold and _depth < 4
             else None)
    if split is not None:
        # sequence split: rewrite-search each half independently (shared
        # segment cache), join, then cost+refine the whole — the reference
        # stitches segment solutions the same way rather than re-running
        # base_optimize over the joined graph
        pre, post, boundary_in, token = _split_at(graph, split)
        pre, _, _ = joint_graph_optimize(
            pre, mesh, config, cm, _xfers, cache, _depth + 1)
        post, _, _ = joint_graph_optimize(
            post, mesh, config, cm, _xfers, cache, _depth + 1)
        best_g = _join(pre, post, boundary_in, token)
        _, best_choice, _ = _joint_cost(best_g, mesh, config, cm, cache)
    else:
        best_g, best_choice, _ = joint_base_optimize(
            graph, mesh, config, cm, _xfers, cache, budget, alpha)
    # refine only the winner (base_optimize-style single-node moves)
    us = UnitySearch(best_g, mesh, config, cm, segment_cache=cache,
                     pinned=derive_pinned_configs(best_g, mesh))
    best_choice = us._refine(best_choice)
    t, mem = us.evaluate(best_choice)
    best_cost = us._memory_penalized(t, mem)
    if best_g is not graph:
        # guarantee the joint result never loses to the pure placement DP:
        # candidates are ranked unrefined, so a rewrite that wins unrefined
        # can refine worse than the refined original — compare refined vs
        # refined and keep the better (optimal_cost in the reference plays
        # the same role of re-anchoring to the un-rewritten baseline)
        us0 = UnitySearch(graph, mesh, config, cm, segment_cache=cache,
                          pinned=derive_pinned_configs(graph, mesh))
        choice0 = us0.run()
        t0, m0 = us0.evaluate(choice0)
        cost0 = us0._memory_penalized(t0, m0)
        if cost0 < best_cost:
            best_g, best_choice, us = graph, choice0, us0
            best_cost = cost0
    if config.perform_memory_search:
        _, mem_f = us.evaluate(best_choice)
        if mem_f > cm.machine.chip.hbm_bytes:
            # λ binary search over the final graph's placements
            # (shared helper; graph_optimize_task, graph.cc:2056-2131)
            from .unity import lambda_memory_search

            best_choice, us = lambda_memory_search(
                lambda: UnitySearch(
                    best_g, mesh, config, cm, segment_cache=cache,
                    pinned=derive_pinned_configs(best_g, mesh)),
                cm.machine.chip.hbm_bytes)
    apply_choice_to_graph(best_g, mesh, best_choice)
    if _depth == 0:
        # one summary record per top-level search (recursive sequence-split
        # halves report through the shared best_first_search events);
        # guarded so the disabled path pays no extra topo_order()
        from .. import telemetry

        if telemetry.active_session() is not None:
            telemetry.event(
                "search", evals=us.evals, cache_hits=us.cache_hits,
                best_cost_s=best_cost, rewritten=best_g is not graph,
                nodes=len(best_g.topo_order()))
    return best_g, best_choice, us
