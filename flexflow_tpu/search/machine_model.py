"""Analytic TPU machine model: compute roofline + ICI/DCN collectives.

Reference: the MachineModel hierarchy (include/flexflow/simulator.h:212-615 —
SimpleMachineModel's intra/inter bandwidths, EnhancedMachineModel's per-path
congestion, NetworkedMachineModel's topology routing). On TPU the network is
a wraparound torus of uniform ICI links per chip, so the analytic model is
simpler and *more* accurate than the reference's NIC/NVLink approximations:
bandwidth-optimal collectives on a ring/torus have closed-form costs.

Collective costs over an axis of size n with per-chip payload B bytes on a
ring (all links active, bidirectional):
  all_gather / reduce_scatter:  (n-1)/n · B_full / bw      (B_full = n·B out)
  all_reduce:                   2·(n-1)/n · B / bw
  all_to_all:                   (n-1)/n · B / bw           (B = per-chip send)
  ppermute (ring hop):          B / bw
Latency: per-hop α added once per step ((n-1) steps).

Chip specs default to the device JAX reports; numbers are public datasheet
values (bf16 peak, HBM BW, ICI per-link).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float      # bf16 FLOP/s
    hbm_bandwidth: float   # B/s
    hbm_bytes: float       # device memory capacity
    ici_bandwidth: float   # B/s per link direction
    ici_links: int         # torus links per chip
    ici_latency: float = 1e-6
    dcn_bandwidth: float = 25e9 / 8  # per-host, conservative
    dcn_latency: float = 10e-6


CHIPS = {
    "v5e": ChipSpec("v5e", 197e12, 8.1e11, 16e9, 4.5e10, 4),
    "v5p": ChipSpec("v5p", 459e12, 2.765e12, 95e9, 9e10, 6),
    "v4": ChipSpec("v4", 275e12, 1.2e12, 32e9, 4.5e10, 6),
    "v6e": ChipSpec("v6e", 918e12, 1.64e12, 32e9, 9e10, 4),
    "cpu": ChipSpec("cpu", 2e11, 5e10, 32e9, 1e10, 2),
}


def detect_chip() -> ChipSpec:
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "").lower()
        if "v5 lite" in kind or "v5e" in kind:
            return CHIPS["v5e"]
        if "v5" in kind:
            return CHIPS["v5p"]
        if "v4" in kind:
            return CHIPS["v4"]
        if "v6" in kind:
            return CHIPS["v6e"]
        if dev.platform == "cpu":
            return CHIPS["cpu"]
    except Exception:
        pass
    return CHIPS["v5p"]


@dataclass
class TPUMachineModel:
    """Collective cost oracle over the mesh's named axes.

    `axis_links[axis]` = number of physical torus links serving that mesh
    axis (a mesh axis folded over 2 torus dims gets 2× bandwidth); axes that
    span hosts use DCN instead (axis_over_dcn)."""

    chip: ChipSpec
    axis_sizes: dict  # axis name -> size
    axis_links: dict | None = None
    axis_over_dcn: frozenset = frozenset()
    # per-axis effective-bandwidth derating for shared/contended paths —
    # the EnhancedMachineModel congestion knob (simulator.h:279) recast:
    # 1.0 = dedicated links, >1 divides the axis's bandwidth
    axis_congestion: dict | None = None

    def _bw(self, axis: str) -> float:
        cong = (self.axis_congestion or {}).get(axis, 1.0)
        if axis in self.axis_over_dcn:
            return self.chip.dcn_bandwidth / cong
        links = (self.axis_links or {}).get(axis, 1)
        return self.chip.ici_bandwidth * links / cong

    def _lat(self, axis: str) -> float:
        return (self.chip.dcn_latency if axis in self.axis_over_dcn
                else self.chip.ici_latency)

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    def all_gather(self, out_bytes: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return (n - 1) / n * out_bytes / self._bw(axis) + (n - 1) * self._lat(axis)

    def reduce_scatter(self, in_bytes: float, axis: str) -> float:
        return self.all_gather(in_bytes, axis)

    def all_reduce(self, bytes_per_chip: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return (2.0 * (n - 1) / n * bytes_per_chip / self._bw(axis)
                + 2 * (n - 1) * self._lat(axis))

    def all_to_all(self, send_bytes_per_chip: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return ((n - 1) / n * send_bytes_per_chip / self._bw(axis)
                + (n - 1) * self._lat(axis))

    def ppermute(self, bytes_per_chip: float, axis: str) -> float:
        return bytes_per_chip / self._bw(axis) + self._lat(axis)

    def rotate(self, bytes_per_chip: float, axis: str) -> float:
        """One ring-rotation step (every chip shifts to its +1 neighbor,
        INCLUDING the wrap pair) — ring attention's K/V hop. On the uniform
        model this equals ppermute; TorusMachineModel prices the wrap edge
        of a non-wraparound axis as a serialized multi-hop traversal."""
        return self.ppermute(bytes_per_chip, axis)

    def compute_time(self, flops: float, bytes_touched: float) -> float:
        """Roofline: max of MXU time and HBM time (the simulator's measured
        per-op µs analog; see CostModel.calibrate for the measured path)."""
        return max(flops / self.chip.peak_flops,
                   bytes_touched / self.chip.hbm_bandwidth)


@dataclass(frozen=True)
class AxisTopology:
    """Physical shape of one mesh axis on the interconnect.

    The NetworkedMachineModel topology analog (simulator.h:212-615,
    network.cc:1-586 — arbitrary adjacency + ECMP shortest-path routing)
    specialized to what TPU fabrics actually are: each mesh axis maps onto
    one or more torus dimensions (`links` physical links per chip serve the
    axis), each either wrapped (full-pod torus dimension) or open (a
    sub-slice is a mesh, not a torus — no wraparound links). Routing on a
    1-D ring/line is shortest-path by construction, so the ECMP machinery
    reduces to closed forms (see TorusMachineModel)."""

    links: int = 1
    wraparound: bool = True
    over_dcn: bool = False


@dataclass
class TorusMachineModel(TPUMachineModel):
    """Topology-aware collective pricing on a (partial) torus.

    Where TPUMachineModel treats every axis as a uniform abstract pipe,
    this model derives collective costs from the axis's physical topology
    (the NetworkedMachineModel/EnhancedMachineModel analog,
    simulator.h:212-615 + network.cc routing, recast to torus closed forms
    instead of per-packet ECMP simulation):

    - ring collectives (all_gather / reduce_scatter / all_reduce) on a
      WRAPPED axis use both ring directions (half the payload each way):
      2× the effective bandwidth of an open (non-wraparound) axis, where
      the missing wrap link leaves only the one-directional-ring schedule;
    - all_to_all pays minimal-route hop·bytes transit spread over the
      axis's link-directions: mean hop distance n/4 on a wrapped ring vs
      (n²−1)/3n on an open line — long axes without wraparound get
      markedly more expensive, exactly the signal a flat model misses;
    - rotate (ring attention's K/V shift) is one neighbor hop everywhere
      on a wrapped axis, but on an open axis the wrap pair must traverse
      the whole line against traffic: (n−1) serialized hops;
    - DCN axes model per-host NIC fan-in: all `chips_per_host` chips of a
      host issue their cross-slice transfers through ONE shared NIC, so
      per-chip DCN bandwidth divides by the fan-in (the shared-bottleneck
      congestion the reference prices via per-path contention counts,
      machine_model.cc:1-1287).
    """

    topology: dict | None = None       # axis -> AxisTopology
    chips_per_host: int = 1            # DCN NIC fan-in

    def _topo(self, axis: str) -> AxisTopology:
        t = (self.topology or {}).get(axis)
        if t is not None:
            return t
        return AxisTopology(links=(self.axis_links or {}).get(axis, 1),
                            wraparound=True,
                            over_dcn=axis in self.axis_over_dcn)

    def _cong(self, axis: str) -> float:
        return (self.axis_congestion or {}).get(axis, 1.0)

    def _link_bw(self, axis: str) -> float:
        """Per-direction bandwidth × parallel links serving the axis."""
        t = self._topo(axis)
        if t.over_dcn:
            # shared per-host NIC: every chip on the host pushes its own
            # cross-slice stream through it simultaneously
            return self.chip.dcn_bandwidth / (
                max(1, self.chips_per_host) * self._cong(axis))
        return self.chip.ici_bandwidth * t.links / self._cong(axis)

    def _ring_bw(self, axis: str) -> float:
        """Effective ring-schedule bandwidth: a wrapped axis runs the
        bidirectional ring (payload halved each way)."""
        t = self._topo(axis)
        bw = self._link_bw(axis)
        if t.over_dcn:
            return bw  # DCN is switched, not a torus: direction-agnostic
        return bw * (2 if t.wraparound else 1)

    def _lat(self, axis: str) -> float:
        return (self.chip.dcn_latency if self._topo(axis).over_dcn
                else self.chip.ici_latency)

    def all_gather(self, out_bytes: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return ((n - 1) / n * out_bytes / self._ring_bw(axis)
                + (n - 1) * self._lat(axis))

    def all_reduce(self, bytes_per_chip: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return (2.0 * (n - 1) / n * bytes_per_chip / self._ring_bw(axis)
                + 2 * (n - 1) * self._lat(axis))

    def all_to_all(self, send_bytes_per_chip: float, axis: str) -> float:
        """Minimal-route transit: chip i sends B/n to each j over d(i,j)
        hops; total hop·bytes spreads over the axis's link-directions.
        Wrapped ring (even n): Σ_j d(i,j) = n²/4, 2n link-dirs
          → time = B·n / (8·link_bw).
        Open line: Σ_{i,j} d = n(n²−1)/3, 2(n−1) link-dirs
          → time = B·(n+1) / (6·link_bw).
        DCN (switched): every byte leaves the host once — the uniform
        (n−1)/n·B over the fan-in-derated NIC bandwidth."""
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        t = self._topo(axis)
        bw = self._link_bw(axis)
        lat = self._lat(axis)
        if t.over_dcn:
            return (n - 1) / n * send_bytes_per_chip / bw + (n - 1) * lat
        if t.wraparound:
            # total transit B·n²/4 over 2n link-dirs (odd n: (n²−1)/4,
            # folded into the even form — off by <2% at n≥5)
            time = send_bytes_per_chip * n / (8 * bw)
        else:
            time = send_bytes_per_chip * (n + 1) / (6 * bw)
        return time + (n - 1) * lat

    def ppermute(self, bytes_per_chip: float, axis: str) -> float:
        """Neighbor hop (no wrap edge) — pipeline stage hand-off."""
        return bytes_per_chip / self._link_bw(axis) + self._lat(axis)

    def rotate(self, bytes_per_chip: float, axis: str) -> float:
        """Full ring rotation (wrap pair included). On an open axis the
        wrap transfer traverses all n−1 links of the line serially while
        they also carry the neighbor shifts — the whole step is gated by
        that traversal."""
        n = self.axis_size(axis)
        t = self._topo(axis)
        hop = bytes_per_chip / self._link_bw(axis) + self._lat(axis)
        if t.over_dcn or t.wraparound or n <= 2:
            return hop
        return (n - 1) * hop


def machine_model_from_file(path: str, mesh) -> TPUMachineModel:
    """--machine-model-file analog (reference EnhancedMachineModel config,
    simulator.h:279 + --machine-model-file in model.cc): a JSON description
    of the machine overriding the detected chip and topology heuristics.

    Format:
      {"chip": "v5p"                      # name from CHIPS, or an object:
               | {"name": ..., "peak_flops": ..., "hbm_bandwidth": ...,
                  "hbm_bytes": ..., "ici_bandwidth": ..., "ici_links": ...,
                  ["ici_latency", "dcn_bandwidth", "dcn_latency"]},
       "axis_links": {"data": 2, ...},    # torus links per mesh axis (opt)
       "dcn_axes": ["dcn"],               # axes that ride DCN (opt)
       "congestion": {"dcn": 2.0},        # per-axis bandwidth derating
                                          # (EnhancedMachineModel's
                                          # congestion, simulator.h:279)
       "topology": {"data": {"wraparound": false, "links": 2}},
                                          # per-axis physical shape: open
                                          # sub-slice axes vs wrapped torus
                                          # dims (NetworkedMachineModel)
       "chips_per_host": 4}               # DCN NIC fan-in (default: inferred
                                          # from the mesh size / host count)
    """
    import json

    with open(path) as f:
        data = json.load(f)
    chip_cfg = data.get("chip", None)
    if chip_cfg is None:
        chip = detect_chip()
    elif isinstance(chip_cfg, str):
        if chip_cfg not in CHIPS:
            raise ValueError(
                f"machine model file {path}: unknown chip {chip_cfg!r}; "
                f"have {sorted(CHIPS)}")
        chip = CHIPS[chip_cfg]
    else:
        name = chip_cfg.get("name", "custom")
        base = CHIPS.get(name)
        core = ("peak_flops", "hbm_bandwidth", "hbm_bytes",
                "ici_bandwidth", "ici_links")
        if base is None and not all(f in chip_cfg for f in core):
            # unknown base chip: every core field must be spelled out,
            # otherwise a typoed name would silently price against v5p
            missing = [f for f in core if f not in chip_cfg]
            raise ValueError(
                f"machine model file {path}: chip name {name!r} is not a "
                f"known base ({sorted(CHIPS)}) and the spec is missing "
                f"{missing}")
        base = base or CHIPS["v5p"]
        fields = {f: chip_cfg.get(f, getattr(base, f))
                  for f in ("name", "peak_flops", "hbm_bandwidth",
                            "hbm_bytes", "ici_bandwidth", "ici_links",
                            "ici_latency", "dcn_bandwidth", "dcn_latency")}
        fields["name"] = name
        chip = ChipSpec(**fields)
    from ..machine import AXIS_DCN

    axis_sizes = dict(mesh.shape) if hasattr(mesh, "shape") else dict(mesh)
    links = {a: 1 for a in axis_sizes}
    links.update({a: int(v) for a, v in data.get("axis_links", {}).items()
                  if a in links})
    # the canonical dcn axis always rides DCN, with or without a file entry
    # (same auto-marking as machine_model_for_mesh)
    over_dcn = {a for a in data.get("dcn_axes", ()) if a in axis_sizes}
    over_dcn |= {a for a in axis_sizes if a == AXIS_DCN}
    unknown = [a for a in data.get("congestion", {}) if a not in axis_sizes]
    if unknown:
        # a typoed axis name must not silently price as uncongested (same
        # strictness as the unknown-chip check above)
        raise ValueError(
            f"machine model file {path}: congestion axes {unknown} not in "
            f"the mesh (have {sorted(axis_sizes)})")
    congestion = {a: float(v) for a, v in data.get("congestion", {}).items()}
    bad = {a: v for a, v in congestion.items() if v < 1.0}
    if bad:
        # reject rather than silently clamp: a fractional value usually
        # means the user meant link efficiency (the inverse convention)
        raise ValueError(
            f"machine model file {path}: congestion factors must be >= 1 "
            f"(bandwidth derating), got {bad}")
    # "topology": {"axis": {"wraparound": bool, "links": int}} — the
    # NetworkedMachineModel config surface; "chips_per_host" sets the DCN
    # NIC fan-in. Unknown axis names rejected like congestion typos.
    topo_cfg = data.get("topology", {})
    unknown = [a for a in topo_cfg if a not in axis_sizes]
    if unknown:
        raise ValueError(
            f"machine model file {path}: topology axes {unknown} not in "
            f"the mesh (have {sorted(axis_sizes)})")
    topology = {}
    for a in axis_sizes:
        spec = topo_cfg.get(a, {})
        topology[a] = AxisTopology(
            links=int(spec.get("links", links.get(a, 1))),
            wraparound=bool(spec.get("wraparound", a not in over_dcn)),
            over_dcn=a in over_dcn)
    if "chips_per_host" in data:
        chips_per_host = max(1, int(data["chips_per_host"]))
    else:
        # infer like machine_model_for_mesh: chips ÷ hosts (hosts = product
        # of the DCN axes) — a file supplied just to tweak congestion must
        # not silently drop the NIC fan-in derating
        total = hosts = 1
        for a, v in axis_sizes.items():
            total *= v
            if a in over_dcn:
                hosts *= v
        chips_per_host = max(1, total // hosts) if hosts > 1 else 1
    return TorusMachineModel(
        chip, axis_sizes, links, frozenset(over_dcn), congestion or None,
        topology=topology, chips_per_host=chips_per_host)


def machine_model_for_mesh(mesh, chip: ChipSpec | None = None,
                           num_hosts: int = 1) -> TorusMachineModel:
    from ..machine import AXIS_DCN

    chip = chip or detect_chip()
    axis_sizes = dict(mesh.shape) if hasattr(mesh, "shape") else dict(mesh)
    # collectives on the dedicated DCN axis (multi-host meshes lead with
    # it, machine.MULTIHOST_AXES) cross the data-center network
    over_dcn = {a for a in axis_sizes if a == AXIS_DCN}
    if num_hosts > 1 and not over_dcn and axis_sizes:
        # legacy spelling: a multi-host run without an explicit dcn axis —
        # the outermost axis spans hosts
        over_dcn.add(next(iter(axis_sizes)))
    # heuristic: the largest ICI axis gets folded over 2 torus dims when
    # the chip has >4 links (v5p 3D torus)
    links = {a: 1 for a in axis_sizes}
    ici_axes = [a for a in axis_sizes if a not in over_dcn]
    if chip.ici_links >= 6 and ici_axes:
        big = max(ici_axes, key=lambda a: axis_sizes[a])
        links[big] = 2
    # default topology: ICI axes are wrapped torus dimensions (full-pod
    # slices wrap; declare open sub-slice axes via --machine-model-file),
    # the DCN NIC is shared by every chip of a host
    topology = {a: AxisTopology(links=links[a], wraparound=a not in over_dcn,
                                over_dcn=a in over_dcn)
                for a in axis_sizes}
    total = 1
    for v in axis_sizes.values():
        total *= v
    chips_per_host = max(1, total // max(1, num_hosts)) if num_hosts > 1 else 1
    return TorusMachineModel(chip, axis_sizes, links, frozenset(over_dcn),
                             topology=topology,
                             chips_per_host=chips_per_host)
