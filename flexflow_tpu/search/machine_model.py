"""Analytic TPU machine model: compute roofline + ICI/DCN collectives.

Reference: the MachineModel hierarchy (include/flexflow/simulator.h:212-615 —
SimpleMachineModel's intra/inter bandwidths, EnhancedMachineModel's per-path
congestion, NetworkedMachineModel's topology routing). On TPU the network is
a wraparound torus of uniform ICI links per chip, so the analytic model is
simpler and *more* accurate than the reference's NIC/NVLink approximations:
bandwidth-optimal collectives on a ring/torus have closed-form costs.

Collective costs over an axis of size n with per-chip payload B bytes on a
ring (all links active, bidirectional):
  all_gather / reduce_scatter:  (n-1)/n · B_full / bw      (B_full = n·B out)
  all_reduce:                   2·(n-1)/n · B / bw
  all_to_all:                   (n-1)/n · B / bw           (B = per-chip send)
  ppermute (ring hop):          B / bw
Latency: per-hop α added once per step ((n-1) steps).

Chip specs default to the device JAX reports; numbers are public datasheet
values (bf16 peak, HBM BW, ICI per-link).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float      # bf16 FLOP/s
    hbm_bandwidth: float   # B/s
    hbm_bytes: float       # device memory capacity
    ici_bandwidth: float   # B/s per link direction
    ici_links: int         # torus links per chip
    ici_latency: float = 1e-6
    dcn_bandwidth: float = 25e9 / 8  # per-host, conservative
    dcn_latency: float = 10e-6


CHIPS = {
    "v5e": ChipSpec("v5e", 197e12, 8.1e11, 16e9, 4.5e10, 4),
    "v5p": ChipSpec("v5p", 459e12, 2.765e12, 95e9, 9e10, 6),
    "v4": ChipSpec("v4", 275e12, 1.2e12, 32e9, 4.5e10, 6),
    "v6e": ChipSpec("v6e", 918e12, 1.64e12, 32e9, 9e10, 4),
    "cpu": ChipSpec("cpu", 2e11, 5e10, 32e9, 1e10, 2),
}


def detect_chip() -> ChipSpec:
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "").lower()
        if "v5 lite" in kind or "v5e" in kind:
            return CHIPS["v5e"]
        if "v5" in kind:
            return CHIPS["v5p"]
        if "v4" in kind:
            return CHIPS["v4"]
        if "v6" in kind:
            return CHIPS["v6e"]
        if dev.platform == "cpu":
            return CHIPS["cpu"]
    except Exception:
        pass
    return CHIPS["v5p"]


@dataclass
class TPUMachineModel:
    """Collective cost oracle over the mesh's named axes.

    `axis_links[axis]` = number of physical torus links serving that mesh
    axis (a mesh axis folded over 2 torus dims gets 2× bandwidth); axes that
    span hosts use DCN instead (axis_over_dcn)."""

    chip: ChipSpec
    axis_sizes: dict  # axis name -> size
    axis_links: dict | None = None
    axis_over_dcn: frozenset = frozenset()
    # per-axis effective-bandwidth derating for shared/contended paths —
    # the EnhancedMachineModel congestion knob (simulator.h:279) recast:
    # 1.0 = dedicated links, >1 divides the axis's bandwidth
    axis_congestion: dict | None = None

    def _bw(self, axis: str) -> float:
        cong = (self.axis_congestion or {}).get(axis, 1.0)
        if axis in self.axis_over_dcn:
            return self.chip.dcn_bandwidth / cong
        links = (self.axis_links or {}).get(axis, 1)
        return self.chip.ici_bandwidth * links / cong

    def _lat(self, axis: str) -> float:
        return (self.chip.dcn_latency if axis in self.axis_over_dcn
                else self.chip.ici_latency)

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    def all_gather(self, out_bytes: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return (n - 1) / n * out_bytes / self._bw(axis) + (n - 1) * self._lat(axis)

    def reduce_scatter(self, in_bytes: float, axis: str) -> float:
        return self.all_gather(in_bytes, axis)

    def all_reduce(self, bytes_per_chip: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return (2.0 * (n - 1) / n * bytes_per_chip / self._bw(axis)
                + 2 * (n - 1) * self._lat(axis))

    def all_to_all(self, send_bytes_per_chip: float, axis: str) -> float:
        n = self.axis_size(axis)
        if n <= 1:
            return 0.0
        return ((n - 1) / n * send_bytes_per_chip / self._bw(axis)
                + (n - 1) * self._lat(axis))

    def ppermute(self, bytes_per_chip: float, axis: str) -> float:
        return bytes_per_chip / self._bw(axis) + self._lat(axis)

    def compute_time(self, flops: float, bytes_touched: float) -> float:
        """Roofline: max of MXU time and HBM time (the simulator's measured
        per-op µs analog; see CostModel.calibrate for the measured path)."""
        return max(flops / self.chip.peak_flops,
                   bytes_touched / self.chip.hbm_bandwidth)


def machine_model_from_file(path: str, mesh) -> TPUMachineModel:
    """--machine-model-file analog (reference EnhancedMachineModel config,
    simulator.h:279 + --machine-model-file in model.cc): a JSON description
    of the machine overriding the detected chip and topology heuristics.

    Format:
      {"chip": "v5p"                      # name from CHIPS, or an object:
               | {"name": ..., "peak_flops": ..., "hbm_bandwidth": ...,
                  "hbm_bytes": ..., "ici_bandwidth": ..., "ici_links": ...,
                  ["ici_latency", "dcn_bandwidth", "dcn_latency"]},
       "axis_links": {"data": 2, ...},    # torus links per mesh axis (opt)
       "dcn_axes": ["dcn"],               # axes that ride DCN (opt)
       "congestion": {"dcn": 2.0}}        # per-axis bandwidth derating
                                          # (EnhancedMachineModel's
                                          # congestion, simulator.h:279)
    """
    import json

    with open(path) as f:
        data = json.load(f)
    chip_cfg = data.get("chip", None)
    if chip_cfg is None:
        chip = detect_chip()
    elif isinstance(chip_cfg, str):
        if chip_cfg not in CHIPS:
            raise ValueError(
                f"machine model file {path}: unknown chip {chip_cfg!r}; "
                f"have {sorted(CHIPS)}")
        chip = CHIPS[chip_cfg]
    else:
        name = chip_cfg.get("name", "custom")
        base = CHIPS.get(name)
        core = ("peak_flops", "hbm_bandwidth", "hbm_bytes",
                "ici_bandwidth", "ici_links")
        if base is None and not all(f in chip_cfg for f in core):
            # unknown base chip: every core field must be spelled out,
            # otherwise a typoed name would silently price against v5p
            missing = [f for f in core if f not in chip_cfg]
            raise ValueError(
                f"machine model file {path}: chip name {name!r} is not a "
                f"known base ({sorted(CHIPS)}) and the spec is missing "
                f"{missing}")
        base = base or CHIPS["v5p"]
        fields = {f: chip_cfg.get(f, getattr(base, f))
                  for f in ("name", "peak_flops", "hbm_bandwidth",
                            "hbm_bytes", "ici_bandwidth", "ici_links",
                            "ici_latency", "dcn_bandwidth", "dcn_latency")}
        fields["name"] = name
        chip = ChipSpec(**fields)
    from ..machine import AXIS_DCN

    axis_sizes = dict(mesh.shape) if hasattr(mesh, "shape") else dict(mesh)
    links = {a: 1 for a in axis_sizes}
    links.update({a: int(v) for a, v in data.get("axis_links", {}).items()
                  if a in links})
    # the canonical dcn axis always rides DCN, with or without a file entry
    # (same auto-marking as machine_model_for_mesh)
    over_dcn = {a for a in data.get("dcn_axes", ()) if a in axis_sizes}
    over_dcn |= {a for a in axis_sizes if a == AXIS_DCN}
    unknown = [a for a in data.get("congestion", {}) if a not in axis_sizes]
    if unknown:
        # a typoed axis name must not silently price as uncongested (same
        # strictness as the unknown-chip check above)
        raise ValueError(
            f"machine model file {path}: congestion axes {unknown} not in "
            f"the mesh (have {sorted(axis_sizes)})")
    congestion = {a: float(v) for a, v in data.get("congestion", {}).items()}
    bad = {a: v for a, v in congestion.items() if v < 1.0}
    if bad:
        # reject rather than silently clamp: a fractional value usually
        # means the user meant link efficiency (the inverse convention)
        raise ValueError(
            f"machine model file {path}: congestion factors must be >= 1 "
            f"(bandwidth derating), got {bad}")
    return TPUMachineModel(chip, axis_sizes, links, frozenset(over_dcn),
                           congestion or None)


def machine_model_for_mesh(mesh, chip: ChipSpec | None = None,
                           num_hosts: int = 1) -> TPUMachineModel:
    from ..machine import AXIS_DCN

    chip = chip or detect_chip()
    axis_sizes = dict(mesh.shape) if hasattr(mesh, "shape") else dict(mesh)
    # collectives on the dedicated DCN axis (multi-host meshes lead with
    # it, machine.MULTIHOST_AXES) cross the data-center network
    over_dcn = {a for a in axis_sizes if a == AXIS_DCN}
    if num_hosts > 1 and not over_dcn and axis_sizes:
        # legacy spelling: a multi-host run without an explicit dcn axis —
        # the outermost axis spans hosts
        over_dcn.add(next(iter(axis_sizes)))
    # heuristic: the largest ICI axis gets folded over 2 torus dims when
    # the chip has >4 links (v5p 3D torus)
    links = {a: 1 for a in axis_sizes}
    ici_axes = [a for a in axis_sizes if a not in over_dcn]
    if chip.ici_links >= 6 and ici_axes:
        big = max(ici_axes, key=lambda a: axis_sizes[a])
        links[big] = 2
    return TPUMachineModel(chip, axis_sizes, links, frozenset(over_dcn))
