"""Mesh factorization search — the machine-view *grid-shape* half of Unity.

The reference's search ranges over MachineViews: device sub-grids of any
shape, so an op can be split 2-way on an 8-GPU machine simply by taking a
2-device view (graph.cc's view enumeration over numNodes × workersPerNode,
substitution.cc:1726-1868 instantiates rewrites per divisor degree). Under
GSPMD a dim shards over WHOLE named mesh axes, so intermediate degrees are
reached the TPU way: by re-factorizing the global device mesh itself —
8 chips = (data 8) | (data 4, model 2) | (data 2, model 4) | (model 8) | …

This module enumerates the factorizations of the chip count over the named
axes, runs the joint rewrite × placement search (`joint_graph_optimize`)
on each candidate mesh, and returns the best. Together with the per-axis /
composite-axis rewrite instantiation in `generate_all_pcg_xfers`, every
divisor of the chip count is expressible on some candidate, closing the
divisor-degree gap a fixed mesh leaves open.

Enabled with --search-mesh-shapes (consumed by FFModel.compile)."""

from __future__ import annotations

from typing import Optional

from ..machine import AXIS_DATA, AXIS_MODEL
from .cost_model import CostModel
from .machine_model import machine_model_for_mesh


class MeshSpec:
    """Shape-only stand-in for jax.sharding.Mesh during costing (the search
    stack only reads `.shape`); `build_mesh` materializes the winner."""

    def __init__(self, sizes: dict):
        self.shape = dict(sizes)

    def __repr__(self):
        return f"MeshSpec({self.shape})"


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_factorizations(n_devices: int,
                             axes: tuple = (AXIS_DATA, AXIS_MODEL)
                             ) -> list[dict]:
    """All ordered factorizations of the chip count over `axes` (size-1
    axes allowed — (data=8, model=1) is plain DP)."""
    if not axes:
        return [{}] if n_devices == 1 else []
    out = []
    for d in _divisors(n_devices):
        for rest in enumerate_factorizations(n_devices // d, axes[1:]):
            out.append({axes[0]: d, **rest})
    return out


def clone_graph(graph):
    """Deep-copy a PCG (nodes + edges + weight metadata) so each candidate
    mesh's rewrite search mutates its own copy."""
    from ..pcg.graph import Graph
    from .joint import _clone_basic
    from .substitution import propagate_parallel_state

    out = Graph()
    clone = {}
    for n in graph.topo_order():
        clone[n.guid] = _clone_basic(out, n)
    for n in graph.topo_order():
        for e in graph.in_edges[n.guid]:
            out.add_edge(clone[e.src], clone[e.dst], e.src_idx, e.dst_idx)
    propagate_parallel_state(out)
    return out


def search_mesh_shapes(
    graph,
    n_devices: int,
    config,
    axes: tuple = (AXIS_DATA, AXIS_MODEL),
    chip=None,
    num_hosts: int = 1,
    calibrated: Optional[CostModel] = None,
    machine_factory=None,
):
    """Run the joint search once per mesh factorization; return
    (best_shape_dict, best_graph, best_choice, best_search, results) where
    `results` is [(shape_dict, cost), ...] for every candidate (the
    unity_vs_dp-style artifact). The input graph is never mutated.

    A calibrated CostModel's measurements transfer across candidates (they
    are per-op, mesh-independent), but each candidate needs its own machine
    model — pass `calibrated` to reuse measurements; its machine is
    replaced per candidate. `machine_factory(mesh) -> TPUMachineModel`
    overrides the analytic default (e.g. machine_model_from_file, so the
    file's topology/congestion fidelity survives the shape search)."""
    from .. import telemetry
    from .joint import joint_graph_optimize

    best = None
    results = []
    skipped: list = []
    for sizes in enumerate_factorizations(n_devices, axes):
        mesh = MeshSpec(sizes)
        machine = (machine_factory(mesh) if machine_factory is not None
                   else machine_model_for_mesh(mesh, chip=chip,
                                               num_hosts=num_hosts))
        cm = CostModel(machine,
                       opt_slots=(calibrated.opt_slots if calibrated else 1))
        if calibrated is not None:
            cm._calibration = calibrated._calibration
        g = clone_graph(graph)
        shape_label = ",".join(f"{a}={d}" for a, d in sizes.items())
        try:
            with telemetry.span("mesh_search.candidate", shape=shape_label):
                g, choice, us = joint_graph_optimize(g, mesh, config, cm)
        except ValueError as e:
            # a factorization the graph cannot shard onto (e.g. batch not
            # divisible): skip it rather than abort the search — but keep
            # the reason, so an every-candidate failure (a search bug, not
            # an unshardable graph) surfaces with diagnostics
            skipped.append((dict(sizes), str(e)))
            telemetry.event("mesh_candidate", shape=dict(sizes),
                            skipped=str(e))
            continue
        t, mem = us.evaluate(choice)
        cost = us._memory_penalized(t, mem)
        results.append((dict(sizes), cost))
        if best is None or cost < best[4]:
            best = (dict(sizes), g, choice, us, cost)
        # per-candidate record: cost + running best — the mesh-shape half
        # of the best-cost-so-far curve
        telemetry.event("mesh_candidate", shape=dict(sizes), cost_s=cost,
                        best_cost_s=best[4], evals=us.evals,
                        cache_hits=us.cache_hits)
        telemetry.counter("mesh_search.best_cost_ms",
                          {"cost": best[4] * 1e3})
    if best is None:
        detail = "; ".join(f"{s}: {r}" for s, r in skipped[:4])
        raise ValueError(
            f"no mesh factorization of {n_devices} devices over {axes} "
            f"admits this graph — per-candidate reasons: {detail}")
    shape, g, choice, us, _ = best
    return shape, g, choice, us, results
