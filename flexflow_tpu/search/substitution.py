"""GraphXfer substitution engine: PCG rewrites that insert/remove parallel ops.

Reference: src/runtime/substitution.cc — TASO-style rewrite rules where a
source pattern of `OpX` nodes (with `TensorX` symbolic tensors) is replaced by
a target pattern, discovered by a backtracking matcher (`find_matches`, :510)
and applied by graph reconstruction (`create_new_graph`, :782); ~30 hand-coded
generators build the rule set (generate_all_pcg_xfers, :1726-1868) and a JSON
loader adds external rules (substitution_loader.cc); `base_optimize`
(:2229-2311) explores rewritten graphs best-first under a budget with alpha
pruning and graph-hash dedup.

TPU-native recast: rewrites operate on our PCG (pcg/graph.py) and insert
explicit Repartition/Combine/Replicate/Reduction nodes
(parallel/ops.apply_parallel_op_shape is the per-node shape transform). One
deliberate divergence from the reference's mechanics: compute-op params stay
GLOBAL after a rewrite (the reference rewrites attention to num_heads/k per
device; under GSPMD the op keeps global heads and the sharding lives in the
tensors' ParallelDim degrees + weight PartitionSpecs, which the executor pins
— XLA then partitions the op). `propagate_parallel_state` is the
solve_parallel_dim_mappings analog: it re-derives every tensor's degrees and
every op's implied weight shardings from the inserted parallel ops.
"""

from __future__ import annotations

import heapq
import itertools
import json
from dataclasses import dataclass, replace
from typing import Any, Callable, Optional

from jax.sharding import PartitionSpec

from ..fftype import ActiMode, OperatorType as OT, PARALLEL_OP_TYPES
from ..machine import AXIS_DATA, AXIS_MODEL
from ..parallel.ops import (
    CombineParams,
    ReductionParams,
    RepartitionParams,
    ReplicateParams,
    apply_parallel_op_shape,
)
from ..pcg.graph import Graph, OpNode, is_expert_buffer
from ..tensor import ParallelDim, ParallelTensor, ParallelTensorShape
from .cost_model import CostModel, price_parallel_node

# --------------------------------------------------------------------- pattern


@dataclass(frozen=True)
class TensorX:
    """Symbolic tensor: output `idx` of pattern op `op`, or (op=None) the
    xfer's free input slot `idx` (reference TensorX, substitution.h)."""

    op: Optional["OpX"] = None
    idx: int = 0


class OpX:
    """One pattern/replacement operator (reference OpX).

    Source-side: `op_type` + `constraints` (predicates on the matched
    OpNode) define what matches. Dest-side: `match_src` names the source OpX
    whose params/name/weights the new node inherits (the reference's
    matchOpX), or `make_params` builds fresh params (parallel ops)."""

    def __init__(
        self,
        op_type: OT,
        inputs: tuple[TensorX, ...] = (),
        num_outputs: int = 1,
        constraints: tuple[Callable[[OpNode], bool], ...] = (),
        match_src: Optional["OpX"] = None,
        make_params: Optional[Callable[[dict], Any]] = None,
    ):
        self.op_type = op_type
        self.inputs = tuple(inputs)
        self.outputs = tuple(TensorX(self, i) for i in range(num_outputs))
        self.constraints = tuple(constraints)
        self.match_src = match_src
        self.make_params = make_params


@dataclass
class Match:
    """One pattern occurrence: pattern op → graph node, free input slot →
    (producer guid, out idx) — or (None, input-node guid) for graph sources."""

    ops: dict  # OpX -> OpNode
    inputs: dict  # slot idx -> (guid, out_idx)


class GraphXfer:
    """A rewrite rule: src pattern → dst pattern (reference GraphXfer)."""

    def __init__(self, name: str):
        self.name = name
        self.src_ops: list[OpX] = []
        self.dst_ops: list[OpX] = []
        # (src TensorX, dst TensorX): external consumers of the src tensor
        # re-point to the dst tensor after the rewrite (map_output)
        self.mapped_outputs: list[tuple[TensorX, TensorX]] = []

    def new_input(self, idx: int) -> TensorX:
        return TensorX(None, idx)

    def map_output(self, src_tx: TensorX, dst_tx: TensorX):
        self.mapped_outputs.append((src_tx, dst_tx))

    # ------------------------------------------------------------- matching

    def find_matches(self, graph: Graph) -> list[Match]:
        """Backtracking pattern match (reference find_matches,
        substitution.cc:510)."""
        matches: list[Match] = []
        order = graph.topo_order()
        self._match_rec(graph, order, 0, Match({}, {}), matches)
        return matches

    def _match_rec(self, graph, order, depth, cur: Match, out: list[Match]):
        if depth == len(self.src_ops):
            if self._check_internal_consumers(graph, cur):
                out.append(Match(dict(cur.ops), dict(cur.inputs)))
            return
        px = self.src_ops[depth]
        for node in order:
            if node.op_type != px.op_type or node in cur.ops.values():
                continue
            if not all(c(node) for c in px.constraints):
                continue
            edges = sorted(graph.in_edges[node.guid], key=lambda e: e.dst_idx)
            if len(edges) < len(px.inputs):
                continue
            binding_inputs = dict(cur.inputs)
            ok = True
            for i, tx in enumerate(px.inputs):
                e = edges[i]
                src = (e.src, e.src_idx)
                if tx.op is not None:  # must come from an earlier matched op
                    want = cur.ops.get(tx.op)
                    if want is None or want.guid != e.src or tx.idx != e.src_idx:
                        ok = False
                        break
                else:  # free input slot: bind or check consistency
                    bound = binding_inputs.get(tx.idx)
                    if bound is None:
                        binding_inputs[tx.idx] = src
                    elif bound != src:
                        ok = False
                        break
            if not ok:
                continue
            cur.ops[px] = node
            saved = cur.inputs
            cur.inputs = binding_inputs
            self._match_rec(graph, order, depth + 1, cur, out)
            cur.inputs = saved
            del cur.ops[px]

    def _check_internal_consumers(self, graph, m: Match) -> bool:
        """Non-mapped outputs of matched ops must have no consumers outside
        the match (else the rewrite would orphan them)."""
        matched = {n.guid for n in m.ops.values()}
        mapped = set()
        for src_tx, _ in self.mapped_outputs:
            node = m.ops[src_tx.op]
            mapped.add((node.guid, src_tx.idx))
        for px, node in m.ops.items():
            for e in graph.out_edges[node.guid]:
                if (node.guid, e.src_idx) in mapped:
                    continue
                if e.dst not in matched:
                    return False
        return True

    # -------------------------------------------------------------- rewrite

    def apply(self, graph: Graph, m: Match) -> Graph:
        """Build the rewritten graph (reference create_new_graph,
        substitution.cc:782). Raises ValueError when the rewritten parallel
        state is inconsistent (invalid candidate — caller discards)."""
        new_g = Graph()
        matched = {n.guid for n in m.ops.values()}
        clone: dict[int, OpNode] = {}
        for node in graph.topo_order():
            if node.guid in matched:
                continue
            clone[node.guid] = _clone_node(new_g, node)
        # instantiate dst ops
        dst_node: dict[OpX, OpNode] = {}
        for dx in self.dst_ops:
            if dx.match_src is not None:
                src_node = m.ops[dx.match_src]
                params = (dx.make_params(m.ops) if dx.make_params
                          else src_node.params)
                n = OpNode(dx.op_type, params, name=src_node.name,
                           layer_guid=src_node.layer_guid,
                           initializers=src_node.initializers)
                n.weight_specs = list(src_node.weight_specs)
                wsrc = getattr(src_node, "weight_source", None)
                if wsrc:
                    n.weight_source = wsrc  # tied weights survive rewrites
            else:
                params = dx.make_params(m.ops) if dx.make_params else None
                n = OpNode(dx.op_type, params)
            new_g.add_node(n)
            dst_node[dx] = n

        def resolve(tx: TensorX) -> tuple[OpNode, int]:
            if tx.op is None:
                guid, idx = m.inputs[tx.idx]
                return clone[guid], idx
            if tx.op in dst_node:
                return dst_node[tx.op], tx.idx
            raise ValueError(f"dangling TensorX in xfer {self.name}")

        # wire dst-op inputs
        for dx in self.dst_ops:
            n = dst_node[dx]
            for dst_idx, tx in enumerate(dx.inputs):
                src_n, src_idx = resolve(tx)
                new_g.add_edge(src_n, n, src_idx, dst_idx)
        # wire edges among unmatched nodes + re-point mapped outputs
        mapped = {}
        for src_tx, dst_tx in self.mapped_outputs:
            node = m.ops[src_tx.op]
            mapped[(node.guid, src_tx.idx)] = resolve(dst_tx)
        for node in graph.topo_order():
            for e in graph.out_edges[node.guid]:
                if e.dst in matched:
                    continue
                if node.guid in matched:
                    src_n, src_idx = mapped[(e.src, e.src_idx)]
                else:
                    src_n, src_idx = clone[e.src], e.src_idx
                new_g.add_edge(src_n, clone[e.dst], src_idx, e.dst_idx)
        # carry node markers through the rewrite so compile (logits) and the
        # joint search's sequence splitter (boundary tokens) can find their
        # nodes after arbitrary rewrites
        for node in graph.topo_order():
            logits = getattr(node, "_is_logits", False)
            marks = getattr(node, "_markers", None)
            if not logits and not marks:
                continue
            if node.guid in matched:
                nn = mapped.get((node.guid, 0), (None, 0))[0]
            else:
                nn = clone[node.guid]
            if nn is None:
                continue
            if logits:
                nn._is_logits = True
            if marks:
                nn._markers = getattr(nn, "_markers", frozenset()) | marks
        propagate_parallel_state(new_g)
        # dst compute ops built fresh (no match_src, e.g. the fused Experts
        # node) declare their weights from the propagated input shapes; the
        # reference rebuilds operators from the rewritten PCG the same way
        # (model.cc:2830-2872)
        for dx, n in dst_node.items():
            if n.weight_specs or n.op_type in _PARALLEL:
                continue
            try:
                in_shapes = [pt.shape.logical_shape for pt in n.inputs]
                n.weight_specs = n.op_def.weights(n.params, in_shapes)
            except NotImplementedError:
                pass
        return new_g


def _clone_node(g: Graph, node: OpNode) -> OpNode:
    n = OpNode(node.op_type, node.params, name=node.name,
               layer_guid=node.layer_guid, initializers=node.initializers)
    n.weight_specs = list(node.weight_specs)
    n.weight_axes = dict(node.weight_axes)
    src = getattr(node, "weight_source", None)
    if src:
        n.weight_source = src  # tied weights survive rewrites by name
    if node.op_type == OT.OP_INPUT:
        # input nodes keep their ParallelTensor shape (degree-1 source)
        n.outputs = [ParallelTensor(pt.shape, name=pt.name)
                     for pt in node.outputs]
    g.add_node(n)
    return n


# ------------------------------------------------- parallel-state propagation

_PASSTHROUGH = frozenset({
    OT.OP_RELU, OT.OP_GELU, OT.OP_SIGMOID, OT.OP_TANH, OT.OP_ELU,
    OT.OP_IDENTITY, OT.OP_DROPOUT, OT.OP_SCALAR_MULTIPLY, OT.OP_SCALAR_ADD,
    OT.OP_SCALAR_SUB, OT.OP_SCALAR_TRUE_DIV, OT.OP_EXP, OT.OP_SIN, OT.OP_COS,
    OT.OP_RSQRT, OT.OP_POW, OT.OP_LAYERNORM, OT.OP_SOFTMAX, OT.OP_CAST,
})

# single source of truth for the parallel-op type set (also used by
# OpNode.is_parallel_op and UnitySearch.evaluate)
_PARALLEL = PARALLEL_OP_TYPES

# Ops that commute with summation: f(sum_i x_i) == sum_i f(x_i). Only these
# may pass a partial-sum replica dim (row-parallel Linear/MHA output)
# through unchanged; relu(partial sums) != partial(relu).
_LINEAR_SAFE = frozenset({
    OT.OP_IDENTITY, OT.OP_CAST, OT.OP_SCALAR_MULTIPLY,
    OT.OP_SCALAR_TRUE_DIV,
})


def propagate_parallel_state(graph: Graph):
    """Re-derive every tensor's ParallelDim degrees and every compute op's
    implied weight shardings from the graph's explicit parallel ops — the
    solve_parallel_dim_mappings analog (reference operator.cc /
    ParallelDimMappingRecord). Raises ValueError on inconsistent state,
    including a partial-sum replica dim flowing through a nonlinear op
    (such a candidate would be mathematically invalid)."""
    # (guid, out_idx) -> True when the tensor's replica dim holds PARTIAL
    # SUMS (row-parallel Linear / head-parallel MHA output) rather than
    # identical copies (Replicate output)
    partial: dict[tuple[int, int], bool] = {}
    for node in graph.topo_order():
        if node.op_type == OT.OP_INPUT:
            if not node.outputs:
                raise ValueError(f"input node {node.name} has no tensor")
            node.inputs = []
            continue
        in_pts: list[ParallelTensor] = []
        in_edges = sorted(graph.in_edges[node.guid], key=lambda e: e.dst_idx)
        for e in in_edges:
            in_pts.append(graph.nodes[e.src].outputs[e.src_idx])
        node.inputs = in_pts
        in_partial = [partial.get((e.src, e.src_idx), False)
                      for e in in_edges]
        in_shapes = [pt.shape for pt in in_pts]
        weight_partition: dict[str, tuple[int, int]] = {}
        out_partial = False

        if node.op_type in _PARALLEL:
            out_shapes = [apply_parallel_op_shape(
                in_shapes[0], node.op_type, node.params)]
            # Reduction consumes partial sums; the others re-place values.
            # A FusedParallelOp is checked per sub-op so a fused Reduction
            # can't bypass the identical-replica check.
            sub_types = ([i.op_type for i in node.params.ops]
                         if node.op_type == OT.OP_FUSED_PARALLEL
                         else [node.op_type])
            cur = in_partial[0] if in_partial else False
            for st in sub_types:
                if st == OT.OP_REDUCTION:
                    if not cur:
                        raise ValueError(
                            f"{node.name}: Reduction over identical "
                            f"replicas would multiply values by the degree")
                    cur = False
            out_partial = cur
        elif node.op_type == OT.OP_LINEAR:
            if any(in_partial):
                raise ValueError(
                    f"{node.name}: Linear consuming a partial-sum tensor "
                    f"is unsupported (bias would be added per replica)")
            out_shapes = [_linear_parallel(node, in_shapes[0],
                                           weight_partition)]
            out_partial = any(d.is_replica_dim for d in out_shapes[0].dims)
        elif node.op_type == OT.OP_MULTIHEAD_ATTENTION:
            if any(in_partial):
                raise ValueError(
                    f"{node.name}: attention over partial sums is invalid "
                    f"(softmax is nonlinear)")
            out_shapes = [_attention_parallel(node, in_shapes,
                                              weight_partition)]
            out_partial = any(d.is_replica_dim for d in out_shapes[0].dims)
        elif node.op_type == OT.OP_CONV2D:
            if any(in_partial):
                raise ValueError(
                    f"{node.name}: Conv2D consuming a partial-sum tensor "
                    f"is unsupported (bias/activation per replica)")
            out_shapes = [_conv_parallel(node, in_shapes[0],
                                         weight_partition)]
            out_partial = any(d.is_replica_dim for d in out_shapes[0].dims)
        elif node.op_type == OT.OP_EMBEDDING:
            if any(in_partial):
                raise ValueError(
                    f"{node.name}: embedding lookup over partial-sum "
                    f"indices is meaningless")
            out_shapes = [_embedding_parallel(node, in_shapes[0],
                                              weight_partition)]
        elif node.op_type in _PASSTHROUGH:
            if in_partial and in_partial[0] and \
                    node.op_type not in _LINEAR_SAFE:
                raise ValueError(
                    f"{node.name} ({node.op_type.name}) is nonlinear and "
                    f"cannot consume a partial-sum replica dim: "
                    f"f(sum x_i) != sum f(x_i)")
            if node.op_type == OT.OP_CAST:
                # a cast changes the VALUE dtype: the IR must carry the
                # target dtype or the ffrules/ffsan dtype-transfer checks
                # would see the stale input dtype
                out_shapes = [ParallelTensorShape(in_shapes[0].dims,
                                                  node.params.dtype)]
            else:
                out_shapes = [in_shapes[0]]
            out_partial = in_partial[0] if in_partial else False
        elif node.op_type in (OT.OP_EW_ADD, OT.OP_EW_SUB, OT.OP_EW_MUL,
                              OT.OP_EW_DIV, OT.OP_EW_MAX, OT.OP_EW_MIN):
            if in_shapes[0].dims != in_shapes[1].dims:
                raise ValueError(
                    f"{node.name}: element-binary operands have different "
                    f"parallel shapes {in_shapes[0]} vs {in_shapes[1]}")
            if any(in_partial):
                # add/sub of two partials distributes over the sum; any
                # other combination (mixed partial/full, nonlinear binop)
                # does not
                if not (all(in_partial) and node.op_type in
                        (OT.OP_EW_ADD, OT.OP_EW_SUB)):
                    raise ValueError(
                        f"{node.name} ({node.op_type.name}): invalid "
                        f"combination of partial-sum operands")
                out_partial = True
            out_shapes = [in_shapes[0]]
        else:
            # generic op: forbid replica dims, propagate positional degrees
            # where the op's inferred output rank matches the input rank,
            # else require unsharded inputs
            for s in in_shapes:
                if s.num_replica_dims:
                    raise ValueError(
                        f"{node.name} ({node.op_type.name}) cannot consume a "
                        f"replicated tensor")
            logical_in = [s.logical_shape for s in in_shapes]
            inferred = node.op_def.infer_shapes(node.params, logical_in)
            out_shapes = []
            for shp in inferred:
                if (in_shapes and len(shp) == len(logical_in[0])
                        and all(d.degree == 1
                                for d in in_shapes[0].dims[1:])):
                    dims = [ParallelDim(shp[0],
                                        in_shapes[0].dims[0].degree,
                                        axes=in_shapes[0].dims[0].axes)]
                    dims += [ParallelDim(s) for s in shp[1:]]
                elif all(d.degree == 1 for s in in_shapes for d in s.dims):
                    dims = [ParallelDim(s) for s in shp]
                else:
                    raise ValueError(
                        f"{node.name} ({node.op_type.name}): unsupported "
                        f"parallel inputs {in_shapes}")
                out_shapes.append(
                    ParallelTensorShape(tuple(dims), in_shapes[0].dtype))

        old = node.outputs
        node.outputs = []
        for i, shape in enumerate(out_shapes):
            name = old[i].name if i < len(old) else f"{node.name}_out{i}"
            pt = ParallelTensor(shape, name=name)
            pt.owner_op, pt.owner_idx = node, i
            node.outputs.append(pt)
            partial[(node.guid, i)] = out_partial
        node._weight_partition = weight_partition


def _linear_parallel(node, in_shape: ParallelTensorShape, wp: dict):
    """Linear under parallel input state (reference linear.cc dim mappings):
    - batch-dim degrees propagate;
    - input replica dim (degree r) → kernel out-dim sharded r, output
      feature dim sharded r, replica dim consumed  [column TP];
    - input feature dim sharded (degree c) → kernel in-dim sharded c, output
      gains a replica dim of degree c (partial sums)  [row TP]."""
    dims = in_shape.dims
    logical = [d for d in dims if not d.is_replica_dim]
    replicas = [d for d in dims if d.is_replica_dim]
    if len(replicas) > 1:
        raise ValueError(f"{node.name}: multiple replica dims unsupported")
    r = replicas[0].degree if replicas else 1
    feat_deg = logical[-1].degree
    if r > 1 and feat_deg > 1:
        raise ValueError(
            f"{node.name}: simultaneous replicate + feature partition "
            f"unsupported")
    out_ch = node.params.out_channels
    out_dims = [replace(d) for d in logical[:-1]]
    if r > 1:
        if out_ch % r != 0:
            raise ValueError(f"{node.name}: out_channels {out_ch} % {r} != 0")
        out_dims.append(ParallelDim(out_ch, r, axes=replicas[0].axes))
        wp["kernel"] = (1, r)
        if node.params.use_bias:
            wp["bias"] = (0, r)
    else:
        out_dims.append(ParallelDim(out_ch))
    if feat_deg > 1:
        wp["kernel"] = (0, feat_deg)
        out_dims.append(ParallelDim(feat_deg, feat_deg, is_replica_dim=True,
                                    axes=logical[-1].axes))
    return ParallelTensorShape(tuple(out_dims), in_shape.dtype)


def _conv_parallel(node, in_shape: ParallelTensorShape, wp: dict):
    """Conv2D (NCHW / OIHW) under parallel input state (reference
    conv_2d.cc dim mappings):
    - sample-dim degrees propagate;
    - input replica dim (degree r) → kernel out-channel dim (O) sharded r,
      output channel dim sharded r, replica consumed  [channel TP];
    - input channel dim sharded (degree c, groups == 1) → kernel in-channel
      dim (I) sharded c, output gains a replica dim of degree c (partial
      sums)  [row-style]."""
    dims = in_shape.dims
    logical = [d for d in dims if not d.is_replica_dim]
    replicas = [d for d in dims if d.is_replica_dim]
    if len(replicas) > 1:
        raise ValueError(f"{node.name}: multiple replica dims unsupported")
    if any(d.degree > 1 for d in logical[2:]):
        raise ValueError(
            f"{node.name}: spatially-sharded conv input unsupported")
    r = replicas[0].degree if replicas else 1
    chan_deg = logical[1].degree
    if r > 1 and chan_deg > 1:
        raise ValueError(
            f"{node.name}: simultaneous replicate + channel partition "
            f"unsupported")
    p = node.params
    out_logical = node.op_def.infer_shapes(
        p, [tuple(d.size for d in logical)])[0]
    out_dims = [replace(logical[0])]
    if r > 1:
        if p.out_channels % r != 0:
            raise ValueError(
                f"{node.name}: out_channels {p.out_channels} % {r} != 0")
        out_dims.append(ParallelDim(p.out_channels, r, axes=replicas[0].axes))
        wp["kernel"] = (0, r)
        if p.use_bias:
            wp["bias"] = (0, r)
    else:
        out_dims.append(ParallelDim(p.out_channels))
    out_dims += [ParallelDim(s) for s in out_logical[2:]]
    if chan_deg > 1:
        if p.groups != 1:
            raise ValueError(
                f"{node.name}: channel-sharded grouped conv unsupported")
        wp["kernel"] = (1, chan_deg)
        out_dims.append(ParallelDim(chan_deg, chan_deg, is_replica_dim=True,
                                    axes=logical[1].axes))
    return ParallelTensorShape(tuple(out_dims), in_shape.dtype)


def _embedding_parallel(node, in_shape: ParallelTensorShape, wp: dict):
    """Embedding under parallel input state (reference embedding.cc:
    partitionable on the sample dim or — via a replicated input — on the
    output-channel dim):
    - sample-dim degrees propagate through the lookup;
    - input replica dim (degree r) → table sharded on the embedding dim,
      output feature dim sharded r, replica consumed (each chip gathers its
      column slice — full value, no partial sums)."""
    from ..fftype import AggrMode

    dims = in_shape.dims
    logical = [d for d in dims if not d.is_replica_dim]
    replicas = [d for d in dims if d.is_replica_dim]
    if len(replicas) > 1:
        raise ValueError(f"{node.name}: multiple replica dims unsupported")
    if any(d.degree > 1 for d in logical[1:]):
        raise ValueError(
            f"{node.name}: entry-dim-sharded embedding input unsupported")
    r = replicas[0].degree if replicas else 1
    p = node.params
    if p.aggr == AggrMode.AGGR_MODE_NONE:
        out_dims = [replace(d) for d in logical]
    else:
        out_dims = [replace(d) for d in logical[:-1]]
    if r > 1:
        if p.out_channels % r != 0:
            raise ValueError(
                f"{node.name}: out_channels {p.out_channels} % {r} != 0")
        out_dims.append(ParallelDim(p.out_channels, r, axes=replicas[0].axes))
        wp["kernel"] = (1, r)
    else:
        out_dims.append(ParallelDim(p.out_channels))
    # lookups emit the table dtype, not the integer index dtype
    return ParallelTensorShape(tuple(out_dims), p.data_type)


def _attention_parallel(node, in_shapes, wp: dict):
    """MHA under replicated input (reference replicate_attention_reduce):
    input replica degree r → q/k/v projections sharded on heads (out dim),
    out-projection row-sharded, output gains a replica dim of degree r
    (partial sums consumed by a Reduction node)."""
    q = in_shapes[0]
    replicas = [d for d in q.dims if d.is_replica_dim]
    r = replicas[0].degree if replicas else 1
    logical = [d for d in q.dims if not d.is_replica_dim]
    if any(d.degree > 1 for d in logical[1:]):
        raise ValueError(f"{node.name}: feature-sharded attention input "
                         f"unsupported")
    out_dims = [replace(d) for d in logical[:-1]]
    out_dims.append(ParallelDim(node.params.embed_dim))
    if r > 1:
        if node.params.num_heads % r != 0:
            raise ValueError(
                f"{node.name}: num_heads {node.params.num_heads} % {r} != 0")
        for w in ("wq", "wk", "wv"):
            wp[w] = (1, r)
        for b in ("bq", "bk", "bv"):
            wp[b] = (0, r)
        wp["wo"] = (0, r)
        out_dims.append(ParallelDim(r, r, is_replica_dim=True,
                                    axes=replicas[0].axes))
    return ParallelTensorShape(tuple(out_dims), q.dtype)


# ---------------------------------------------------------- axis assignment

def assign_axes_from_degrees(graph: Graph, mesh):
    """Map every tensor's ParallelDim degrees to mesh axes and emit weight
    PartitionSpecs — the FFMapper analog for rewritten graphs. Dims whose
    rewrite declared its mesh axes (ParallelDim.axes, threaded from the
    parallel-op params) use them verbatim — including composite multi-axis
    degrees; legacy degree-only dims fall back to size inference (batch
    degrees ride `data`, feature/replica degrees ride `model`). Unsharded
    tensors get the default data-parallel batch sharding
    (graph.cc:1939-1964 fallback)."""
    sizes = dict(mesh.shape)
    data_deg = sizes.get(AXIS_DATA, 1)
    model_deg = sizes.get(AXIS_MODEL, 1)

    def axes_for(dim_idx: int, degree: int, axes=()) -> tuple:
        if axes:
            prod = 1
            for a in axes:
                prod *= sizes.get(a, 1)
            if prod != degree:
                raise ValueError(
                    f"declared axes {axes} (product {prod}) do not carry "
                    f"degree {degree} on mesh {sizes}")
            return tuple(axes)
        if dim_idx == 0 and degree == data_deg:
            return (AXIS_DATA,)
        if degree == model_deg:
            return (AXIS_MODEL,)
        if degree == data_deg:
            return (AXIS_DATA,)
        raise ValueError(
            f"degree {degree} matches no mesh axis in {sizes}")

    def wp_axes(node, degree) -> tuple:
        # a weight partition's degree originates from the Replicate's
        # replica dim (column TP) or a sharded NON-BATCH logical dim
        # (row TP feature / conv channel). The batch dim can carry the
        # same degree on different axes (dp×tp), so it must never source
        # a weight partition's axes — match replica dims first, then
        # non-batch logical dims only.
        for pt in node.inputs:
            for d in pt.shape.dims:
                if d.is_replica_dim and d.degree == degree and d.axes:
                    return d.axes
        for pt in node.inputs:
            logical_idx = -1
            for d in pt.shape.dims:
                if d.is_replica_dim:
                    continue
                logical_idx += 1
                if logical_idx == 0:
                    continue
                if d.degree == degree and d.axes:
                    return d.axes
        return ()

    for node in graph.topo_order():
        for pt in node.outputs:
            assignment = []
            used_axes: set = set()
            logical_idx = 0
            for d in pt.shape.dims:
                if d.is_replica_dim:
                    assignment.append(())
                    continue
                if d.degree > 1:
                    entry = axes_for(logical_idx, d.degree, d.axes)
                    dup = used_axes.intersection(entry)
                    if dup or len(set(entry)) != len(entry):
                        # a mesh axis can shard at most one dim once — a
                        # nested same-axis rewrite must be pruned at
                        # costing, not handed to the executor
                        raise ValueError(
                            f"{node.name}: mesh axes used twice in one "
                            f"tensor assignment ({entry}, already used "
                            f"{sorted(used_axes)})")
                    used_axes.update(entry)
                    assignment.append(entry)
                elif (logical_idx == 0 and data_deg > 1
                      and d.size % data_deg == 0
                      and not is_expert_buffer(node)):
                    # default data-parallel batch sharding composes with the
                    # rewrite-derived feature/replica shardings (dp x tp)
                    assignment.append((AXIS_DATA,))
                else:
                    assignment.append(())
                logical_idx += 1
            pt.assign_axes(tuple(assignment))
        wp = getattr(node, "_weight_partition", None)
        if wp:
            for wname, (dim_idx, degree) in wp.items():
                ws = next((w for w in node.weight_specs if w.name == wname),
                          None)
                if ws is None:
                    continue
                entries = [None] * len(ws.shape)
                axes = axes_for(-1, degree, wp_axes(node, degree))
                entries[dim_idx] = axes if len(axes) > 1 else axes[0]
                node.weight_axes[wname] = PartitionSpec(*entries)


# ------------------------------------------------------------- graph costing

def evaluate_assigned_graph(graph: Graph, mesh, cm: CostModel,
                            overlap_sync: bool = False,
                            totals: dict | None = None
                            ) -> tuple[float, float]:
    """(time, per-chip memory) of a PCG on its ALREADY-materialized
    assignments — no re-derivation, so it is safe on a compiled model
    whose strategy was applied by `_assign_strategy` (the
    weight-update-sharding decision prices the live graph through here).
    Compute ops go through the cost model on their emitted assignments;
    parallel ops are priced as the collectives they lower to. Total time
    is the task-graph makespan. When the cost model prices a ZeRO-sharded
    update (cm.update_sharding + cm.overlap_update), the grad RS+AG rides
    the overlappable channel — max(compute, comm) + hop latency — exactly
    as UnitySearch.evaluate prices it; under stage 3 (cm.param_gather)
    the just-in-time weight-gather pair joins it via price_param_gather
    and the per-chip memory charges weights at 1/shards plus at most two
    gathered layers in flight. `totals`, when a dict, additionally
    accumulates the summed grad-sync seconds under "sync_s" (the
    update-sharding decision reads the sync fraction off it) and the
    summed gather seconds under "param_gather_s"."""
    from .cost_model import (
        _MakespanAccum, price_grad_sync, price_param_gather,
    )

    acc = _MakespanAccum(overlap_sync=overlap_sync)
    mem = 0.0
    gather_peak = 0.0
    machine = cm.machine
    for node in graph.topo_order():
        if node.op_type in (OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP):
            continue
        if node.op_type in _PARALLEL:
            comm, comm_axes = price_parallel_node(node, machine)
            acc.add(node.guid, 0.0, comm, comm_axes=comm_axes)
            continue
        in_shapes, in_assigns = [], []
        for pt in node.inputs:
            in_shapes.append(pt.shape.logical_shape)
            in_assigns.append(_logical_assignment(pt))
        cmx = cm.op_cost(
            node, [_logical_assignment(pt) for pt in node.outputs],
            dict(node.weight_axes), in_shapes, in_assigns)
        grad_sync = cmx.sync_time + cmx.update_sync_time
        if totals is not None:
            totals["sync_s"] = totals.get("sync_s", 0.0) + grad_sync
            totals["param_gather_s"] = (totals.get("param_gather_s", 0.0)
                                        + cmx.param_gather_time)
        # the shared update-mode pricing rules (cost_model.price_grad_sync
        # / price_param_gather — the same rules UnitySearch.evaluate
        # applies, so the decision made through here matches the reported
        # makespan)
        sync, overlap_comm, overlap_overhead, _ = price_grad_sync(
            cmx, cm.update_sharding, getattr(cm, "overlap_update", False))
        pg_serial, pg_overlap, pg_overhead, _ = price_param_gather(
            cmx, getattr(cm, "overlap_update", False))
        acc.add(node.guid, cmx.forward_time + cmx.backward_time,
                cmx.comm_time + pg_serial, sync=sync,
                comm_axes=(AXIS_DATA,)
                if grad_sync > 0 or cmx.param_gather_time > 0 else (),
                overlappable_comm=overlap_comm + pg_overlap,
                overlap_overhead=overlap_overhead + pg_overhead)
        mem += cmx.memory
        gather_peak = max(gather_peak, cmx.gather_bytes)
    mem += 2.0 * gather_peak
    return acc.makespan(graph.in_edges), mem


def evaluate_graph(graph: Graph, mesh, cm: CostModel,
                   overlap_sync: bool = False) -> tuple[float, float]:
    """(time, per-chip memory) of a rewritten PCG: materialize the
    rewrite's degree-derived assignments first (assign_axes_from_degrees
    — the FFMapper analog), then price via evaluate_assigned_graph."""
    assign_axes_from_degrees(graph, mesh)
    return evaluate_assigned_graph(graph, mesh, cm,
                                   overlap_sync=overlap_sync)


def _logical_assignment(pt: ParallelTensor):
    return tuple(a for d, a in zip(pt.shape.dims, pt.axis_assignment)
                 if not d.is_replica_dim)


# ------------------------------------------------------------ rule generators

def _lin_act(act):
    return lambda n: n.params.activation == act


def _axes_tag(axes) -> str:
    return f",axes={'x'.join(axes)}" if axes else ""


def create_partition_linear_combine(degree: int, activation,
                                    axes: tuple = ()) -> GraphXfer:
    """Repartition(sample) → Linear → Combine(sample)
    (substitution.cc:3041). `axes` optionally binds the split to named
    mesh axes (possibly composite, e.g. ('data', 'seq'))."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_linear_combine[deg={degree},"
                  f"act={activation}{_axes_tag(axes)}]")
    inp = x.new_input(0)
    lin1 = OpX(OT.OP_LINEAR, (inp,), constraints=(_lin_act(activation),))
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, degree, axes))
    lin2 = OpX(OT.OP_LINEAR, (rep.outputs[0],), match_src=lin1)
    comb = OpX(OT.OP_COMBINE, (lin2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [lin1]
    x.dst_ops = [rep, lin2, comb]
    x.map_output(lin1.outputs[0], comb.outputs[0])
    return x


def create_replicate_linear_combine(degree: int, activation,
                                    axes: tuple = ()) -> GraphXfer:
    """Replicate → Linear(kernel out-dim sharded) → Combine(feature): column
    tensor parallelism (substitution.cc:3226)."""
    axes = tuple(axes)
    x = GraphXfer(f"replicate_linear_combine[deg={degree},"
                  f"act={activation}{_axes_tag(axes)}]")
    inp = x.new_input(0)
    lin1 = OpX(OT.OP_LINEAR, (inp,), constraints=(_lin_act(activation),))
    repl = OpX(OT.OP_REPLICATE, (inp,),
               make_params=lambda m: ReplicateParams(degree, axes))
    lin2 = OpX(OT.OP_LINEAR, (repl.outputs[0],), match_src=lin1)

    def combine_feature(m):
        lin = m[lin1]
        ndim = len(lin.outputs[0].shape.logical_shape)
        return CombineParams(ndim - 1, degree, axes)

    comb = OpX(OT.OP_COMBINE, (lin2.outputs[0],),
               make_params=combine_feature)
    x.src_ops = [lin1]
    x.dst_ops = [repl, lin2, comb]
    x.map_output(lin1.outputs[0], comb.outputs[0])
    return x


def create_replicate_attention_reduce(degree: int,
                                      axes: tuple = ()) -> GraphXfer:
    """Replicate → MHA(heads sharded, row-parallel out-proj) → Reduction:
    inserts an explicit Reduction node consuming the partial-sum replica dim
    (substitution.cc create_replicate_attention_reduce)."""
    axes = tuple(axes)
    x = GraphXfer(f"replicate_attention_reduce[deg={degree}"
                  f"{_axes_tag(axes)}]")
    inp = x.new_input(0)
    attn1 = OpX(
        OT.OP_MULTIHEAD_ATTENTION, (inp, inp, inp),
        constraints=(lambda n: n.params.num_heads % degree == 0,),
    )
    repl = OpX(OT.OP_REPLICATE, (inp,),
               make_params=lambda m: ReplicateParams(degree, axes))
    r0 = repl.outputs[0]
    attn2 = OpX(OT.OP_MULTIHEAD_ATTENTION, (r0, r0, r0), match_src=attn1)
    red = OpX(OT.OP_REDUCTION, (attn2.outputs[0],),
              make_params=lambda m: ReductionParams(degree, axes))
    x.src_ops = [attn1]
    x.dst_ops = [repl, attn2, red]
    x.map_output(attn1.outputs[0], red.outputs[0])
    return x


def create_partition_attention_combine(degree: int,
                                       axes: tuple = ()) -> GraphXfer:
    """Repartition(sample) → MHA → Combine(sample)
    (substitution.cc create_partition_attention_combine)."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_attention_combine[deg={degree}"
                  f"{_axes_tag(axes)}]")
    inp = x.new_input(0)
    attn1 = OpX(OT.OP_MULTIHEAD_ATTENTION, (inp, inp, inp))
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, degree, axes))
    r0 = rep.outputs[0]
    attn2 = OpX(OT.OP_MULTIHEAD_ATTENTION, (r0, r0, r0), match_src=attn1)
    comb = OpX(OT.OP_COMBINE, (attn2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [attn1]
    x.dst_ops = [rep, attn2, comb]
    x.map_output(attn1.outputs[0], comb.outputs[0])
    return x


def create_partition_add_combine(degree: int, axes: tuple = ()) -> GraphXfer:
    """Repartition both addends on sample, add, Combine back
    (substitution.cc:3257)."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_add_combine[deg={degree}{_axes_tag(axes)}]")
    a, b = x.new_input(0), x.new_input(1)
    add1 = OpX(OT.OP_EW_ADD, (a, b))
    rep1 = OpX(OT.OP_REPARTITION, (a,),
               make_params=lambda m: RepartitionParams(0, degree, axes))
    rep2 = OpX(OT.OP_REPARTITION, (b,),
               make_params=lambda m: RepartitionParams(0, degree, axes))
    # match_src is load-bearing: without it the rewritten add carries
    # params=None and the executor's _binary_forward crashes at runtime
    # (caught by the ffrules semantic oracle)
    add2 = OpX(OT.OP_EW_ADD, (rep1.outputs[0], rep2.outputs[0]),
               match_src=add1)
    comb = OpX(OT.OP_COMBINE, (add2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [add1]
    x.dst_ops = [rep1, rep2, add2, comb]
    x.map_output(add1.outputs[0], comb.outputs[0])
    return x


def _passthrough_partition(op_type: OT, degree: int, tag: str,
                           axes: tuple = ()) -> GraphXfer:
    axes = tuple(axes)
    x = GraphXfer(f"partition_{tag}_combine[deg={degree}{_axes_tag(axes)}]")
    inp = x.new_input(0)
    op1 = OpX(op_type, (inp,))
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, degree, axes))
    op2 = OpX(op_type, (rep.outputs[0],), match_src=op1)
    comb = OpX(OT.OP_COMBINE, (op2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [op1]
    x.dst_ops = [rep, op2, comb]
    x.map_output(op1.outputs[0], comb.outputs[0])
    return x


def create_partition_relu_combine(degree: int, axes: tuple = ()) -> GraphXfer:
    return _passthrough_partition(OT.OP_RELU, degree, "relu", axes)


def create_partition_softmax_combine(degree: int,
                                     axes: tuple = ()) -> GraphXfer:
    return _passthrough_partition(OT.OP_SOFTMAX, degree, "softmax", axes)


def create_partition_conv2d_combine(degree: int,
                                    axes: tuple = ()) -> GraphXfer:
    """Repartition(sample) → Conv2D → Combine(sample)
    (substitution.cc create_partition_conv2d_combine)."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_conv2d_combine[deg={degree}{_axes_tag(axes)}]")
    inp = x.new_input(0)
    c1 = OpX(OT.OP_CONV2D, (inp,))
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, degree, axes))
    c2 = OpX(OT.OP_CONV2D, (rep.outputs[0],), match_src=c1)
    comb = OpX(OT.OP_COMBINE, (c2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [c1]
    x.dst_ops = [rep, c2, comb]
    x.map_output(c1.outputs[0], comb.outputs[0])
    return x


def create_replicate_conv2d_combine(degree: int,
                                    axes: tuple = ()) -> GraphXfer:
    """Replicate → Conv2D(out-channel-sharded kernel) → Combine(channel):
    the channel/attribute-parallel conv rewrite (substitution.cc
    create_partition_attention_combine's conv sibling)."""
    axes = tuple(axes)
    x = GraphXfer(f"replicate_conv2d_combine[deg={degree}{_axes_tag(axes)}]")
    inp = x.new_input(0)
    c1 = OpX(OT.OP_CONV2D, (inp,),
             constraints=(lambda n: n.params.out_channels % degree == 0,))
    repl = OpX(OT.OP_REPLICATE, (inp,),
               make_params=lambda m: ReplicateParams(degree, axes))
    c2 = OpX(OT.OP_CONV2D, (repl.outputs[0],), match_src=c1)
    comb = OpX(OT.OP_COMBINE, (c2.outputs[0],),
               make_params=lambda m: CombineParams(1, degree, axes))
    x.src_ops = [c1]
    x.dst_ops = [repl, c2, comb]
    x.map_output(c1.outputs[0], comb.outputs[0])
    return x


def create_partition_pool2d_combine(degree: int,
                                    axes: tuple = ()) -> GraphXfer:
    return _passthrough_partition(OT.OP_POOL2D, degree, "pool2d", axes)


def create_partition_concat_combine(degree: int,
                                    axes: tuple = ()) -> GraphXfer:
    """Repartition both concat operands on sample, concat, Combine back —
    the 2-ary instance (substitution.cc create_partition_concat_combine;
    the reference generates per num_inputs too)."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_concat_combine[deg={degree}{_axes_tag(axes)}]")
    a, b = x.new_input(0), x.new_input(1)
    # arity constraint is load-bearing: the matcher only checks the node has
    # AT LEAST as many inputs as the pattern, so without it a 3-input
    # concat would match and the rewrite would silently drop operands
    cat1 = OpX(OT.OP_CONCAT, (a, b),
               constraints=(lambda n: n.params.axis != 0,
                            lambda n: n.params.n == 2,))
    rep1 = OpX(OT.OP_REPARTITION, (a,),
               make_params=lambda m: RepartitionParams(0, degree, axes))
    rep2 = OpX(OT.OP_REPARTITION, (b,),
               make_params=lambda m: RepartitionParams(0, degree, axes))
    cat2 = OpX(OT.OP_CONCAT, (rep1.outputs[0], rep2.outputs[0]),
               match_src=cat1)
    comb = OpX(OT.OP_COMBINE, (cat2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [cat1]
    x.dst_ops = [rep1, rep2, cat2, comb]
    x.map_output(cat1.outputs[0], comb.outputs[0])
    return x


def create_partition_embedding_combine(degree: int,
                                       axes: tuple = ()) -> GraphXfer:
    """Repartition(sample) → Embedding → Combine(sample)
    (embedding.cc is partitionable on the sample dim)."""
    axes = tuple(axes)
    x = GraphXfer(f"partition_embedding_combine[deg={degree}"
                  f"{_axes_tag(axes)}]")
    inp = x.new_input(0)
    e1 = OpX(OT.OP_EMBEDDING, (inp,))
    rep = OpX(OT.OP_REPARTITION, (inp,),
              make_params=lambda m: RepartitionParams(0, degree, axes))
    e2 = OpX(OT.OP_EMBEDDING, (rep.outputs[0],), match_src=e1)
    comb = OpX(OT.OP_COMBINE, (e2.outputs[0],),
               make_params=lambda m: CombineParams(0, degree, axes))
    x.src_ops = [e1]
    x.dst_ops = [rep, e2, comb]
    x.map_output(e1.outputs[0], comb.outputs[0])
    return x


def create_fuse_moe_trio(n: int) -> GraphXfer:
    """Fuse the reference-parity unfused MoE trio — Group_by → n per-expert
    Dense → Aggregate (src/ops/moe.cc:20-50) — into the single stacked
    Experts op, whose (n, d, h) kernel shards over the expert/model mesh
    axis (UnitySearch's "ep" config). This is how expert parallelism
    reaches models built through the unfused API: the reference gives the
    trio attribute-parallel machine views (examples/cpp/mixture_of_experts);
    under GSPMD per-expert ops can't be "placed", so the capability is
    delivered by this rewrite + a sharding instead.

    Expert weights are re-initialized by the rewrite (the reference also
    rebuilds operators from the optimized PCG at compile, model.cc:2830+).
    """
    from ..ops.moe import ExpertsParams

    x = GraphXfer(f"fuse_moe_trio[n={n}]")
    data = x.new_input(0)
    values = x.new_input(1)
    assign = x.new_input(2)
    probs = x.new_input(3)

    gb = OpX(OT.OP_GROUP_BY, (data, assign), num_outputs=n,
             constraints=(lambda node: node.params.n == n,))
    linears = [
        OpX(OT.OP_LINEAR, (TensorX(gb, i),),
            constraints=(lambda node: node.params.use_bias,))
        for i in range(n)
    ]
    agg = OpX(OT.OP_AGGREGATE, tuple(
        [values, assign, assign, probs] + [l.outputs[0] for l in linears]))

    def experts_params(m):
        gbp = m[gb].params
        aggp = m[agg].params
        lps = [m[l].params for l in linears]
        hidden = lps[0].out_channels
        act = lps[0].activation
        if any(p.out_channels != hidden or p.activation != act
               for p in lps):
            raise ValueError("fuse_moe_trio: experts disagree on shape/act")
        act_name = {ActiMode.AC_MODE_RELU: "relu",
                    ActiMode.AC_MODE_GELU: "gelu",
                    ActiMode.AC_MODE_NONE: "none"}.get(act)
        if act_name is None:
            raise ValueError(f"fuse_moe_trio: unsupported activation {act}")
        return ExpertsParams(n, hidden, gbp.alpha, aggp.lambda_bal,
                             use_bias=True, activation=act_name)

    experts = OpX(OT.OP_EXPERTS, (data, values, assign),
                  make_params=experts_params)
    x.src_ops = [gb] + linears + [agg]
    x.dst_ops = [experts]
    x.map_output(agg.outputs[0], experts.outputs[0])
    return x


def create_linear_relu_merge() -> GraphXfer:
    """Fuse Linear(no act) + ReLU into Linear(relu) — the algebraic (non-
    parallel) substitution family (substitution.cc create_linear_relu_merge).
    """
    x = GraphXfer("linear_relu_merge")
    inp = x.new_input(0)
    lin = OpX(OT.OP_LINEAR, (inp,),
              constraints=(_lin_act(ActiMode.AC_MODE_NONE),))
    relu = OpX(OT.OP_RELU, (lin.outputs[0],))

    def fused_params(m):
        return replace(m[lin].params, activation=ActiMode.AC_MODE_RELU)

    fused = OpX(OT.OP_LINEAR, (inp,), match_src=lin,
                make_params=fused_params)
    x.src_ops = [lin, relu]
    x.dst_ops = [fused]
    x.map_output(relu.outputs[0], fused.outputs[0])
    return x


def _axes_kw(kw):
    return tuple(kw.get("axes", ()))


_GENERATORS = {
    "partition_linear_combine":
        lambda deg, **kw: create_partition_linear_combine(
            deg, kw.get("activation", ActiMode.AC_MODE_NONE), _axes_kw(kw)),
    "replicate_linear_combine":
        lambda deg, **kw: create_replicate_linear_combine(
            deg, kw.get("activation", ActiMode.AC_MODE_NONE), _axes_kw(kw)),
    "replicate_attention_reduce":
        lambda deg, **kw: create_replicate_attention_reduce(deg, _axes_kw(kw)),
    "partition_attention_combine":
        lambda deg, **kw: create_partition_attention_combine(deg, _axes_kw(kw)),
    "partition_add_combine":
        lambda deg, **kw: create_partition_add_combine(deg, _axes_kw(kw)),
    "partition_relu_combine":
        lambda deg, **kw: create_partition_relu_combine(deg, _axes_kw(kw)),
    "partition_softmax_combine":
        lambda deg, **kw: create_partition_softmax_combine(deg, _axes_kw(kw)),
    "partition_conv2d_combine":
        lambda deg, **kw: create_partition_conv2d_combine(deg, _axes_kw(kw)),
    "replicate_conv2d_combine":
        lambda deg, **kw: create_replicate_conv2d_combine(deg, _axes_kw(kw)),
    "partition_pool2d_combine":
        lambda deg, **kw: create_partition_pool2d_combine(deg, _axes_kw(kw)),
    "partition_concat_combine":
        lambda deg, **kw: create_partition_concat_combine(deg, _axes_kw(kw)),
    "partition_embedding_combine":
        lambda deg, **kw: create_partition_embedding_combine(deg, _axes_kw(kw)),
    "linear_relu_merge": lambda deg, **kw: create_linear_relu_merge(),
    "fuse_moe_trio": lambda deg, **kw: create_fuse_moe_trio(
        int(kw.get("n", deg))),
}


def generate_all_pcg_xfers(mesh, config, graph: Optional[Graph] = None
                           ) -> list[GraphXfer]:
    """The rule set for a mesh (generate_all_pcg_xfers,
    substitution.cc:1726-1868): one instance of each family per EXPRESSIBLE
    parallel degree, where the mesh's single ICI axes and composite axis
    pairs play the role of the reference's per-degree loops. On a TPU mesh
    the expressible degrees are exactly products of whole named axes (GSPMD
    shards a dim over whole axes); sub-axis degrees — a degree-2 split on an
    8-wide axis — are reached by re-factorizing the mesh itself
    (search/mesh_search.py), not by a rewrite. Each instance carries its
    axes on the parallel-op params, so assignment and pricing never infer
    an axis from a degree. When the graph is given, data-driven families
    are added too (one fuse_moe_trio per distinct Group_by expert count)."""
    from ..machine import AXIS_SEQ

    xfers: list[GraphXfer] = [create_linear_relu_merge()]
    if graph is not None:
        seen_n = set()
        for node in graph.topo_order():
            if node.op_type == OT.OP_GROUP_BY and node.params.n not in seen_n:
                seen_n.add(node.params.n)
                xfers.append(create_fuse_moe_trio(node.params.n))
    sizes = dict(mesh.shape)
    acts = (ActiMode.AC_MODE_NONE, ActiMode.AC_MODE_RELU,
            ActiMode.AC_MODE_SIGMOID, ActiMode.AC_MODE_GELU)

    def deg_of(axes) -> int:
        d = 1
        for a in axes:
            d *= sizes[a]
        return d

    # batch-split (Repartition) axis groups: data, seq, and their
    # composition; weight-split (Replicate/Reduction) groups: model, and
    # model×seq. The seq axis doubles as extra batch/TP capacity when the
    # graph doesn't need it for ring attention — the search arbitrates.
    batch_groups = [(a,) for a in (AXIS_DATA, AXIS_SEQ)
                    if sizes.get(a, 1) > 1]
    if len(batch_groups) == 2:
        batch_groups.append((AXIS_DATA, AXIS_SEQ))
    tp_groups = [(AXIS_MODEL,)] if sizes.get(AXIS_MODEL, 1) > 1 else []
    if tp_groups and sizes.get(AXIS_SEQ, 1) > 1:
        tp_groups.append((AXIS_MODEL, AXIS_SEQ))

    seen_names = {x.name for x in xfers}

    def add(x: GraphXfer):
        # names encode (family, degree, act, axes): the dedup bound on the
        # candidate pool
        if x.name not in seen_names:
            seen_names.add(x.name)
            xfers.append(x)

    for axes in tp_groups:
        deg = deg_of(axes)
        for act in acts:
            add(create_replicate_linear_combine(deg, act, axes))
        add(create_replicate_attention_reduce(deg, axes))
        add(create_replicate_conv2d_combine(deg, axes))
    for axes in batch_groups:
        deg = deg_of(axes)
        for act in acts:
            add(create_partition_linear_combine(deg, act, axes))
        add(create_partition_attention_combine(deg, axes))
        add(create_partition_add_combine(deg, axes))
        add(create_partition_relu_combine(deg, axes))
        add(create_partition_softmax_combine(deg, axes))
        add(create_partition_conv2d_combine(deg, axes))
        add(create_partition_pool2d_combine(deg, axes))
        add(create_partition_concat_combine(deg, axes))
        add(create_partition_embedding_combine(deg, axes))
    # stable, content-hashable emission order (ffrules pass 5, registry
    # determinism): sorted by the name that encodes (family, degree, act,
    # axes) — the dedup key above — so two processes, or two runs of one
    # process, emit byte-identical rule sets and the registry fingerprint
    # (analysis/rules.rules_fingerprint) is a real content address
    xfers.sort(key=lambda x: x.name)
    return xfers


_ACT_NAMES = {
    "none": ActiMode.AC_MODE_NONE, "relu": ActiMode.AC_MODE_RELU,
    "sigmoid": ActiMode.AC_MODE_SIGMOID,
    "gelu": ActiMode.AC_MODE_GELU, "tanh": ActiMode.AC_MODE_TANH,
}

# parallel-op param constructors for pattern rules: field lists give the
# JSON "params" keys in positional order
_PARALLEL_PARAMS = {
    OT.OP_REPARTITION: (RepartitionParams, ("dim", "degree")),
    OT.OP_COMBINE: (CombineParams, ("dim", "degree")),
    OT.OP_REPLICATE: (ReplicateParams, ("degree",)),
    OT.OP_REDUCTION: (ReductionParams, ("degree",)),
}


def _op_type_by_name(name: str) -> OT:
    key = f"OP_{name.upper()}"
    try:
        return OT[key]
    except KeyError:
        raise ValueError(f"unknown op type {name!r} in substitution rule")


def _resolve_attr_value(v):
    """JSON attr values: activation names resolve to ActiMode; everything
    else passes through."""
    if isinstance(v, str) and v.strip().lower() in _ACT_NAMES:
        return _ACT_NAMES[v.strip().lower()]
    return v


def _make_constraint(spec: dict):
    """One source-op constraint: {"attr": f, "eq": v} (equality, enum names
    resolved) or {"attr": f, "mod": d} (divisibility) — the expressible
    subset of substitution_loader.cc's PMParameter conditions."""
    attr = spec["attr"]
    if "eq" in spec:
        want = _resolve_attr_value(spec["eq"])
        return lambda n: getattr(n.params, attr, None) == want
    if "mod" in spec:
        d = int(spec["mod"])
        return lambda n: getattr(n.params, attr, 0) % d == 0
    raise ValueError(f"constraint {spec} needs 'eq' or 'mod'")


def compile_pattern_rule(rule: dict) -> GraphXfer:
    """Compile one declarative src→dst pattern rule into a GraphXfer — the
    substitution_loader.cc analog, able to express NEW rewrites (arbitrary
    ops, multi-op patterns, constraints), not just parameterize built-ins.

    Schema:
      {"name": str,
       "src": [{"op": "linear", "inputs": ["$0"], "out": "l1",
                "constraints": [{"attr": "activation", "eq": "none"}]}],
       "dst": [{"op": "repartition", "inputs": ["$0"],
                "params": {"dim": 0, "degree": 4}, "out": "r1"},
               {"op": "linear", "inputs": ["r1"], "match": "l1",
                "params_update": {"activation": "relu"}, "out": "l2"},
               ...],
       "map_outputs": [["l1", "c1"]]}

    `inputs` entries: "$i" = the xfer's free input slot i; "name" or
    "name:idx" = output idx of a previously declared pattern op. `match`
    makes a dst compute op inherit the named src op's params/weights
    (matchOpX); `params_update` overrides fields on the inherited params;
    parallel-op `params` build the op's param struct."""
    x = GraphXfer(rule.get("name", "pattern_rule"))
    tensors: dict[str, TensorX] = {}

    def resolve_input(ref: str) -> TensorX:
        if ref.startswith("$"):
            return x.new_input(int(ref[1:]))
        name, _, idx = ref.partition(":")
        if name not in tensors:
            raise ValueError(
                f"rule {x.name}: input {ref!r} references unknown op")
        base = tensors[name]
        if idx:
            return TensorX(base.op, int(idx))
        return base

    named_ops: dict[str, OpX] = {}
    for spec in rule.get("src", []):
        if not isinstance(spec, dict) or "op" not in spec:
            raise ValueError(
                f"rule {x.name}: each src entry must be an object with "
                f"an 'op' field, got {spec!r}")
        ot = _op_type_by_name(spec["op"])
        ins = tuple(resolve_input(r) for r in spec.get("inputs", []))
        cons = tuple(_make_constraint(c)
                     for c in spec.get("constraints", []))
        op = OpX(ot, ins, num_outputs=int(spec.get("num_outputs", 1)),
                 constraints=cons)
        # the declarative constraint specs stay attached so the ffrules
        # verifier (analysis/rules.py) can honor eq/mod hints when it
        # synthesizes a concrete instance (closures are opaque)
        op._constraint_specs = tuple(spec.get("constraints", []))
        x.src_ops.append(op)
        out = spec.get("out")
        if out:
            named_ops[out] = op
            tensors[out] = op.outputs[0]

    for spec in rule.get("dst", []):
        if not isinstance(spec, dict) or "op" not in spec:
            raise ValueError(
                f"rule {x.name}: each dst entry must be an object with "
                f"an 'op' field, got {spec!r}")
        ot = _op_type_by_name(spec["op"])
        ins = tuple(resolve_input(r) for r in spec.get("inputs", []))
        if ot in _PARALLEL_PARAMS:
            cls, fields = _PARALLEL_PARAMS[ot]
            params = spec.get("params", {})
            missing = [f for f in fields if f not in params]
            if missing:
                raise ValueError(
                    f"rule {x.name}: parallel dst op {spec['op']!r} "
                    f"params missing field(s) {missing} (needs {fields})")
            args = []
            for f in fields:  # dim/degree are ints by schema — coerce
                try:
                    args.append(int(params[f]))
                except (TypeError, ValueError):
                    raise ValueError(
                        f"rule {x.name}: parallel dst op {spec['op']!r} "
                        f"param {f!r} must be an integer, got "
                        f"{params[f]!r}")
            op = OpX(ot, ins, make_params=lambda m, c=cls, a=tuple(args):
                     c(*a))
        elif "match" in spec:
            src_op = named_ops.get(spec["match"])
            if src_op is None or src_op not in x.src_ops:
                raise ValueError(
                    f"rule {x.name}: match={spec['match']!r} names no "
                    f"source op")
            updates = {k: _resolve_attr_value(v)
                       for k, v in spec.get("params_update", {}).items()}
            mk = ((lambda m, s=src_op, u=dict(updates):
                   replace(m[s].params, **u)) if updates else None)
            op = OpX(ot, ins, num_outputs=int(spec.get("num_outputs", 1)),
                     match_src=src_op, make_params=mk)
        else:
            raise ValueError(
                f"rule {x.name}: dst op {spec['op']!r} needs 'match' (to "
                f"inherit a source op's params) or must be a parallel op "
                f"with 'params'")
        x.dst_ops.append(op)
        out = spec.get("out")
        if out:
            named_ops[out] = op
            tensors[out] = op.outputs[0]

    for src_ref, dst_ref in rule.get("map_outputs", []):
        sname, _, sidx = src_ref.partition(":")
        dname, _, didx = dst_ref.partition(":")
        if sname not in named_ops or dname not in named_ops:
            raise ValueError(
                f"rule {x.name}: map_outputs references unknown op")
        x.map_output(TensorX(named_ops[sname], int(sidx or 0)),
                     TensorX(named_ops[dname], int(didx or 0)))
    if not x.src_ops or not x.dst_ops or not x.mapped_outputs:
        raise ValueError(
            f"rule {x.name}: needs src ops, dst ops, and map_outputs")
    return x


def load_rule_collection(path: str, mesh,
                         config=None) -> list[GraphXfer]:
    """JSON rule loader wired to --substitution-json (reference
    substitution_loader.cc + substitutions/graph_subst_3_v2.json). Two rule
    forms, mixable in one file:

      {"rules": [
         {"generator": "replicate_linear_combine",
          "degree": 4, "activation": "relu"},        # parameterized built-in
         {"name": "...", "src": [...], "dst": [...],
          "map_outputs": [...]}                       # full src→dst pattern
      ]}

    `degree` defaults to the mesh's model-axis size. Unknown generators /
    ops / malformed patterns raise (matching the reference loader's
    strictness).

    When `config` is given, every loaded rule is VERIFIED through the
    ffrules passes (analysis/rules.py) before it can reach the search —
    external rules are the trust boundary TASO formalized: an unsound
    rule raises a structured RuleVerificationError naming the rule and
    finding class; `--no-verify-rules` downgrades to a warning with the
    verdict recorded in strategy_report.json's analysis section."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or not isinstance(
            data.get("rules", []), list):
        raise ValueError(
            f"{path}: substitution file must be an object with a "
            f"'rules' list")
    sizes = dict(mesh.shape)
    default_deg = sizes.get(AXIS_MODEL, 1)
    xfers = []
    for rule in data.get("rules", []):
        if not isinstance(rule, dict):
            raise ValueError(
                f"{path}: each rule must be an object, got {rule!r}")
        if "src" in rule or "dst" in rule:
            xfers.append(compile_pattern_rule(rule))
            continue
        gen = rule.get("generator")
        if gen not in _GENERATORS:
            raise ValueError(
                f"unknown substitution generator {gen!r}; have "
                f"{sorted(_GENERATORS)}")
        kw = {}
        if "activation" in rule:
            act = rule["activation"].strip().lower()
            if act not in _ACT_NAMES:
                raise ValueError(
                    f"unknown activation {rule['activation']!r}; have "
                    f"{sorted(_ACT_NAMES)}")
            kw["activation"] = _ACT_NAMES[act]
        if "n" in rule:
            kw["n"] = int(rule["n"])
        xfers.append(_GENERATORS[gen](int(rule.get("degree", default_deg)),
                                      **kw))
    if config is not None:
        from ..analysis.rules import gate_loaded_rules

        gate_loaded_rules(xfers, mesh, config, path)
    return xfers


# -------------------------------------------------------------- base_optimize

def best_first_search(
    graph: Graph,
    xfers: list[GraphXfer],
    cost_fn,
    budget: int,
    alpha: float,
):
    """The base_optimize loop (reference substitution.cc:2229-2311) with the
    candidate evaluator injected: a priority queue of rewritten graphs
    ordered by cost, budgeted pops, alpha pruning against the incumbent, and
    graph-hash dedup. `cost_fn(g) -> (cost, payload)` may raise ValueError
    to reject a candidate. Returns (best graph, best cost, best payload).
    Shared by the degree-priced substitution search and the joint search
    (which prices candidates with the placement DP)."""
    from .. import telemetry

    counter = itertools.count()
    best_cost, best_payload = cost_fn(graph)
    best_g = graph
    pq: list = [(best_cost, next(counter), graph)]
    seen = {graph.hash()}
    pops = 0
    evaluated = 0
    with telemetry.span("search.best_first", budget=budget):
        while pq and pops < budget:
            cost, _, g = heapq.heappop(pq)
            pops += 1
            if cost > best_cost * alpha:
                continue
            for xfer in xfers:
                for m in xfer.find_matches(g):
                    try:
                        ng = xfer.apply(g, m)
                    except ValueError:
                        continue
                    h = ng.hash()
                    if h in seen:
                        continue
                    seen.add(h)
                    try:
                        nc, npayload = cost_fn(ng)
                    except ValueError:
                        continue
                    evaluated += 1
                    if nc < best_cost:
                        best_g, best_cost, best_payload = ng, nc, npayload
                        # best-cost-so-far curve across rewritten candidates
                        telemetry.counter(
                            "search.best_cost_ms",
                            {"cost": best_cost * 1e3})
                    if nc < best_cost * alpha:
                        heapq.heappush(pq, (nc, next(counter), ng))
    telemetry.event("search_candidates", candidates=evaluated, pops=pops,
                    best_cost_s=best_cost)
    return best_g, best_cost, best_payload


def base_optimize(
    graph: Graph,
    mesh,
    cm: CostModel,
    xfers: list[GraphXfer],
    budget: int = 16,
    alpha: float = 1.2,
    hbm_cap: Optional[float] = None,
    overlap_sync: bool = False,
) -> tuple[Graph, float]:
    """Substitution-only search: candidates priced through the fixed
    degree-derived axis assignment (evaluate_graph) with per-chip HBM
    validity (graph.cc is_valid_strategy). The joint search (search/joint.py)
    prices the same candidates with the full placement DP instead."""

    def cost_of(g: Graph):
        t, mem = evaluate_graph(g, mesh, cm, overlap_sync=overlap_sync)
        cap = hbm_cap if hbm_cap is not None else cm.machine.chip.hbm_bytes
        if mem > cap:
            t *= 1.0 + 10.0 * (mem - cap) / cap
        return t, None

    best_g, best_cost, _ = best_first_search(graph, xfers, cost_of,
                                             budget, alpha)
    assign_axes_from_degrees(best_g, mesh)
    return best_g, best_cost


def graph_optimize(graph: Graph, mesh, config,
                   cm: Optional[CostModel] = None) -> Graph:
    """Substitution-search entry (GraphSearchHelper::graph_optimize,
    substitution.cc:1898): build the rule set (JSON rules when
    --substitution-json is given, built-in generators otherwise), run
    base_optimize, return the best rewritten graph with axes assigned."""
    from .machine_model import machine_model_for_mesh

    cm = cm or CostModel(machine_model_for_mesh(mesh))
    if config.substitution_json_path:
        # external rules verify at load (ffrules gate via config=)
        xfers = load_rule_collection(config.substitution_json_path, mesh,
                                     config=config)
    else:
        # built-in registry: swept by scripts/ffrules.py in CI
        xfers = generate_all_pcg_xfers(mesh, config, graph)  # fflint: ok unverified_rule_load
    budget = config.search_budget or 16
    best, _ = base_optimize(
        graph, mesh, cm, xfers, budget=budget, alpha=config.search_alpha,
        overlap_sync=config.search_overlap_backward_update)
    return best
