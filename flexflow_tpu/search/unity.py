"""Unity search: choose a mesh-axis assignment per PCG node.

Algorithm parity with the reference (SURVEY §3.2):

- `graph_cost` DP over sequence splits at bottleneck nodes
  (SearchHelper::find_optimal_sequence_graph_time, graph.cc:115-180): a
  bottleneck is a node every source→sink path crosses; the DP state is the
  candidate config of the bottleneck tensor, and segment costs are memoized
  per (in_config, out_config) — exactly the reference's memoized
  sequence-split recursion with MachineViews replaced by axis assignments.
- inside a segment, configs are enumerated jointly when the segment is small
  (the reference's nonsequence exhaustive split, graph.cc:267-321) and
  greedily otherwise.
- the candidate configs per node are the reference's parallelization
  substitution families (substitution.cc:1726-1868): data-parallel,
  partition-linear-combine (column TP), replicate-linear-reduce (row TP),
  partition-attention (head TP), expert partition; gated by the same flags
  (--enable-parameter-parallel etc., config.h:133-137).
- `base_optimize`-style refinement: best-first over single-segment config
  changes with a search budget and alpha pruning (substitution.cc:2229-2311).
- memory-aware search: per-chip memory validity (graph.cc:1983-2032) and the
  λ runtime/memory blend binary search (graph_optimize_task, 2056-2131).

Output is a `parallel.Strategy` consumed by FFModel.compile.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field
from typing import Optional

from jax.sharding import PartitionSpec

from ..fftype import OperatorType as OT, PARALLEL_OP_TYPES as _PARALLEL_OPS
from ..machine import AXIS_DATA, AXIS_MODEL, AXIS_SEQ, batch_axes_for
from ..parallel.strategies import Strategy
from .cost_model import (
    CostModel,
    _MakespanAccum,
    _axes_of,
    _shard_elems,
    _spec_to_assignment,
    classify_reshard,
    dtype_bytes,
    price_grad_sync,
    price_param_gather,
    price_parallel_node,
)
from .machine_model import TPUMachineModel


@dataclass(frozen=True)
class NodeConfig:
    """One parallelization choice for a node (the MachineView analog)."""

    name: str  # dp | tp_col | tp_row | tp_attn | ep | feat | xfer | xfer_comm
    out_assign: tuple        # output axis assignment
    weight_specs: tuple = () # ((weight_name, PartitionSpec), ...)
    # extra collective cost this config implies (e.g. row-parallel psum)
    psum_axes: tuple = ()
    # rewrite-pinned configs (joint search) carry the degree-derived input
    # assignments the rewritten node consumes, so reshard at the boundary
    # between searched and pinned regions is priced correctly
    in_assigns: Optional[tuple] = None


def _dp_assign(ndim, batch_ok=True, last_axes=(), batch_axes=(AXIS_DATA,)):
    a = [()] * ndim
    if ndim > 0 and batch_ok:
        a[0] = tuple(batch_axes)
    if last_axes and ndim > 1:
        a[-1] = tuple(last_axes)
    return tuple(a)


class UnitySearch:
    def __init__(self, graph, mesh, config, cost_model: CostModel,
                 segment_cache: Optional[dict] = None,
                 pinned: Optional[dict] = None, refine: bool = True):
        self.graph = graph
        self.mesh = mesh
        self.config = config
        self.cm = cost_model
        self.axis_sizes = dict(mesh.shape)
        self.model_deg = self.axis_sizes.get(AXIS_MODEL, 1)
        self.data_deg = self.axis_sizes.get(AXIS_DATA, 1)
        self.seq_deg = self.axis_sizes.get(AXIS_SEQ, 1)
        # multi-host meshes compose (dcn, data) on the batch dim; DCN-axis
        # collectives are priced at DCN bandwidth by the machine model
        self.batch_axes = batch_axes_for(self.axis_sizes)
        self.batch_deg = 1
        for ax in self.batch_axes:
            self.batch_deg *= self.axis_sizes.get(ax, 1)
        self.order = graph.topo_order()
        # {guid -> NodeConfig} fixed by a substitution rewrite (joint
        # search): the placement DP searches only the remaining free nodes
        self.pinned = pinned or {}
        # refinement can be disabled for inner joint-search evaluations
        # (only the winning candidate is refined)
        self.refine = refine
        # memoized segment costs keyed by (segment structure hash, boundary
        # configs, λ) — the SearchHelper::graph_cost memo (graph.cc:1586).
        # Shareable across UnitySearch instances (the joint search reuses
        # one cache across rewritten candidate graphs, so unchanged
        # segments cost nothing to re-evaluate).
        self._segment_cache: dict = (segment_cache if segment_cache
                                     is not None else {})
        self.cache_hits = 0
        self.evals = 0  # evaluate() calls — the search-effort telemetry

    # ---------------------------------------------------- candidate configs

    def _batch_entry(self):
        """The batch axes as one PartitionSpec entry (an axis name, or a
        tuple when dcn composes with data)."""
        return (self.batch_axes[0] if len(self.batch_axes) == 1
                else tuple(self.batch_axes))

    def node_configs(self, node) -> list[NodeConfig]:
        """Candidate parallelizations (substitution families)."""
        pin = self.pinned.get(node.guid)
        if pin is not None:
            return [pin]
        ndim = len(node.outputs[0].shape.dims) if node.outputs else 0
        batch_ok = (ndim > 0 and node.outputs and
                    node.outputs[0].shape.dims[0].size % max(1, self.batch_deg) == 0
                    and node.op_type != OT.OP_GROUP_BY)
        dp = NodeConfig("dp", _dp_assign(ndim, batch_ok,
                                          batch_axes=self.batch_axes))
        if node.op_type == OT.OP_INC_MULTIHEAD_ATTENTION and batch_ok:
            # cache-aware dp: the KV cache's slot dim rides the batch axes
            # (matching model._assign_strategy's serving default), so the
            # dp candidate is priced with the cache memory/IO per chip the
            # executor will actually place — a replicated-cache price here
            # would make dp look max_seq·slots-bytes heavier than it runs
            dp = NodeConfig("dp", dp.out_assign, tuple(
                (w.name, PartitionSpec(self._batch_entry(),
                                       *([None] * (len(w.shape) - 1))))
                for w in node.weight_specs if not w.trainable))
        # (the PAGED op's pool deliberately gets NO slot-dim entry: its
        # leading dim is physical blocks shared across slots — prefix
        # sharing means any slot may read any block, so the pool stays
        # whole on the batch axes and the dp price correctly charges the
        # full pool per chip; the feature dim is the searched dim below)
        out = [dp]
        if node.op_type == OT.OP_PIPE_BLOCKS:
            from ..machine import AXIS_PIPE

            pipe_deg = self.axis_sizes.get(AXIS_PIPE, 1)
            if pipe_deg > 1 and node.params.num_layers % pipe_deg != 0:
                # the runtime pipelines whenever the mesh has a pipe axis
                # (parallel/pipeline.py) and would reject this division at
                # dispatch — fail the candidate at costing so the mesh
                # factorization search prunes it instead of picking an
                # unexecutable shape
                raise ValueError(
                    f"{node.name}: {node.params.num_layers} blocks do not "
                    f"divide over pipe axis of size {pipe_deg}")
            if pipe_deg > 1:
                # pipeline parallelism over the pipe axis (EXCEEDS the
                # reference, whose OP_PIPELINE is enum-only): stacked block
                # weights shard their layer dim, the runtime executes the
                # ppermute fill/drain schedule (parallel/pipeline.py). This
                # is the ONLY config on a pipe-carrying mesh because the
                # runtime pipelines exactly when the mesh has a pipe axis —
                # costing anything else would diverge from execution. The
                # dp-vs-pp decision is made where it is executable: across
                # mesh factorizations (search/mesh_search.py).
                ws = tuple((w.name, PartitionSpec(AXIS_PIPE))
                           for w in node.weight_specs)
                return [NodeConfig("pp", dp.out_assign, ws)]
            return out
        if self.config.only_data_parallel or (
                self.model_deg <= 1 and self.seq_deg <= 1):
            return out
        allow_param = (self.model_deg > 1
                       and (self.config.enable_parameter_parallel
                            or self.config.search_budget > 0))
        allow_attr = (self.model_deg > 1
                      and (self.config.enable_attribute_parallel
                           or self.config.search_budget > 0))
        # seq/sample-dim families gate on the reference's sample-parallel
        # flag (config.h:134), like param/attr families gate on theirs
        allow_seq = (self.seq_deg > 1
                     and (self.config.enable_sample_parallel
                          or self.config.search_budget > 0))
        if node.op_type == OT.OP_LINEAR and allow_param:
            p = node.params
            if p.out_channels % self.model_deg == 0:
                out.append(NodeConfig(
                    "tp_col",
                    _dp_assign(ndim, batch_ok, last_axes=(AXIS_MODEL,),
                               batch_axes=self.batch_axes),
                    (("kernel", PartitionSpec(None, AXIS_MODEL)),
                     ("bias", PartitionSpec(AXIS_MODEL))),
                ))
            out.append(NodeConfig(
                "tp_row", _dp_assign(ndim, batch_ok,
                                      batch_axes=self.batch_axes),
                (("kernel", PartitionSpec(AXIS_MODEL, None)),
                 ("bias", PartitionSpec())),
                psum_axes=(AXIS_MODEL,),
            ))
        elif node.op_type == OT.OP_MULTIHEAD_ATTENTION:
            p = node.params
            if allow_attr and p.num_heads % self.model_deg == 0:
                ws = [(w, PartitionSpec(None, AXIS_MODEL))
                      for w in ("wq", "wk", "wv")]
                ws += [(b, PartitionSpec(AXIS_MODEL))
                       for b in ("bq", "bk", "bv")]
                ws += [("wo", PartitionSpec(AXIS_MODEL, None)),
                       ("bo", PartitionSpec())]
                out.append(NodeConfig(
                    "tp_attn",
                    _dp_assign(ndim, batch_ok, batch_axes=self.batch_axes),
                    tuple(ws),
                    psum_axes=(AXIS_MODEL,),
                ))
            if (getattr(p, "impl", "") == "ring" and ndim == 3
                    and allow_seq
                    and node.outputs[0].shape.dims[1].size
                    % self.seq_deg == 0):
                # sequence-parallel config (AXIS_SEQ): ring attention keeps
                # queries resident while K/V rotate over the seq axis, so
                # the (b, s, d) activation stays seq-sharded through the op
                # — the long-context capability the reference lacks
                # (SURVEY §5); paired with the "sp" pass-through below
                assign = list(_dp_assign(ndim, batch_ok,
                                         batch_axes=self.batch_axes))
                assign[1] = (AXIS_SEQ,)
                out.append(NodeConfig("sp", tuple(assign)))
        elif node.op_type in (OT.OP_INC_MULTIHEAD_ATTENTION,
                              OT.OP_PAGED_INC_MULTIHEAD_ATTENTION):
            p = node.params
            if (allow_attr and p.num_heads % self.model_deg == 0
                    and p.embed_dim % self.model_deg == 0):
                # head-parallel decode attention: QKV column-parallel, O
                # row-parallel (psum), and — the serving-specific dim —
                # the KV cache's feature axis sharded over `model` so each
                # chip stores and scans only its own heads' cache rows.
                # The KV-cache placement is thereby a searched parallel
                # dim priced by the same cost model as the projections.
                # Contiguous caches additionally ride the batch axes on
                # their slot dim; the paged POOL's leading dim is
                # slot-agnostic physical blocks (shared by prefix reuse),
                # so only its feature dim shards.
                paged = node.op_type == OT.OP_PAGED_INC_MULTIHEAD_ATTENTION
                ws = [(w, PartitionSpec(None, AXIS_MODEL))
                      for w in ("wq", "wk", "wv")]
                ws += [(b, PartitionSpec(AXIS_MODEL))
                       for b in ("bq", "bk", "bv")]
                ws += [("wo", PartitionSpec(AXIS_MODEL, None)),
                       ("bo", PartitionSpec())]
                ws += [(w.name, PartitionSpec(
                            None if paged else
                            (self._batch_entry() if batch_ok else None),
                            None, AXIS_MODEL))
                       for w in node.weight_specs if not w.trainable]
                out.append(NodeConfig(
                    "tp_attn",
                    _dp_assign(ndim, batch_ok, batch_axes=self.batch_axes),
                    tuple(ws),
                    psum_axes=(AXIS_MODEL,),
                ))
        elif node.op_type == OT.OP_CONV2D and allow_attr and ndim == 4:
            # channel/attribute-parallel conv (NCHW dim 1 over `model`,
            # OIHW kernel dim 0 sharded) — the conv sibling of tp_attn
            p = node.params
            if p.out_channels % self.model_deg == 0:
                assign = list(_dp_assign(ndim, batch_ok,
                                         batch_axes=self.batch_axes))
                assign[1] = (AXIS_MODEL,)
                ws = [("kernel", PartitionSpec(AXIS_MODEL, None, None, None))]
                if p.use_bias:
                    ws.append(("bias", PartitionSpec(AXIS_MODEL)))
                out.append(NodeConfig("tp_conv", tuple(assign), tuple(ws)))
        elif node.op_type == OT.OP_EXPERTS and allow_attr:
            p = node.params
            if p.n % self.model_deg == 0:
                ws = [("kernel", PartitionSpec(AXIS_MODEL, None, None))]
                if p.use_bias:
                    ws.append(("bias", PartitionSpec(AXIS_MODEL, None)))
                out.append(NodeConfig("ep",
                                      _dp_assign(ndim, batch_ok,
                                                 batch_axes=self.batch_axes),
                                      tuple(ws)))
        elif node.op_type == OT.OP_EMBEDDING and allow_param:
            p = node.params
            if p.out_channels % self.model_deg == 0:
                out.append(NodeConfig(
                    "tp_col",
                    _dp_assign(ndim, batch_ok, last_axes=(AXIS_MODEL,),
                               batch_axes=self.batch_axes),
                    (("kernel", PartitionSpec(None, AXIS_MODEL)),),
                ))
        elif node.op_type in (OT.OP_POOL2D, OT.OP_BATCHNORM) and ndim == 4:
            # channel passthrough so a tp_conv chain can stay sharded on
            # NCHW dim 1 between conv pairs
            dims = node.outputs[0].shape.dims
            if self.model_deg > 1 and dims[1].size % self.model_deg == 0:
                assign = list(_dp_assign(ndim, batch_ok,
                                         batch_axes=self.batch_axes))
                assign[1] = (AXIS_MODEL,)
                out.append(NodeConfig("chan", tuple(assign)))
        elif node.op_type in _FEATURE_ELEMENTWISE and ndim > 1:
            # pass-through configs so TP activations can stay sharded
            # across elementwise/norm ops between a col/row pair
            dims = node.outputs[0].shape.dims
            if self.model_deg > 1 and dims[-1].size % self.model_deg == 0:
                out.append(NodeConfig(
                    "feat", _dp_assign(ndim, batch_ok,
                                       batch_axes=self.batch_axes,
                                       last_axes=(AXIS_MODEL,)),
                ))
            if (ndim == 3 and allow_seq
                    and dims[1].size % self.seq_deg == 0):
                # seq-sharded pass-through between ring-attention ops
                assign = list(_dp_assign(ndim, batch_ok,
                                         batch_axes=self.batch_axes))
                assign[1] = (AXIS_SEQ,)
                out.append(NodeConfig("sp", tuple(assign)))
        return out

    # ---------------------------------------------------- strategy evaluation

    def evaluate(self, choice: dict, only=None,
                 collect=None) -> tuple[float, float]:
        """(makespan seconds, peak per-chip memory bytes) of a full
        assignment {guid -> NodeConfig} — the simulate_runtime analog:
        per-node compute serializes across the chip set while communication
        overlaps other ops' compute, so the result is
        max(sum compute, critical path of compute+comm) via graph_makespan
        (native ff_eval_makespan), not an additive sum — concurrent
        branches (DLRM towers) are priced at max(paths). `only` restricts
        accumulation to a guid subset (segment costing): configs outside it
        still feed reshard classification but don't contribute cost.

        `collect`, when an EMPTY list, receives one dict per accumulated
        node with the full cost attribution (forward/backward/sync/reshard/
        collective seconds, per-chip memory bytes, comm axes) in
        accumulation order — the substrate of the strategy explain report
        (diagnostics/explain). Each entry also carries the accumulator's
        actual per-task (compute_s, comm_s, comm_axis_id) so the report
        reproduces the evaluator's makespan by construction, not by
        re-deriving the accumulation rules."""
        self.evals += 1
        acc = _MakespanAccum(
            overlap_sync=self.config.search_overlap_backward_update)
        mem = 0.0
        # stage-3 transient gather working set: at most two gathered
        # layers in flight (the current layer + the one-ahead prefetch),
        # charged once per plan at the LARGEST node's gathered bytes
        gather_peak = 0.0
        for node in self.order:
            if node.op_type in (OT.OP_INPUT, OT.OP_WEIGHT, OT.OP_NOOP):
                continue
            cfg = choice.get(node.guid)
            if cfg is None:
                continue
            if only is not None and node.guid not in only:
                continue
            if node.op_type in _PARALLEL_OPS:
                # explicit parallel-op node (joint search over rewritten
                # graphs): zero compute, collective comm (SURVEY §2.3);
                # a mismatched free producer additionally pays the reshard
                # into the degree-derived input placement
                comm, comm_axes = price_parallel_node(node, self.cm.machine)
                if cfg.in_assigns:
                    for e in sorted(self.graph.in_edges[node.guid],
                                    key=lambda e: e.dst_idx):
                        src = self.graph.nodes[e.src]
                        src_cfg = choice.get(src.guid)
                        if src_cfg is None or e.dst_idx >= len(cfg.in_assigns):
                            continue
                        pt = src.outputs[e.src_idx]
                        shape = tuple(d.size for d in pt.shape.dims
                                      if not d.is_replica_dim)
                        comm += classify_reshard(
                            shape, src_cfg.out_assign,
                            cfg.in_assigns[e.dst_idx], pt.dtype,
                            self.cm.machine)
                acc.add(node.guid, 0.0, comm, comm_axes=comm_axes)
                if collect is not None:
                    collect.append({
                        "guid": node.guid, "name": node.name,
                        "op_type": node.op_type.name, "config": cfg.name,
                        "forward_s": 0.0, "backward_s": 0.0, "sync_s": 0.0,
                        "reshard_s": 0.0, "collective_s": comm,
                        "memory_bytes": 0.0, "comm_axes": list(comm_axes)})
                continue
            in_shapes, in_assigns, reshard = [], [], 0.0
            for e in sorted(self.graph.in_edges[node.guid],
                            key=lambda e: e.dst_idx):
                src = self.graph.nodes[e.src]
                src_cfg = choice.get(src.guid)
                src_assign = (src_cfg.out_assign if src_cfg
                              else _dp_assign(
                                  len(src.outputs[e.src_idx].shape.dims),
                                  batch_axes=self.batch_axes))
                shape = tuple(d.size for d in
                              src.outputs[e.src_idx].shape.dims
                              if not d.is_replica_dim)
                in_shapes.append(shape)
                in_assigns.append(src_assign)
                # consumer's expected input spec: tp_row expects the feature
                # dim sharded (no reshard after tp_col); dp expects batch
                expected = self._expected_input(node, cfg, e.dst_idx,
                                                len(shape))
                if expected is not None:
                    reshard += classify_reshard(
                        shape, src_assign, expected,
                        src.outputs[e.src_idx].dtype, self.cm.machine)
            cm = self.cm.op_cost(node, [cfg.out_assign] * len(node.outputs),
                                 dict(cfg.weight_specs), in_shapes,
                                 in_assigns)
            psum = 0.0
            for ax in cfg.psum_axes:
                out_pt = node.outputs[0]
                shard_bytes = _shard_elems(
                    tuple(d.size for d in out_pt.shape.dims
                          if not d.is_replica_dim),
                    cfg.out_assign, self.axis_sizes) * dtype_bytes(out_pt.dtype)
                psum += self.cm.machine.all_reduce(shard_bytes, ax)
            comm_axes = tuple(cfg.psum_axes)
            overlap_comm = 0.0
            overlap_overhead = 0.0
            if (cfg.name == "sp"
                    and node.op_type == OT.OP_MULTIHEAD_ATTENTION):
                # ring attention's defining cost: K and V blocks rotate
                # (seq_deg − 1) neighbor hops per forward, and the backward
                # re-rotates them (≈2× fwd) — priced as ppermute traffic of
                # the local activation block (parallel/ring_attention.py).
                # rotate, not ppermute: the K/V shift includes the wrap
                # pair, which a non-wraparound (open) seq axis pays as a
                # full line traversal (TorusMachineModel.rotate); the
                # calibrated hop (collective_rotate) overrides the analytic
                # guess when the warm-start DB carries a measurement.
                out_pt = node.outputs[0]
                local_bytes = _shard_elems(
                    tuple(d.size for d in out_pt.shape.dims
                          if not d.is_replica_dim),
                    cfg.out_assign, self.axis_sizes) * dtype_bytes(out_pt.dtype)
                hops = 2 * (self.seq_deg - 1)  # K and V, fwd
                ring_comm = 3.0 * hops * self.cm.collective_rotate(
                    local_bytes, AXIS_SEQ)
                comm_axes = comm_axes + (AXIS_SEQ,)
                if getattr(self.config, "overlap_collectives", True):
                    # the runtime issues each hop before the block compute
                    # it overlaps (double-buffered ppermute pipeline), so
                    # the honest price is max(compute, comm) plus the
                    # fixed per-hop issue latency that never hides
                    overlap_comm = ring_comm
                    overlap_overhead = (
                        3.0 * hops * self.cm.machine._lat(AXIS_SEQ))
                else:
                    psum += ring_comm
            grad_sync = cm.sync_time + cm.update_sync_time
            # the shared update-mode pricing rules (cost_model.
            # price_grad_sync / price_param_gather — also what
            # choose_update_sharding decides through, via
            # evaluate_assigned_graph)
            sync_arg, gs_overlap, gs_overhead, grad_sync_sharded = (
                price_grad_sync(cm, self.cm.update_sharding,
                                self.cm.overlap_update))
            pg_serial, pg_overlap, pg_overhead, param_gather_s = (
                price_param_gather(cm, self.cm.overlap_update))
            overlap_comm += gs_overlap + pg_overlap
            overlap_overhead += gs_overhead + pg_overhead
            compute_t = cm.forward_time + cm.backward_time
            if (cfg.name == "pp"
                    and node.op_type == OT.OP_PIPE_BLOCKS):
                # fill/drain bubble + stage hand-off pricing for the
                # ppermute pipeline (parallel/pipeline.py): the ideal
                # per-chip compute T/(data·P) (already reflected in
                # op_cost's sharded flops) stretches by (M+P−1)/M — this
                # INCLUDES the placeholder compute every stage burns
                # during fill/drain ticks (SPMD executes everywhere) —
                # and each of the ~3·(M+P−1) fwd+bwd ticks hands one
                # microbatch activation to the next stage over a neighbor
                # ICI link.
                from ..machine import AXIS_PIPE

                p = node.params
                P = self.axis_sizes.get(AXIS_PIPE, 1)
                M = p.num_microbatches or 2 * P
                compute_t *= (M + P - 1) / M
                out_pt = node.outputs[0]
                mb_bytes = (_shard_elems(
                    tuple(d.size for d in out_pt.shape.dims
                          if not d.is_replica_dim),
                    cfg.out_assign, self.axis_sizes)
                    * dtype_bytes(out_pt.dtype) / M)
                psum += 3.0 * (M + P - 1) * self.cm.machine.ppermute(
                    mb_bytes, AXIS_PIPE)
                comm_axes = comm_axes + (AXIS_PIPE,)
            if not comm_axes and (grad_sync > 0
                                  or cm.param_gather_time > 0):
                comm_axes = (AXIS_DATA,)  # gradient sync rides `data`
            acc.add(node.guid,
                    compute_t,
                    cm.comm_time + reshard + psum + pg_serial,
                    comm_axes=comm_axes, sync=sync_arg,
                    overlappable_comm=overlap_comm,
                    overlap_overhead=overlap_overhead)
            mem += cm.memory
            gather_peak = max(gather_peak, cm.gather_bytes)
            if collect is not None:
                # compute_t may carry the pipeline bubble stretch; report
                # the stretched split so entries still sum to compute_t
                stretch = (compute_t
                           / max(cm.forward_time + cm.backward_time, 1e-30))
                collect.append({
                    "guid": node.guid, "name": node.name,
                    "op_type": node.op_type.name, "config": cfg.name,
                    "forward_s": cm.forward_time * stretch,
                    "backward_s": cm.backward_time * stretch,
                    "sync_s": sync_arg,
                    "reshard_s": reshard,
                    "collective_s": cm.comm_time + psum + pg_serial,
                    # overlap-capable collective traffic (hidden behind
                    # this op's compute; still occupies its ICI axis) —
                    # ring hops plus, under weight-update sharding, the
                    # grad RS+AG (grad_sync_s names that share)
                    "overlap_s": overlap_comm,
                    "overlap_overhead_s": overlap_overhead,
                    "grad_sync_s": grad_sync_sharded,
                    # stage-3 just-in-time weight gathers (fwd + bwd
                    # re-gather): inside overlap_s when overlapped,
                    # inside this node's comm when serial
                    "param_gather_s": param_gather_s,
                    "update_shards": cm.update_shards,
                    "memory_bytes": cm.memory,
                    "comm_axes": list(comm_axes)})
        if collect is not None:
            # entries align 1:1 with the accumulator's task arrays (both
            # append once per accumulated node, in self.order)
            for d, c, q, ax in zip(collect, acc.compute, acc.comm,
                                   acc.axis):
                d["compute_s"] = c
                d["comm_s"] = q
                d["comm_axis_id"] = ax
        # stage 3: the per-node memory dropped the resident gathered
        # copies; charge the double-buffered gather working set once
        mem += 2.0 * gather_peak
        return acc.makespan(self.graph.in_edges), mem

    def _expected_input(self, node, cfg, dst_idx, ndim):
        """The input spec a config consumes (None = producer's choice OK).

        Applies to EVERY input edge, not just input 0 — multi-input ops
        (aggregate's expert outputs, element-binary towers, concat) must
        pay the reshard their secondary operands need, otherwise e.g. a
        feature-sharded expert output flows into a dp aggregate for free
        and the search underprices unfused plans."""
        if cfg.in_assigns is not None:  # rewrite-pinned: degree-derived
            if dst_idx < len(cfg.in_assigns):
                return cfg.in_assigns[dst_idx]
            return None
        if cfg.name == "tp_row":
            if dst_idx == 0:
                return _dp_assign(ndim, True, last_axes=(AXIS_MODEL,),
                                  batch_axes=self.batch_axes)
            return _dp_assign(ndim, True, batch_axes=self.batch_axes)
        if cfg.name in ("dp", "tp_col", "tp_attn", "tp_conv", "ep", "pp"):
            # tp_conv included: an O-sharded kernel consumes the FULL input
            # channels, so a chan-sharded producer pays a real all-gather;
            # pp consumes the plain batch-sharded activation (stage weights
            # ride pipe, activations ride data)
            return _dp_assign(ndim, True, batch_axes=self.batch_axes)
        if cfg.name in ("feat", "chan", "sp") and len(cfg.out_assign) == ndim:
            # pass-through configs consume their own (sharded) layout
            return cfg.out_assign
        return None

    # ---------------------------------------------------- bottleneck DP

    def bottlenecks(self) -> list:
        """Nodes every source→sink path crosses (the sequence-split points,
        graph.cc find_bottleneck_node)."""
        from ..pcg.graph import find_bottlenecks

        return find_bottlenecks(self.graph, self.order)

    def run(self) -> dict:
        """Memoized sequence DP over bottleneck-node configs — the
        find_optimal_sequence_graph_time recursion flattened
        (graph.cc:115-180, 1586-1843): the graph is cut at bottleneck
        nodes; the DP state is the config of the cut node's tensor; each
        segment's interior is optimized once per (in-config, out-config)
        boundary pair and memoized by segment *structure*, so repeated
        transformer blocks (and unchanged segments across rewritten
        candidate graphs) hit the cache. Best-first refinement afterwards
        (base_optimize analog). Returns {guid -> NodeConfig}."""
        from .. import telemetry

        with telemetry.span("unity.dp", nodes=len(self.order)):
            choice = self._run_dp()
        telemetry.counter("unity.search_effort", {
            "evals": self.evals, "cache_hits": self.cache_hits})
        return choice

    def _run_dp(self) -> dict:
        segments = self._split_segments()
        if len(segments) <= 1:
            choice: dict = {}
            for seg in segments:
                choice.update(self._optimize_segment(seg, choice))
            return self._refine(choice) if self.refine else choice
        # dp: {boundary NodeConfig -> (cumulative cost, full choice)}
        dp: dict = {None: (0.0, {})}
        prev_bn = None
        for k, seg in enumerate(segments):
            bn = seg[-1]
            last = k == len(segments) - 1
            # the sink segment's boundary is unconstrained (its configs are
            # chosen by the interior optimization)
            out_cfgs = [None] if last else self.node_configs(bn)
            ndp: dict = {}
            for in_cfg, (prev_cost, prev_choice) in dp.items():
                for out_cfg in out_cfgs:
                    seg_choice, seg_cost = self._segment_cost(
                        seg, in_cfg, out_cfg, prev_bn)
                    tot = prev_cost + seg_cost
                    cur = ndp.get(out_cfg)
                    if cur is None or tot < cur[0]:
                        full = dict(prev_choice)
                        full.update(seg_choice)
                        ndp[out_cfg] = (tot, full)
            dp = ndp
            prev_bn = bn
        _, best_choice = min(dp.values(), key=lambda t: t[0])
        return self._refine(best_choice) if self.refine else best_choice

    def _split_segments(self):
        cuts = {n.guid for n in self.bottlenecks()}
        segments, cur = [], []
        for n in self.order:
            cur.append(n)
            if n.guid in cuts and len(cur) >= self.config.base_optimize_threshold:
                segments.append(cur)
                cur = []
        if cur:
            segments.append(cur)
        return segments

    def _segment_key(self, seg):
        """Structural hash of a segment: op types/params/output shapes +
        internal wiring + external input shapes. Two segments with equal
        keys have identical cost surfaces, so (key, boundary configs) fully
        determines the memoized optimum — the reference memoizes graph_cost
        by (subgraph hash, source/sink MachineViews)."""
        idx = {n.guid: i for i, n in enumerate(seg)}
        parts = []
        for n in seg:
            edges = []
            for e in sorted(self.graph.in_edges[n.guid],
                            key=lambda e: e.dst_idx):
                src = self.graph.nodes[e.src]
                if e.src in idx:
                    edges.append((idx[e.src], e.src_idx, e.dst_idx))
                else:  # external producer: its full PARALLEL shape (degrees
                    # + replica dims, not just logical sizes) drives both
                    # reshard cost and any rewrite-pinned configs inside the
                    # segment, so it must be part of the key — two joint-
                    # search candidates can agree on logical shapes but
                    # differ in boundary parallel state
                    pt = src.outputs[e.src_idx]
                    edges.append((-1, repr(pt.shape), e.dst_idx))
            parts.append((n.op_type, repr(n.params),
                          tuple(repr(pt.shape) for pt in n.outputs),
                          tuple(edges)))
        return hash(tuple(parts))

    def _segment_cost(self, seg, in_cfg, out_cfg, prev_bn):
        """Memoized optimal (choice, cost) of one segment under fixed
        boundary configs. Bottleneck cuts guarantee every edge crossing the
        cut leaves the bottleneck node itself, so (in_cfg, out_cfg) is the
        complete external context."""
        lam = getattr(self, "_lambda", 0.0)
        key = (self._segment_key(seg), in_cfg, out_cfg, lam)
        hit = self._segment_cache.get(key)
        if hit is not None:
            self.cache_hits += 1
            cfgs, cost = hit
            return {n.guid: c for n, c in zip(seg, cfgs)}, cost
        context = ({prev_bn.guid: in_cfg}
                   if prev_bn is not None and in_cfg is not None else {})
        pinned = {seg[-1].guid: out_cfg} if out_cfg is not None else {}
        choice = self._optimize_segment(seg, context, pinned)
        only = {n.guid for n in seg}
        full = dict(context)
        full.update(choice)
        cost, mem = self.evaluate(full, only=only)
        cost = self._memory_penalized(cost, mem)
        self._segment_cache[key] = (tuple(choice[n.guid] for n in seg), cost)
        return choice, cost

    def _optimize_segment(self, seg, context: dict,
                          pinned: Optional[dict] = None) -> dict:
        """Jointly enumerate configs for interesting nodes in the segment
        (the nonsequence exhaustive split); pass-through nodes follow.
        `pinned` fixes boundary-node configs chosen by the outer DP."""
        pinned = pinned or {}
        interesting = [n for n in seg
                       if n.guid not in pinned
                       and len(self.node_configs(n)) > 1]
        base = {n.guid: self.node_configs(n)[0] for n in seg}
        base.update(pinned)
        if not interesting:
            return base
        only = {n.guid for n in seg}
        # cap the joint enumeration (reference caps via threshold + DP)
        cap = 6
        heads, tail = interesting[:cap], interesting[cap:]
        best, best_cost = base, None
        for combo in itertools.product(
                *(self.node_configs(n) for n in heads)):
            cand = dict(base)
            for n, cfg in zip(heads, combo):
                cand[n.guid] = cfg
            self._propagate_feature_chains(seg, cand)
            cand.update(pinned)
            full = dict(context)
            full.update(cand)
            cost, mem = self.evaluate(full, only=only)
            cost = self._memory_penalized(cost, mem)
            if best_cost is None or cost < best_cost:
                best, best_cost = cand, cost
        for n in tail:  # greedy for the rest
            cands = self.node_configs(n)
            cur_best, cur_cost = None, None
            for cfg in cands:
                cand = dict(best)
                cand[n.guid] = cfg
                full = dict(context)
                full.update(cand)
                cost, mem = self.evaluate(full, only=only)
                cost = self._memory_penalized(cost, mem)
                if cur_cost is None or cost < cur_cost:
                    cur_best, cur_cost = cand, cost
            best = cur_best
        return best

    def _propagate_feature_chains(self, seg, cand):
        """Between a tp_col producer and a tp_row consumer, flip elementwise
        nodes to their 'feat' config so the sharded activation survives."""
        by_guid = {n.guid: n for n in seg}
        for n in seg:
            cfg = cand.get(n.guid)
            if cfg is None or cfg.name != "tp_row":
                continue
            # walk the first-input chain upward while elementwise
            cur = n
            while True:
                edges = self.graph.in_edges[cur.guid]
                if not edges:
                    break
                src = self.graph.nodes[sorted(edges,
                                              key=lambda e: e.dst_idx)[0].src]
                if src.guid not in by_guid:
                    break
                scfg = cand.get(src.guid)
                if scfg and scfg.name in ("tp_col", "tp_attn"):
                    break
                feats = [c for c in self.node_configs(src)
                         if c.name == "feat"]
                if not feats:
                    break
                cand[src.guid] = feats[0]
                cur = src

    def _memory_penalized(self, cost, mem):
        cap = self.cm.machine.chip.hbm_bytes
        if mem > cap:
            # invalid strategy: harsh penalty (is_valid_strategy analog)
            return cost * (1.0 + 10.0 * (mem - cap) / cap)
        if self.config.perform_memory_search:
            lam = getattr(self, "_lambda", 0.0)
            return cost * (1 - lam) + lam * cost * (mem / cap)
        return cost

    def _refine(self, choice: dict) -> dict:
        """Budgeted best-first single-node moves (base_optimize analog)."""
        from .. import telemetry

        budget = self.config.search_budget or 8
        alpha = self.config.search_alpha
        best = dict(choice)
        cost0, mem0 = self.evaluate(best)
        best_cost = self._memory_penalized(cost0, mem0)
        frontier = [best]
        seen = set()
        for _ in range(budget):
            if not frontier:
                break
            cur = frontier.pop(0)
            for node in self.order:
                for cfg in self.node_configs(node)[1:]:
                    if cur.get(node.guid) is cfg:
                        continue
                    cand = dict(cur)
                    cand[node.guid] = cfg
                    key = tuple(sorted((g, c.name) for g, c in cand.items()))
                    if key in seen:
                        continue
                    seen.add(key)
                    cost, mem = self.evaluate(cand)
                    cost = self._memory_penalized(cost, mem)
                    if cost < best_cost:
                        best, best_cost = cand, cost
                        frontier.append(cand)
                        # best-cost-so-far curve: one counter sample per
                        # improvement, visible as a descending staircase
                        telemetry.counter(
                            "unity.best_cost_ms",
                            {"cost": best_cost * 1e3})
                    elif cost < best_cost * alpha:
                        frontier.append(cand)
        return best

    # ---------------------------------------------------- emission

    def to_strategy(self, choice: dict) -> Strategy:
        """Choice → exportable Strategy. Rewrite-pinned compute configs
        ("xfer") are included in their logical-rank form: under GSPMD the
        same placements expressed as plain per-node specs on the ORIGINAL
        graph execute identically (the inserted Replicate/Reduction nodes
        become implicit collectives), so an exported plan replays without
        the rewritten graph. Parallel-op nodes ("xfer_comm") are therefore
        skipped — their effect is carried by their neighbors' specs."""
        s = Strategy()
        for node in self.order:
            cfg = choice.get(node.guid)
            if cfg is None or cfg.name in ("dp", "xfer_comm"):
                continue
            for i in range(len(node.outputs)):
                s.set_output(node.name, i, cfg.out_assign)
            declared = {ws.name for ws in node.weight_specs}
            for wname, spec in cfg.weight_specs:
                if wname in declared:
                    s.set_weight(node.name, wname, spec)
        return s


_FEATURE_ELEMENTWISE = frozenset({
    OT.OP_RELU, OT.OP_GELU, OT.OP_SIGMOID, OT.OP_TANH, OT.OP_ELU,
    OT.OP_IDENTITY, OT.OP_DROPOUT, OT.OP_SCALAR_MULTIPLY, OT.OP_SCALAR_ADD,
    OT.OP_SCALAR_SUB, OT.OP_SCALAR_TRUE_DIV, OT.OP_LAYERNORM, OT.OP_SOFTMAX,
    OT.OP_EW_ADD, OT.OP_EW_MUL,
})


def mcmc_optimize(search: UnitySearch, budget: int = 1000,
                  alpha: float = 0.05, seed: int = 0) -> dict:
    """Legacy pre-Unity MCMC strategy search (FFModel::mcmc_optimize,
    model.cc:3285-3357, exposed via STRATEGY_SEARCH_TASK_ID): simulated
    annealing over per-node configs starting from data parallel — a random
    single-node rewrite per iteration, accepted when cheaper or with
    probability exp(-alpha·Δµs) (Δ is in seconds here where the reference
    simulator works in ~µs-scale units, hence the 1e6 factor below), with a
    periodic reset to the incumbent (reset_span = clamp(budget/100, 1,
    1000)). Returns {guid -> NodeConfig}; superseded by the joint Unity
    search but kept for parity."""
    import random

    rng = random.Random(seed)
    mutable = [n for n in search.order if len(search.node_configs(n)) > 1]

    def cost_of(choice):
        t, mem = search.evaluate(choice)
        return search._memory_penalized(t, mem)

    best = {n.guid: search.node_configs(n)[0] for n in search.order
            if search.node_configs(n)}
    best_cost = cost_of(best)
    current, current_cost = dict(best), best_cost
    if not mutable:
        return best
    reset_span = min(max(budget // 100, 1), 1000)
    last_reset = 0
    for it in range(budget + 1):
        if it - last_reset >= reset_span:
            current, current_cost = dict(best), best_cost
            last_reset = it
        node = rng.choice(mutable)
        cfgs = search.node_configs(node)
        nxt = dict(current)
        nxt[node.guid] = rng.choice(cfgs)
        nxt_cost = cost_of(nxt)
        if nxt_cost < best_cost:
            best, best_cost = dict(nxt), nxt_cost
        if nxt_cost < current_cost or (
                rng.random() < math.exp(
                    -alpha * max(0.0, nxt_cost - current_cost) * 1e6)):
            current, current_cost = nxt, nxt_cost
    return best


def mcmc_search_strategy(graph, mesh, config,
                         cost_model: Optional[CostModel] = None,
                         alpha: float = 0.05) -> Strategy:
    """MCMC entry returning a Strategy (the STRATEGY_SEARCH_TASK_ID
    surface). `alpha` is the annealing temperature coefficient (reference
    default 0.05) — deliberately NOT config.search_alpha, which is the
    best-first pruning slack with a completely different scale."""
    from .machine_model import machine_model_for_mesh

    cm = cost_model or CostModel(machine_model_for_mesh(mesh))
    search = UnitySearch(graph, mesh, config, cm)
    budget = config.search_budget or 1000
    choice = mcmc_optimize(search, budget=budget, alpha=alpha,
                           seed=config.seed)
    return search.to_strategy(choice)


def lambda_memory_search(make_search, hbm_bytes: float, iters: int = 5):
    """λ binary search between pure-runtime and memory-lean strategies
    (graph_optimize_task, graph.cc:2056-2131). `make_search()` supplies the
    UnitySearch for each probe (callers reuse one instance or rebuild a
    pinned one); λ is part of the segment-cache key, so every probe
    re-optimizes under its own blended objective. Returns (choice, search)
    of the lightest feasible probe — or of the last probe when none fits,
    matching the reference's fall-through when even λ=1 exceeds memory."""
    lo, hi = 0.0, 1.0
    best = None
    last = None
    for _ in range(iters):
        mid = (lo + hi) / 2
        s = make_search()
        s._lambda = mid
        choice = s.run()
        _, mem = s.evaluate(choice)
        last = (choice, s)
        if mem > hbm_bytes:
            lo = mid
        else:
            best = (choice, s)
            hi = mid
    return best or last


def choose_update_sharding(graph, mesh, config,
                           cost_model: Optional[CostModel] = None,
                           opt_slots: int = 1) -> dict:
    """Decide how the weight update runs — replicated, ZeRO stage 2
    (masters/grads/optimizer slots at 1/dp, Xu et al. 2020), or ZeRO-3 /
    FSDP stage 3 (the trainable weights themselves sharded at rest with
    just-in-time per-layer gathers, Rajbhandari et al. SC'20; Zhao et
    al. VLDB'23) — the update-dimension half of the Unity search, priced
    by the same evaluator after the per-node placements are materialized
    on the graph.

    All three candidates move comparable ring bytes (allreduce ≡ RS+AG;
    stage 3 re-gathers on the backward), so the decision is exactly the
    papers' tradeoff: stage 2 wins when the plan is GRAD-SYNC-BOUND (the
    overlappable channel hides the pair behind backward compute while
    the replicated allreduce serializes) or MEMORY-BOUND (masters +
    slots at 1/dp bring the plan under the per-chip HBM cap); stage 3
    wins exactly when the plan is memory-bound past stage 2 — the
    RESIDENT GATHERED COPIES (per-chip model bytes flat in dp) are
    themselves over the cap, and 1/shards-at-rest weights plus at most
    two gathered layers in flight are what fits; replicated wins when
    the model is so small that the pair's fixed per-hop issue latency
    exceeds the sync it hides (the 2% margin keeps tiny CI models on
    the replicated baseline rather than flip-flopping on pricing
    noise). `--weight-update-sharding[=stage3|stage2|off]` /
    `--no-weight-update-sharding` force the outcome; every trajectory
    is bit-identical, so forcing is always safe.

    Returns the decision record the model stashes (`_update_sharding`),
    checkpoint manifests embed, and strategy_report.json surfaces —
    including `stage` (0 | 2 | 3). As a side effect the cost model is
    left pricing the CHOSEN update mode, so the explain report / drift
    monitor describe the running config."""
    from ..fftype import CompMode
    from ..machine import batch_axes_for
    from .machine_model import machine_model_for_mesh
    from .substitution import evaluate_assigned_graph

    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    axes = batch_axes_for(axis_sizes)
    shards = 1
    for ax in axes:
        shards *= axis_sizes.get(ax, 1)
    decision = {
        "enabled": False,
        "stage": 0,
        "shards": shards,
        "axes": list(axes),
        "forced": config.weight_update_sharding,
        "forced_stage": config.weight_update_stage,
    }
    trainable = any(
        ws.trainable
        for n in graph.topo_order()
        if not getattr(n, "weight_source", None)
        for ws in n.weight_specs)
    if (shards <= 1 or not trainable
            or config.computation_mode != CompMode.COMP_MODE_TRAINING):
        decision["reason"] = ("no_grad_sync" if shards <= 1 or not trainable
                              else "inference")
        return decision
    cm = cost_model or CostModel(
        machine_model_for_mesh(mesh, num_hosts=config.num_nodes),
        opt_slots=opt_slots)
    cap = (config.device_mem if config.device_mem > 0
           else cm.machine.chip.hbm_bytes)

    def _priced(stage: int, totals=None):
        cm.update_sharding = stage >= 2
        cm.param_gather = stage >= 3
        cm.overlap_update = stage >= 2 and bool(config.overlap_collectives)
        # same overlap_sync the real evaluator prices with — the decision
        # and the strategy report must read the same makespan rule
        t, mem = evaluate_assigned_graph(
            graph, mesh, cm,
            overlap_sync=bool(config.search_overlap_backward_update),
            totals=totals)
        pen = t * (1.0 + 10.0 * (mem - cap) / cap) if mem > cap else t
        return t, mem, pen

    rep_totals: dict = {}
    t_rep, mem_rep, c_rep = _priced(0, totals=rep_totals)
    t_s2, mem_s2, c_s2 = _priced(2)
    s3_totals: dict = {}
    t_s3, mem_s3, c_s3 = _priced(3, totals=s3_totals)
    sync_frac = (rep_totals.get("sync_s", 0.0) / t_rep if t_rep > 0
                 else 0.0)
    # the ONE stage-3 trigger, shared by auto and the bare force-on: the
    # resident gathered copies of stage 2 are over the per-chip cap and
    # the 1/shards-at-rest pricing is actually cheaper under the penalty
    stage3_memory_bound = mem_s2 > cap and c_s3 < c_s2
    if config.weight_update_sharding is not None:
        # forced (every trajectory is bit-identical, so forcing is
        # always safe); the candidates are still all priced so the
        # decision record / bench ablation carry the comparison
        enabled = config.weight_update_sharding
        if not enabled:
            stage = 0
        elif config.weight_update_stage in (2, 3):
            stage = config.weight_update_stage
        else:
            # bare legacy --weight-update-sharding: sharded forced on,
            # the stage still priced
            stage = 3 if stage3_memory_bound else 2
        decision["reason"] = "flag"
    elif config.weight_update_stage == 0:
        # stage forced to replicated (programmatic weight_update_stage=0
        # without the boolean flag): honored exactly like =off
        enabled = False
        stage = 0
        decision["reason"] = "flag"
    else:
        # grad-sync-bound: the replicated allreduce is a material slice
        # (≥10%) of the predicted step AND the overlappable pricing is
        # ≥2% cheaper — tiny models whose sync the hop latency would
        # dominate stay replicated rather than flip-flop on noise
        memory_bound = mem_rep > cap and min(c_s2, c_s3) < c_rep
        overlap_bound = c_s2 < 0.98 * c_rep and sync_frac >= 0.1
        enabled = memory_bound or overlap_bound
        if not enabled:
            stage = 0
            decision["reason"] = "replicated_cheaper"
        elif config.weight_update_stage in (2, 3):
            # enablement stayed auto, but a set weight_update_stage PINS
            # the stage used when sharding wins (the documented 2/3 =
            # forced contract — e.g. cap at stage 2 programmatically)
            stage = config.weight_update_stage
            decision["reason"] = ("memory_bound" if memory_bound
                                  else "overlap_bound")
        elif stage3_memory_bound:
            stage = 3
            decision["reason"] = "memory_bound"
        else:
            stage = 2
            decision["reason"] = ("memory_bound" if memory_bound
                                  else "overlap_bound")
    decision["enabled"] = bool(enabled) and stage >= 2
    decision["stage"] = stage if decision["enabled"] else 0
    t_sh, mem_sh, c_sh = ((t_s3, mem_s3, c_s3) if stage == 3
                          else (t_s2, mem_s2, c_s2))
    decision["predicted"] = {
        "replicated_s": t_rep, "sharded_s": t_sh,
        "replicated_cost_s": c_rep, "sharded_cost_s": c_sh,
        "replicated_mem_bytes": mem_rep, "sharded_mem_bytes": mem_sh,
        "stage2_s": t_s2, "stage3_s": t_s3,
        "stage2_cost_s": c_s2, "stage3_cost_s": c_s3,
        "stage2_mem_bytes": mem_s2, "stage3_mem_bytes": mem_s3,
        "param_gather_s": s3_totals.get("param_gather_s", 0.0),
        "grad_sync_fraction": sync_frac,
        "hbm_cap_bytes": cap,
    }
    # leave the cost model pricing the chosen mode (the strategy report
    # and the drift monitor's predicted makespan must describe what runs)
    cm.update_sharding = decision["enabled"]
    cm.param_gather = decision["stage"] == 3
    cm.overlap_update = (decision["enabled"]
                         and bool(config.overlap_collectives))
    return decision


def search_strategy(graph, mesh, config,
                    machine: Optional[TPUMachineModel] = None,
                    cost_model: Optional[CostModel] = None) -> Strategy:
    """Entry point: GRAPH_OPTIMIZE_TASK analog (graph.cc:2046). Runs the DP
    + refinement, with the λ memory binary search when requested."""
    from .machine_model import machine_model_for_mesh

    machine = machine or machine_model_for_mesh(mesh)
    cm = cost_model or CostModel(machine)
    search = UnitySearch(graph, mesh, config, cm)
    if config.perform_memory_search:
        choice, search = lambda_memory_search(
            lambda: search, machine.chip.hbm_bytes)
    else:
        choice = search.run()
    return search.to_strategy(choice)
