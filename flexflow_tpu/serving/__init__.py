"""Serving engine on the PCG (docs/serving.md).

The inference half the reference snapshot predates: `model.serve()`
compiles a *decode* graph from the same PCG the trainer built — causal
attention becomes incremental attention over first-class sharded KV-cache
state, placed and priced by the same Unity search and warm-started by the
same plan cache — and runs Orca-style continuous batching over a fixed
slot set with greedy/temperature sampling, EOS/max-length completion, and
per-request time-to-first-token telemetry. The default KV layout is a
PAGED block pool + per-slot page tables with copy-on-write prefix sharing
(paged.BlockManager; `--serve-kv-layout contiguous` is the bit-identical
ablation), and prefill proceeds one bucketed chunk per iteration,
interleaved with the in-flight decodes.

    engine = model.serve(slots=8, max_new_tokens=64)
    outputs = engine.generate(prompts)          # batch convenience
    req = engine.submit(prompt); engine.step()  # iteration-level control
"""

from .decode_graph import ServingSpec, adopt_params, build_decode_model
from .disagg import DisaggregatedServingEngine
from .engine import ServingEngine
from .paged import BlockManager, CopyPlan, PagedStats
from .radix import RadixPrefixCache
from .scheduler import ContinuousBatchingScheduler, Request, Slot
from .speculative import DrafterPlane, SpeculativeServingEngine

__all__ = [
    "ServingEngine", "DisaggregatedServingEngine",
    "SpeculativeServingEngine", "DrafterPlane", "ServingSpec",
    "Request", "Slot",
    "ContinuousBatchingScheduler", "build_decode_model", "adopt_params",
    "BlockManager", "CopyPlan", "PagedStats", "RadixPrefixCache",
]
