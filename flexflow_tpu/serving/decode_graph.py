"""Decode-graph construction: the trainer's PCG, re-expressed for serving.

The serving engine does NOT fork the model definition: it replays the
trained FFModel's layer list into a fresh FFModel whose inputs are
(slots, 1)-shaped — one new token per continuous-batching slot — and
whose causal `multihead_attention` layers become `inc_multihead_attention`
over per-layer KV-cache state (ops/inc_attention.py). Everything else
(embeddings, norms, MLPs, residuals, tied weights) replays verbatim with
the SAME layer names, so:

  - the trained parameters transfer to the decode graph by (node, weight)
    name — `adopt_params` re-places them under the decode plan's
    shardings;
  - the decode graph is a real PCG: `FFModel.compile` runs the same Unity
    search (the KV-cache placement priced as a parallel dim,
    search/unity.py), the same warm-start plan cache (a second serving
    compile of the same (model, slots, max_seq, mesh) is a fingerprint
    hit with zero evaluations), and the same telemetry/diagnostics hooks
    as a training compile.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from ..fftype import CompMode, DataType, LossType, OperatorType as OT


@dataclass
class ServingSpec:
    """Engine-level serving parameters (model.serve(**overrides))."""

    slots: int = 4
    max_seq_len: int = 0  # 0 → the model's training sequence length
    prefill_chunk: int = 16
    max_new_tokens: int = 32  # per-request default
    eos_id: Optional[int] = None  # per-request default (None = never)
    impl: str = "auto"  # decode attention impl (auto|xla|flash)
    # KV-cache layout (--serve-kv-layout): "paged" = block pool + per-slot
    # page tables with COW prefix sharing (the default);  "contiguous" =
    # the (slots, max_seq+1, embed) per-slot region — the ablation/
    # fallback layout (docs/serving.md)
    kv_layout: str = "paged"
    kv_block_size: int = 16  # pool rows per block (paged only)
    # physical pool blocks incl. the reserved scratch block; 0 → sized
    # from the per-chip HBM budget, capped at contiguous capacity parity
    kv_num_blocks: int = 0
    prefix_sharing: bool = True  # COW prompt-prefix reuse (paged only)
    # cross-request radix prefix cache (--serve-prefix-cache): cached
    # prompt blocks survive their residents under LRU eviction; None
    # defers to config.serve_prefix_cache. False = live sharing only.
    prefix_cache: Optional[bool] = None
    # disaggregated serving: which side this decode compile serves
    # ("" unified | "prefill" | "decode") — joins the warm-start plan
    # fingerprint via config.serve_role so the two sides' plans cache
    # independently
    role: str = ""
    # extra FFConfig fields applied to the decode compile only (e.g.
    # {"search_budget": 6, "enable_parameter_parallel": True})
    config_overrides: dict = field(default_factory=dict)
    # explicit decode-plan overrides (Strategy or raw dict) — applied via
    # set_strategy, plan_source "manual"; None → search/cache/default
    strategy: object = None


def _decode_config(model, spec: ServingSpec):
    """The decode compile's FFConfig: the trainer's, minus run-lifecycle
    subsystems that belong to the training job (its checkpoints, its
    telemetry session), plus spec.config_overrides. Search flags, mesh
    axes, and the warm-start dir carry over — the decode plan is searched
    and cached with the same machinery."""
    cfg = copy.copy(model.config)  # plain copy: __post_init__ re-parses argv
    cfg.batch_size = spec.slots
    # the layout is part of the decode plan's identity: the warm-start
    # fingerprint hashes serve_kv_layout (warmstart/fingerprint.py), so a
    # contiguous and a paged plan can never share a cache address even
    # before the structural graph difference discriminates them
    cfg.serve_kv_layout = spec.kv_layout
    # the disaggregated role is part of the plan's identity too: the
    # prefill and decode sides search the same graph over different
    # sub-meshes and must never share a warm-start address
    cfg.serve_role = spec.role
    cfg.telemetry_dir = ""
    cfg.xprof_dir = ""
    cfg.diagnostics = False
    # the decode model must not grow its own controller — the ENGINE
    # owns decode-mesh elasticity (ServingEngine.replan_mesh)
    cfg.elastic = False
    cfg.checkpoint_dir = ""
    cfg.auto_resume = False
    cfg.pipeline_steps = 1
    cfg.import_strategy_file = ""
    cfg.export_strategy_file = ""
    cfg.export_strategy_computation_graph_file = ""
    for k, v in (spec.config_overrides or {}).items():
        if not hasattr(cfg, k):
            raise ValueError(f"config_overrides: FFConfig has no field {k!r}")
        setattr(cfg, k, v)
    return cfg


def resolve_pool_blocks(model, spec: ServingSpec, max_seq: int) -> int:
    """Physical block count for the paged pool (incl. the reserved scratch
    block 0). spec.kv_num_blocks > 0 pins it; 0 sizes the pool from the
    per-chip HBM budget — the machine model's chip capacity minus the
    decode graph's non-pool footprint (the trained weights that transfer
    by name — the same number the ffcheck liveness pass charges as
    persistent weight bytes) — capped at contiguous capacity parity
    (every slot can reach max_seq), floored at one block per slot so the
    engine can always make progress."""
    bs = spec.kv_block_size
    if bs < 1:
        raise ValueError(f"kv_block_size must be >= 1, got {bs}")
    table_width = -(-max_seq // bs)
    if spec.kv_num_blocks:
        if spec.kv_num_blocks < 2:
            raise ValueError(
                f"kv_num_blocks must be >= 2 (scratch + 1), got "
                f"{spec.kv_num_blocks}")
        return spec.kv_num_blocks
    capacity = spec.slots * table_width + 1
    try:
        import numpy as np

        from ..search.machine_model import machine_model_for_mesh

        hbm = machine_model_for_mesh(model.mesh).chip.hbm_bytes
        weight_bytes = sum(
            np.asarray(w).size * np.asarray(w).dtype.itemsize
            for ws in (model._params or {}).values() for w in ws.values())
        attn = [l for l in model.layers
                if l.op_type == OT.OP_MULTIHEAD_ATTENTION]
        block_bytes = sum(2 * bs * l.params.embed_dim * 4 for l in attn)
        if block_bytes <= 0:
            return capacity
        budget = 0.9 * hbm - weight_bytes
        fit = int(budget // block_bytes)
        return max(spec.slots + 1, min(capacity, fit))
    except Exception:
        # no machine model / no params yet: capacity parity is always safe
        return capacity


def infer_max_seq_len(model) -> int:
    """Default KV-cache length: the training graph's sequence extent (dim 1
    of the first embedding-consuming input), so decode never outruns the
    learned positional table."""
    for t in model._input_tensors:
        if len(t.dims) >= 2:
            return int(t.dims[1])
    raise ValueError("cannot infer max_seq_len: no rank-2 input "
                     "(pass max_seq_len explicitly)")


def build_decode_model(model, spec: ServingSpec):
    """Replay `model`'s layers into a compiled decode FFModel.

    Raises for graphs serving can't express yet: non-causal or
    cross-attention (decode needs self-attention with a causal order), and
    ops whose shape inference rejects (slots, 1, ...) activations."""
    from ..model import FFModel
    from ..optimizer import SGDOptimizer

    if spec.kv_layout not in ("contiguous", "paged"):
        raise ValueError(
            f"kv_layout must be 'contiguous' or 'paged', got "
            f"{spec.kv_layout!r}")
    max_seq = spec.max_seq_len or infer_max_seq_len(model)
    paged = spec.kv_layout == "paged"
    num_blocks = resolve_pool_blocks(model, spec, max_seq) if paged else 0
    dec = FFModel(_decode_config(model, spec))

    # --- inputs: (batch, seq, ...) → (slots, 1, ...); the `positions`
    # input doubles as every attention layer's position feed
    tensor_map: dict[int, object] = {}
    positions = None
    for t in model._input_tensors:
        if len(t.dims) < 2:
            raise ValueError(
                f"serving input {t.name!r} is rank {len(t.dims)}; decode "
                f"inputs need a (batch, seq, ...) shape")
        nt = dec.create_tensor((spec.slots, 1) + tuple(t.dims[2:]),
                               t.dtype, create_grad=False, name=t.name)
        if hasattr(t, "constant_value"):
            nt.constant_value = t.constant_value
        tensor_map[t.tensor_guid] = nt
        if t.name == "positions":
            positions = nt
    if positions is None:
        positions = dec.create_tensor((spec.slots, 1), DataType.DT_INT32,
                                      create_grad=False, name="positions")
    page_table = None
    if paged:
        # one page table feeds every attention layer: block ids index the
        # same physical slot across all layers' pools (vLLM's layout), so
        # the host manages ONE table per slot, not one per layer
        table_width = -(-max_seq // spec.kv_block_size)
        page_table = dec.create_tensor(
            (spec.slots, table_width), DataType.DT_INT32,
            create_grad=False, name="page_table")

    # --- layers, replayed name-for-name
    layer_map: dict[int, object] = {}  # train layer guid -> decode Layer
    for layer in model.layers:
        ins = []
        for t in layer.inputs:
            mapped = tensor_map.get(t.tensor_guid)
            if mapped is None:
                raise ValueError(
                    f"layer {layer.name!r} reads a tensor serving did not "
                    f"replay ({t.name!r})")
            ins.append(mapped)
        shared = None
        if layer.shared_layer_guid >= 0:
            src = layer_map.get(layer.shared_layer_guid)
            if src is None:
                raise ValueError(
                    f"{layer.name}: tied-weight source layer not replayed")
            shared = src
        if layer.op_type == OT.OP_MULTIHEAD_ATTENTION:
            p = layer.params
            if not p.causal:
                raise ValueError(
                    f"{layer.name}: serving decode requires causal "
                    f"attention (non-causal layers see future tokens the "
                    f"cache does not hold yet)")
            if not (layer.inputs[0] is layer.inputs[1]
                    is layer.inputs[2]):
                raise ValueError(
                    f"{layer.name}: serving decode supports "
                    f"self-attention only (q, k, v must be one tensor)")
            if (p.kdim not in (0, p.embed_dim)
                    or p.vdim not in (0, p.embed_dim)):
                raise ValueError(
                    f"{layer.name}: kdim/vdim != embed_dim not supported "
                    f"in the decode graph")
            if paged:
                from ..ops import PagedIncMultiHeadAttentionParams

                np_ = PagedIncMultiHeadAttentionParams(
                    p.embed_dim, p.num_heads, max_seq,
                    spec.kv_block_size, num_blocks, p.use_bias,
                    impl=spec.impl)
                new = dec._add_layer(
                    OT.OP_PAGED_INC_MULTIHEAD_ATTENTION, np_,
                    [ins[0], positions, page_table],
                    name=layer.name, data_type=layer.data_type)
            else:
                from ..ops import IncMultiHeadAttentionParams

                np_ = IncMultiHeadAttentionParams(
                    p.embed_dim, p.num_heads, max_seq, p.use_bias,
                    impl=spec.impl)
                new = dec._add_layer(
                    OT.OP_INC_MULTIHEAD_ATTENTION, np_, [ins[0], positions],
                    name=layer.name, data_type=layer.data_type)
        else:
            new = dec._add_layer(
                layer.op_type, layer.params, ins, name=layer.name,
                initializers=dict(layer.initializers),
                data_type=layer.data_type, shared_op=shared)
        layer_map[layer.layer_guid] = new
        for t_out, d_out in zip(layer.outputs, new.outputs):
            tensor_map[t_out.tensor_guid] = d_out

    if spec.strategy is not None:
        dec.set_strategy(spec.strategy)
    dec.compile(optimizer=SGDOptimizer(lr=0.0),
                loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                comp_mode=CompMode.COMP_MODE_INFERENCE)
    return dec, max_seq


def adopt_params(dec, model) -> int:
    """Move the trained model's parameters into the decode model by
    (node, weight) name, re-placed under the decode plan's shardings
    (set_weight device_puts with the decode-side sharding). Non-trainable
    state with a matching name/shape (e.g. BatchNorm stats) transfers
    too; the KV caches keep their zero init. Returns weights adopted."""
    import numpy as np

    moved = 0
    for node_name, ws in dec._params.items():
        for wname in ws:
            val = model.get_weight(node_name, wname)
            if tuple(val.shape) != tuple(np.asarray(ws[wname]).shape):
                raise ValueError(
                    f"{node_name}.{wname}: trained shape {val.shape} != "
                    f"decode shape {np.asarray(ws[wname]).shape}")
            dec.set_weight(node_name, wname, val)
            moved += 1
    for node_name, ws in (dec._state or {}).items():
        src = (model._state or {}).get(
            model._resolve_weight_owner(node_name), {})
        for wname in ws:
            if wname in ("cache_k", "cache_v", "pool_k", "pool_v"):
                continue
            if wname in src:
                arr = np.asarray(src[wname])
                old = ws[wname]
                import jax
                import jax.numpy as jnp

                ws[wname] = jax.device_put(
                    jnp.asarray(arr, old.dtype), old.sharding)
                moved += 1
    return moved
