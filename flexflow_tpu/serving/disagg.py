"""Disaggregated prefill/decode serving (DistServe / Splitwise class).

Prefill is compute-bound (one long matmul-heavy chunk per request),
decode is memory-bound (one tiny batched step per token); co-locating
them makes every long prompt a TBT spike for every in-flight decode.
This coordinator splits the chip budget into two DISJOINT sub-meshes —
`mesh_device_offset` + `mesh_axis_sizes` config overrides carve
device windows — and compiles TWO Unity plans, one per role, each
priced and placed by its own search over its own sub-mesh (the role
joins the warm-start fingerprint, so the two plans cache
independently).

A request's life: prefill-side engine runs the full prompt (its own
radix prefix cache shortens repeated prefixes) and samples the FIRST
token; the coordinator lifts the prompt-extent KV blocks off the
prefill pools (the pre-release hook fires while the page table still
maps them), then hands the request to the decode engine, which maps
any decode-side radix-cached prefix for free, injects only the
uncovered block extent through one donated executable, and decodes to
completion. Every handoff is an fftrans transfer program — the
host-staged rows are modeled as `host_hop` collectives, verified by
`verify_transition` and priced by the SAME machine-model oracle as
every other collective — with measured-vs-predicted recorded per
handoff in the strategy report (`run_doctor --check` re-verifies the
makespan identity from the report alone).

The elastic tier gets a third trigger: when prefill queue-wait p95 and
decode TBT p95 diverge, the coordinator proposes a one-notch
chip-ratio shift, prices the two-sided re-plan, and gates it through
the SAME payoff inequality as every other migration
(`lhs = predicted_migration_s x fidelity_ratio < benefit x horizon`);
an approved shift shrinks one side's mesh first, then grows the other
into the freed window via `replan_mesh` — verified, priced state
migration per side, in-flight requests riding through untouched.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from .. import telemetry
from .engine import ServingEngine
from .scheduler import Request


def sub_mesh_axes(model, n: int) -> tuple:
    """The n-chip sub-mesh factorization of `model`'s configured mesh:
    rescale the data axis, every other axis kept — the same shape
    discipline the elastic capacity trigger uses, so a sub-mesh plan is
    always a shape the search already prices. Shared by the
    disaggregated (prefill/decode) and speculative (drafter/target)
    engines — both carve disjoint device windows with it."""
    from ..machine import AXIS_DATA, DEFAULT_AXES

    ms = model.config.mesh_shape()
    sizes = list(int(s) for s in ms.axis_sizes)
    names = list(ms.axis_names)
    if len(names) != len(DEFAULT_AXES):
        raise ValueError(
            "sub-mesh serving runs single-host for now "
            "(multi-host meshes carry a dcn axis)")
    di = names.index(AXIS_DATA)
    fixed = 1
    for i, s in enumerate(sizes):
        if i != di:
            fixed *= s
    if n % fixed:
        raise ValueError(
            f"{n} chips cannot keep the non-data axes "
            f"(product {fixed}) of mesh {tuple(sizes)}")
    sizes[di] = n // fixed
    return tuple(sizes)


class DisaggregatedServingEngine:
    """Two ServingEngines on disjoint device windows + the KV handoff
    plane between them. Mirrors the ServingEngine surface (submit /
    step / run_until_drained / generate / stats / metrics_summary) so
    drivers swap in with one flag."""

    def __init__(self, model, prefill_chips: Optional[int] = None,
                 **overrides):
        import jax

        cfg = model.config
        self.model = model
        self._total_chips = len(jax.devices())
        if prefill_chips is None:
            prefill_chips = int(getattr(cfg, "serve_prefill_chips", 0))
        if not prefill_chips:
            prefill_chips = self._total_chips // 2
        if not 0 < prefill_chips < self._total_chips:
            raise ValueError(
                f"serve(disaggregate=True) needs 1..{self._total_chips - 1} "
                f"prefill chips out of {self._total_chips}, got "
                f"{prefill_chips}")
        if overrides.get("kv_layout", cfg.serve_kv_layout) != "paged":
            raise ValueError(
                "disaggregated serving requires the paged KV layout "
                "(the handoff moves pool blocks)")
        self.prefill_chips = int(prefill_chips)
        user_over = dict(overrides.pop("config_overrides", None) or {})
        self.prefill = self._build_side(
            "prefill", 0, self.prefill_chips, user_over, overrides)
        self.decode = self._build_side(
            "decode", self.prefill_chips, self.decode_chips, user_over,
            overrides)
        # prefill completes every request after ONE token; the hook
        # lifts the KV while the page table still maps it, and the
        # suppression keeps completion accounting single-sourced on the
        # decode side (doctor's drained-TTFT identity counts each
        # request exactly once)
        self.prefill._pre_release_hook = self._on_prefill_done
        self.prefill._suppress_completion_events = True
        self._machine = self._build_machine()
        self._kv_stash: dict[int, tuple] = {}  # request_id -> (k, v, s)
        self._pending: list[Request] = []  # prefilled, awaiting a slot
        self.handoffs: list[dict] = []
        self._programs: dict[int, dict] = {}  # injected blocks -> plan
        self._plan_cache: dict[int, tuple] = {}
        self._rebalance_decisions: list[dict] = []
        self.completed: list[Request] = []
        self._iterations = 0
        self.rebalance_min_samples = 8
        self.rebalance_factor = 1.5
        # the two pools live on DISJOINT devices, so their steps really
        # do run concurrently: one worker thread drives the prefill
        # engine while the coordinator thread drives decode — without
        # it, every in-flight decode dispatch serializes in front of
        # every waiting prefill and TTFT inherits the decode tail
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ff-serve-prefill")

    def _build_side(self, role: str, offset: int, chips: int,
                    user_over: dict, overrides: dict) -> ServingEngine:
        over = dict(user_over)
        over["mesh_axis_sizes"] = self._sub_axes(chips)
        over["mesh_device_offset"] = int(offset)
        return ServingEngine(self.model, role=role,
                             config_overrides=over, **overrides)

    def _build_machine(self):
        from ..search.machine_model import machine_model_for_mesh

        return machine_model_for_mesh(
            self.decode.decode_model.mesh,
            num_hosts=self.model.config.num_nodes)

    @property
    def decode_chips(self) -> int:
        return self._total_chips - self.prefill_chips

    def _sub_axes(self, n: int) -> tuple:
        return sub_mesh_axes(self.model, n)

    # ------------------------------------------------------------ intake

    def submit(self, prompt: Sequence[int], **request_kw) -> Request:
        """Enqueue on the prefill side, clamped to ONE generated token
        there — the first token is the prefill pool's last output; the
        rest of the budget decodes on the decode pool."""
        req = self.prefill.submit(prompt, **request_kw)
        req._disagg_max_new = req.max_new_tokens
        req.max_new_tokens = 1
        return req

    def _on_prefill_done(self, slot, req: Request):
        """Pre-release hook on the prefill engine: the slot's page
        table still maps the prompt blocks, so lift them now."""
        t0 = time.perf_counter()
        ks, vs = self.prefill.extract_kv(slot.index, len(req.prompt))
        # this pair is the extract half of the handoff's measured_s —
        # it reaches the metrics plane via _record_handoff, and a span
        # here would double-record every handoff
        self._kv_stash[req.request_id] = (
            ks, vs, time.perf_counter() - t0)  # fflint: ok raw_timer_in_hot_path

    # ------------------------------------------------------------ iterate

    def step(self) -> list[Request]:
        """One coordinator iteration: the prefill and decode engine
        steps run CONCURRENTLY (disjoint device windows — the worker
        thread prefills while this thread decodes, so a long prompt is
        never a TBT spike and an in-flight decode batch never delays a
        waiting prefill), then handoff routing and decode-side
        admissions (FCFS, head-blocking — a full decode batch never
        reorders the handoff queue). Returns the requests that
        completed. The session activation is held across the overlap so
        the inner engines' nested activate/deactivate pairs (either
        thread) cannot tear the telemetry sink down mid-step."""
        done: list[Request] = []
        tel = self.decode.telemetry
        if tel is not None:
            telemetry.activate(tel)
        try:
            fut = self._pool.submit(self.prefill.step)
            dec_done = self.decode.step()
            pre_done = fut.result()
        finally:
            if tel is not None:
                telemetry.deactivate(tel)
        for req in pre_done:
            done.extend(self._route_prefilled(req))
        while self._pending:
            if not self._admit_handoff(self._pending[0]):
                break
            self._pending.pop(0)
        done.extend(dec_done)
        self._iterations += 1
        self.completed.extend(done)
        return done

    def _route_prefilled(self, req: Request) -> list[Request]:
        """Classify one prefill completion: truly finished (EOS on the
        first token, a one-token budget, or a full cache) is recorded
        on the decode side and returned; everything else joins the
        handoff queue with its real token budget restored."""
        real = getattr(req, "_disagg_max_new", req.max_new_tokens)
        req.max_new_tokens = real
        if req.finish_reason == "max_tokens" and real > len(req.generated):
            if len(req.prompt) >= self.decode.max_seq_len:
                # the decode cache has no row for a second token — the
                # same "length" verdict the unified engine reaches
                req.finish_reason = "length"
            else:
                req.finished = False
                req.finish_reason = ""
                req.finish_t = None
                self._pending.append(req)
                return []
        self.decode.scheduler.completed.append(req)
        with self.decode._active():
            self.decode.record_completion(req)
        return [req]

    def _admit_handoff(self, req: Request) -> bool:
        """Try to land one prefilled request on the decode pool; False
        means no slot/reservation (retry next step, order kept)."""
        ks, vs, extract_s = self._kv_stash[req.request_id]
        t0 = time.perf_counter()
        injected = self.decode.admit_prefilled(
            req, req.generated[-1], ks, vs)
        if injected is None:
            return False
        measured = extract_s + (time.perf_counter() - t0)
        del self._kv_stash[req.request_id]
        self._record_handoff(req, injected, measured)
        return True

    # ------------------------------------------------------------ handoff plane

    def _record_handoff(self, req: Request, injected: int,
                        measured_s: float):
        bs = self.decode.block_manager.block_size
        nlb = -(-len(req.prompt) // bs)
        predicted = 0.0
        if injected > 0:
            prog = self._handoff_program(injected)
            predicted = float(prog["predicted_s"])
        rec = {
            "request_id": req.request_id,
            "prompt_tokens": len(req.prompt),
            "prompt_blocks": nlb,
            "matched_prefix_len": req.matched_prefix_len,
            "injected_blocks": int(injected),
            "predicted_s": predicted,
            "measured_s": float(measured_s),
        }
        self.handoffs.append(rec)
        with self.decode._active():
            telemetry.event("serve.handoff", **rec)

    def _handoff_program(self, nblk: int) -> dict:
        """The verified, priced fftrans transfer program for an
        nblk-block handoff — built once per distinct block count (the
        program depends only on the extent): per-layer host-resident
        (nblk, block, embed) K/V leaves on the prefill side hop through
        the host NIC into the decode pools' sharding, exactly the
        device_get -> device_put the implementation performs."""
        cached = self._programs.get(nblk)
        if cached is not None:
            return cached
        from ..analysis.transition import (
            LeafInfo, PlanSide, build_transition_plan, verify_transition,
            _assignment_of_leaf)

        bs = self.decode.block_manager.block_size
        dec = self.decode.decode_model
        src = PlanSide(axis_sizes={
                           k: int(v) for k, v
                           in dict(self.prefill.decode_model.mesh
                                   .shape).items()},
                       plan_source="serve_prefill", kv_block_size=bs,
                       on_device=False, label="prefill_kv")
        dst = PlanSide(axis_sizes={k: int(v) for k, v
                                   in dict(dec.mesh.shape).items()},
                       plan_source=dec._plan_source, kv_block_size=bs,
                       on_device=True, label="decode_kv")
        for i, name in enumerate(self.decode.kv_pool_layers()):
            for part in ("pool_k", "pool_v"):
                pool = dec._state[name][part]
                key = f"['{name}']['{part}']"
                shape = (int(nblk), int(pool.shape[1]),
                         int(pool.shape[2]))
                src.leaves[key] = LeafInfo(
                    key=key, shape=shape, dtype=str(pool.dtype),
                    assignment=None, kv_pool=True, topo_pos=i)
                dst.leaves[key] = LeafInfo(
                    key=key, shape=shape, dtype=str(pool.dtype),
                    assignment=_assignment_of_leaf(pool), kv_pool=True,
                    topo_pos=i)
        plan = build_transition_plan(src, dst, machine=self._machine)
        analysis = verify_transition(plan)
        prog = plan.to_json(analysis)
        self._programs[nblk] = prog
        return prog

    # ------------------------------------------------------------ rebalance

    def propose_ratio_shift(self) -> Optional[dict]:
        """The prefill:decode ratio trigger: when prefill queue-wait
        p95 and decode TBT p95 diverge past `rebalance_factor`, propose
        the next feasible one-notch boundary shift toward the starved
        side. Pure observation — no state changes."""
        from ..telemetry.metrics import percentile_from_hist

        qwh = self.prefill._h_queue_wait
        tbth = self.decode._h_tbt
        if (qwh.count < self.rebalance_min_samples
                or tbth.count < self.rebalance_min_samples):
            return None
        qw = percentile_from_hist(qwh.to_dict(), 95)
        tbt = percentile_from_hist(tbth.to_dict(), 95)
        if qw > self.rebalance_factor * tbt:
            direction = 1  # queue backs up at prefill: grow prefill
        elif tbt > self.rebalance_factor * qw:
            direction = -1  # decode batch starves: grow decode
        else:
            return None
        new_p = self._next_split(direction)
        if new_p is None:
            return None
        return {"new_prefill_chips": new_p, "queue_wait_p95_s": qw,
                "tbt_p95_s": tbt, "direction": direction}

    def _next_split(self, direction: int) -> Optional[int]:
        cand = self.prefill_chips + direction
        while 0 < cand < self._total_chips:
            try:
                self._sub_axes(cand)
                self._sub_axes(self._total_chips - cand)
                return cand
            except ValueError:
                cand += direction
        return None

    def maybe_rebalance(self, horizon_steps: int = 256,
                        forced: bool = False) -> Optional[dict]:
        """Price a proposed ratio shift through the payoff inequality
        and execute it when (and only when) migration pays for itself
        within the horizon — the serving twin of the training-side
        drift/capacity triggers, producing the SAME decision-record
        shape `run_doctor --check` reproduces arithmetic from."""
        from ..elastic.payoff import evaluate_payoff, load_fidelity

        prop = self.propose_ratio_shift()
        if prop is None:
            return None
        fidelity, samples = load_fidelity(self.model)
        benefit = abs(prop["queue_wait_p95_s"] - prop["tbt_p95_s"])
        decision = {
            "trigger": "serve_ratio", "scope": "serving_disagg",
            "old_prefill_chips": self.prefill_chips,
            "fidelity_samples": samples,
        }
        decision.update(prop)
        decision.update(evaluate_payoff(
            predicted_migration_s=self._predict_rebalance_s(
                prop["new_prefill_chips"]),
            fidelity_ratio=fidelity,
            benefit_s_per_step=benefit,
            horizon_steps=horizon_steps,
            forced=forced))
        if decision["would_migrate"] or forced:
            t0 = time.perf_counter()
            self._set_split(prop["new_prefill_chips"])
            decision["decision"] = "migrated"
            decision["migration_measured_s"] = time.perf_counter() - t0
        else:
            decision["decision"] = "declined"
        with self.decode._active():
            telemetry.event("replan", **decision)
        self._rebalance_decisions.append(decision)
        # ride the elastic report section so the doctor's payoff gate
        # covers ratio decisions with zero new plumbing
        if not hasattr(self.model, "_elastic_decisions"):
            self.model._elastic_decisions = []
        self.model._elastic_decisions.append(decision)
        return decision

    def _predict_rebalance_s(self, new_p: int) -> float:
        """Priced cost of re-planning BOTH sides: each side's full
        decode state (params + pools) staged through the host NIC —
        the conservative cross-window figure, priced by the same
        oracle as the handoff programs."""
        from ..search.cost_model import price_transfer_collective

        total = 0.0
        for eng in (self.prefill, self.decode):
            b = 0.0
            for ws in eng.decode_model._state.values():
                for arr in ws.values():
                    b += float(arr.size) * arr.dtype.itemsize
            total += price_transfer_collective(
                "host_hop", b, b, "", self._machine)
        return total

    def _set_split(self, new_p: int):
        """Move the chip boundary: the shrinking side re-plans FIRST
        (its new window is a subset of its old one), then the growing
        side expands into the freed devices — the two windows stay
        disjoint at every instant."""
        total = self._total_chips
        if new_p < self.prefill_chips:
            order = [(self.prefill, new_p, 0),
                     (self.decode, total - new_p, new_p)]
        else:
            order = [(self.decode, total - new_p, new_p),
                     (self.prefill, new_p, 0)]
        for eng, chips, offset in order:
            eng.spec.config_overrides = dict(
                eng.spec.config_overrides or {})
            eng.spec.config_overrides["mesh_device_offset"] = int(offset)
            eng.replan_mesh(self._sub_axes(chips), trigger="serve_ratio")
        self.prefill_chips = int(new_p)
        self._machine = self._build_machine()
        self._plan_cache.clear()
        self._programs.clear()  # re-priced against the new decode mesh

    # ------------------------------------------------------------ drain

    @property
    def drained(self) -> bool:
        return (self.prefill.scheduler.drained
                and self.decode.scheduler.drained
                and not self._pending)

    def run_until_drained(self, max_iterations: int = 0) -> list[Request]:
        done: list[Request] = []
        t0 = time.perf_counter()
        it = 0
        while not self.drained:
            done.extend(self.step())
            it += 1
            if max_iterations and it >= max_iterations:
                break
        self.note_drain(time.perf_counter() - t0)
        return done

    def note_drain(self, wall_s: float):
        """Close one measured window: ONE merged summary event, ONE
        drained metrics snapshot (both engines' registries are attached
        to the same session, so the snapshot merges the pair), and the
        strategy report's serving_disagg section rewritten in place."""
        self.prefill._last_wall_s = wall_s
        self.decode._last_wall_s = wall_s
        with self.decode._active():
            telemetry.event("serve.summary", **self.metrics_summary())
        tel = self.decode.telemetry
        if tel is not None:
            tel.write_metrics_snapshot(reason="serve_drain",
                                       drained=bool(self.drained))
            tel.flush()
        self._update_report()

    def _update_report(self):
        self.model._serving_disagg = self.disagg_section()
        diag = getattr(self.model, "_diagnostics", None)
        if diag is not None and getattr(diag, "report", None):
            from ..diagnostics.explain import rewrite_strategy_report

            diag.report["serving_disagg"] = self.model._serving_disagg
            rewrite_strategy_report(diag.report, diag.directory)

    def generate(self, prompts: Sequence[Sequence[int]],
                 **request_kw) -> list[list[int]]:
        reqs = [self.submit(p, **request_kw) for p in prompts]
        self.run_until_drained()
        return [r.generated for r in reqs]

    # ------------------------------------------------------------ stats

    def disagg_section(self) -> dict:
        """The strategy report's `serving_disagg` section: split
        geometry, every handoff's measured-vs-predicted, the distinct
        verified transfer programs they reference (keyed by injected
        block count), and the ratio-trigger decision log. run_doctor
        --check recomputes each program's predicted_s from its own
        transfer entries and requires every handoff to reproduce it."""
        n = len(self.handoffs)
        return {
            "prefill_chips": self.prefill_chips,
            "decode_chips": self.decode_chips,
            "prefill_mesh_axes": {
                k: int(v) for k, v
                in dict(self.prefill.decode_model.mesh.shape).items()},
            "decode_mesh_axes": {
                k: int(v) for k, v
                in dict(self.decode.decode_model.mesh.shape).items()},
            "handoffs": list(self.handoffs),
            "programs": {str(k): v for k, v in self._programs.items()},
            "summary": {
                "count": n,
                "predicted_s": sum(h["predicted_s"]
                                   for h in self.handoffs),
                "measured_s": sum(h["measured_s"]
                                  for h in self.handoffs),
                "fully_cached": sum(1 for h in self.handoffs
                                    if h["injected_blocks"] == 0),
            },
            "rebalances": list(self._rebalance_decisions),
        }

    def stats(self) -> dict:
        pre = self.prefill.stats()
        dec = self.decode.stats()
        out = {
            "disaggregated": True,
            "prefill_chips": self.prefill_chips,
            "decode_chips": self.decode_chips,
            "num_chips": self._total_chips,
            "requests_completed": dec["requests_completed"],
            "handoffs": len(self.handoffs),
            "handoff_predicted_s": sum(h["predicted_s"]
                                       for h in self.handoffs),
            "handoff_measured_s": sum(h["measured_s"]
                                      for h in self.handoffs),
            "pending_handoffs": len(self._pending),
            "prefill": pre,
            "decode": dec,
        }
        wall = getattr(self.decode, "_last_wall_s", 0.0) or 0.0
        if wall > 0:
            out["requests_per_sec_per_chip"] = (
                dec["requests_completed"] / wall / self._total_chips)
        return out

    def metrics_summary(self) -> dict:
        out = self.stats()
        out["prefill"] = self.prefill.metrics_summary()
        out["decode"] = self.decode.metrics_summary()
        return out

    def reset_stats(self) -> None:
        self.prefill.reset_stats()
        self.decode.reset_stats()
        self.completed.clear()
        self.handoffs.clear()
        self._iterations = 0
