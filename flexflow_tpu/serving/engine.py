"""ServingEngine: continuous-batching inference on the decode PCG.

The device side of serving (scheduler.py is the policy side; paged.py the
block-pool side): one donated jitted step (`Executor.build_decode_step`)
threads (params, kv-cache state, tokens, positions[, page tables]) and
returns the next token per slot, sampled in-program (greedy /
temperature-Gumbel per slot).

**Chunked prefill, interleaved with decode** (Orca's iteration-level
scheduling at sub-request grain): every `step()` runs exactly ONE device
call, carrying at most one prefill CHUNK (engine/chunking.plan_chunks
buckets, power-of-two widths) in the admitted slot's rows while every
DECODING slot advances one token in column 0 of the same call — long
prompts therefore never stall the continuous batch, and a decoding slot's
token stream is bit-identical either way because slot rows are computed
independently (padding columns point at the scratch row/block).

**KV layouts** (`--serve-kv-layout`, ServingSpec.kv_layout):
  - "paged" (default): per-layer block POOLS (num_blocks, block_size,
    embed) + per-slot page tables, with copy-on-write prompt-prefix
    sharing managed host-side by paged.BlockManager — N requests with one
    system prompt store (and prefill) it once. COW copies run through the
    donated `Executor.build_block_copy` executable before the step that
    writes.
  - "contiguous": the (slots, max_seq+1, embed) per-slot cache — the
    ablation/fallback layout.
Both are first-class stateful parallel tensors placed by the Unity
search; the two layouts are token-identical on the full test matrix.

Invariants the tests pin down (tests/test_serving.py):
  - greedy decode is token-identical to the teacher-forced training
    forward's argmax at every position;
  - an interleaved continuous batch is token-identical to serving each
    request alone (slot rows are computed independently);
  - paged decode is token-identical to contiguous decode, COW included;
  - the engine compile is a normal Unity compile: warm-start plan-cache
    hits apply (second serving compile of the same (model, slots,
    max_seq, mesh, kv layout) = 0 search evaluations), and contiguous and
    paged plans never share a cache address.

Telemetry (when the trained model has a session): `serve.compile` /
`serve.prefill` / `serve.step` spans, per-iteration queue-depth and
slot-occupancy counters, a `serve.request` event per completion carrying
time-to-first-token, and a `serve.summary` event with requests/s/chip and
decode tokens/s/chip.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional, Sequence

import numpy as np

from .. import telemetry
from ..engine.chunking import plan_chunks
from .decode_graph import ServingSpec, adopt_params, build_decode_model
from .paged import SCRATCH_BLOCK, BlockManager
from .scheduler import ContinuousBatchingScheduler, Request


class ServingEngine:
    def __init__(self, model, **overrides):
        import jax

        if jax.process_count() > 1:
            raise NotImplementedError(
                "serving runs single-controller for now (multi-host "
                "serving is the prefill/decode disaggregation item, "
                "ROADMAP)")
        cfg = model.config
        spec = ServingSpec(
            slots=cfg.serve_slots,
            max_seq_len=cfg.serve_max_seq_len,
            prefill_chunk=cfg.serve_prefill_chunk,
            kv_layout=cfg.serve_kv_layout,
            kv_block_size=cfg.serve_kv_block_size,
            kv_num_blocks=cfg.serve_kv_blocks,
        )
        for k, v in overrides.items():
            if not hasattr(spec, k):
                raise ValueError(f"serve(): unknown option {k!r}")
            setattr(spec, k, v)
        if spec.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if spec.prefix_cache is None:
            spec.prefix_cache = bool(
                getattr(cfg, "serve_prefix_cache", 1))
        self.model = model
        self.spec = spec
        self.role = spec.role
        self.telemetry = model._telemetry
        with self._active():
            t0 = time.perf_counter()
            with telemetry.span("serve.compile", slots=spec.slots):
                self.decode_model, self.max_seq_len = build_decode_model(
                    model, spec)
                self.adopted = adopt_params(self.decode_model, model)
                self._step_fn = (
                    self.decode_model.executor.build_decode_step())
            telemetry.event(
                "serve.compile",
                duration_s=time.perf_counter() - t0,
                slots=spec.slots, max_seq_len=self.max_seq_len,
                prefill_chunk=spec.prefill_chunk,
                kv_layout=spec.kv_layout,
                plan_source=self.decode_model._plan_source,
                weights_adopted=self.adopted,
                mesh_axes={k: int(v) for k, v
                           in self.decode_model.mesh.shape.items()})
            if self.telemetry is not None:
                self.telemetry.flush()
        self.scheduler = ContinuousBatchingScheduler(
            spec.slots, self.max_seq_len)
        self.num_chips = int(self.decode_model.mesh.devices.size)
        self._rng = None  # lazily split jax PRNG for sampling steps
        # paged layout: host-side block manager + the donated COW copy
        # executable; pool geometry comes from the BUILT op (resolve_
        # pool_blocks ran inside build_decode_model)
        self.block_manager = None
        self._copy_fn = None
        self._inject_fn = None  # lazily built KV-handoff landing pad
        if spec.kv_layout == "paged":
            from ..fftype import OperatorType as OT

            attn = next(
                n for n in self.decode_model.graph.topo_order()
                if n.op_type == OT.OP_PAGED_INC_MULTIHEAD_ATTENTION)
            p = attn.params
            self.block_manager = BlockManager(
                p.num_blocks, p.block_size, p.blocks_per_slot,
                sharing=spec.prefix_sharing,
                cross_time=bool(spec.prefix_cache))
            self._copy_fn = (
                self.decode_model.executor.build_block_copy())
        # graph input roles: exactly one token stream + the positions /
        # page-table feeds (+ constants, which the engine materializes)
        self._token_input = None
        self._const_inputs = {}
        for t in self.decode_model._input_tensors:
            if t.name in ("positions", "page_table"):
                continue
            if hasattr(t, "constant_value"):
                self._const_inputs[t.name] = (
                    tuple(t.dims), t.dtype, t.constant_value)
            elif self._token_input is None:
                self._token_input = t.name
            else:
                raise ValueError(
                    f"serving needs exactly one token input; model has "
                    f"{self._token_input!r} and {t.name!r}")
        if self._token_input is None:
            raise ValueError("serving: model has no token input")
        # sanitizer baseline: events reported before this engine existed
        # (e.g. a training NaN earlier in the process) are not decode
        # corruption — only NEW reports surface as serve.nonfinite
        if self.decode_model.config.sanitize_numerics:
            from ..sanitize import get_monitor

            self._numerics_reported = {
                (e["op"], e["phase"]) for e in get_monitor().snapshot()}
        # disaggregation hooks (serving/disagg.py): the coordinator taps
        # completions BEFORE block release (to lift the prompt KV out of
        # the pool while the page table still maps it) and silences the
        # prefill side's request-grain completion accounting so the
        # merged metrics plane counts every request exactly once
        self._pre_release_hook = None
        self._suppress_completion_events = False
        # run accounting (stats())
        self._decode_iterations = 0
        self._decode_tokens = 0
        self._prefill_tokens = 0
        self._prefill_calls = 0
        self._device_s = 0.0
        self._last_step_device_s = 0.0  # most recent device call's wall
        # ffpulse metrics plane: engine-owned registry so serving metrics
        # exist (and metrics_summary works) without a telemetry dir, and
        # reset_stats can zero the serving series alone. Every series the
        # step loop touches is created HERE — a serving step allocates no
        # metric objects (the overhead-guard invariant).
        from ..telemetry.metrics import MetricsRegistry

        reg = self.metrics = MetricsRegistry()
        self._h_queue_wait = reg.histogram("serve_queue_wait_s")
        self._h_ttft = reg.histogram("serve_ttft_s")
        self._h_tbt = reg.histogram("serve_tbt_s")
        self._h_e2e = reg.histogram("serve_e2e_s")
        self._h_step_device = reg.histogram("serve_step_device_s")
        self._g_slots_active = reg.gauge("serve_slots_active")
        self._g_slots_total = reg.gauge("serve_slots_total")
        self._g_slots_total.set(spec.slots)
        self._g_queue_depth = reg.gauge("serve_queue_depth")
        self._g_blocks_free = reg.gauge("serve_kv_blocks_free")
        self._g_blocks_used = reg.gauge("serve_kv_blocks_used")
        self._g_blocks_reserved = reg.gauge("serve_kv_blocks_reserved")
        self._c_cow_copies = reg.counter("serve_cow_copies_total")
        # radix prefix-cache plane (all pre-created — steady-state steps
        # allocate no metric objects): cached = blocks only the cache
        # holds (the evictable set), pinned = cache entries a live slot
        # also maps; hits/misses count admissions, evictions count nodes
        self._g_prefix_cached = reg.gauge(
            "serve_prefix_cache_blocks", state="cached")
        self._g_prefix_pinned = reg.gauge(
            "serve_prefix_cache_blocks", state="pinned")
        self._c_prefix_hits = reg.counter("serve_prefix_cache_hits_total")
        self._c_prefix_misses = reg.counter(
            "serve_prefix_cache_misses_total")
        self._c_prefix_evictions = reg.counter(
            "serve_prefix_cache_evictions_total")
        self._h_matched_prefix = reg.histogram("serve_matched_prefix_len")
        self._evictions_seen = 0
        self._c_tokens_out = reg.counter("serve_tokens_generated_total")
        self._c_prefill_tok = reg.counter("serve_prefill_tokens_total")
        self._c_completed = {
            r: reg.counter("serve_requests_completed_total", reason=r)
            for r in ("eos", "max_tokens", "length")}
        if self.telemetry is not None:
            self.telemetry.attach_registry(reg)
            if getattr(cfg, "metrics_interval", 0) or getattr(
                    cfg, "metrics_port", 0):
                self.telemetry.start_exporter(
                    interval_s=getattr(cfg, "metrics_interval", 0.0),
                    port=getattr(cfg, "metrics_port", 0))
        # elastic decode-mesh scaling (--elastic): poll the visible
        # device set between steps and grow/shrink the decode mesh via
        # replan_mesh; in-flight requests ride through untouched
        self._capacity_watcher = None
        self._steps_since_capacity_check = 0
        self.replan_decisions: list[dict] = []
        if getattr(cfg, "elastic", False):
            self.enable_autoscale()

    def enable_autoscale(self, visible_devices_fn=None,
                         check_every: int = 16):
        """Arm between-steps capacity watching on the decode mesh: when
        the visible device set no longer matches it, the engine re-plans
        to the factorization CapacityWatcher proposes (grow or shrink).
        `visible_devices_fn` is injectable for tests."""
        from ..elastic import CapacityWatcher

        self._capacity_watcher = CapacityWatcher(
            self.decode_model, visible_devices_fn,
            check_every=max(1, int(check_every)))
        return self._capacity_watcher

    def _maybe_autoscale(self):
        """step() preamble: consume one capacity delta if the watcher
        sees one. Runs OUTSIDE the per-token device call — a re-plan
        happens between scheduler iterations, never inside one."""
        w = self._capacity_watcher
        if w is None:
            return
        self._steps_since_capacity_check += 1
        delta = w.check(self._steps_since_capacity_check)
        if delta is None or delta.new_axes is None:
            return
        self.replan_mesh(delta.new_axes, trigger="capacity")

    # ------------------------------------------------------------ session

    @contextlib.contextmanager
    def _active(self):
        """Route module-level telemetry to the trained model's session for
        the duration of one engine operation. No flush here — step() runs
        once per generated token, and a per-iteration flush would rewrite
        the whole trace buffer each time (quadratic I/O in the hot loop);
        the trace persists at compile end, drain end, and session close."""
        tel = self.telemetry
        if tel is None:
            yield
            return
        telemetry.activate(tel)
        try:
            yield
        finally:
            telemetry.deactivate(tel)

    # ------------------------------------------------------------ replan

    def replan_mesh(self, mesh_axis_sizes, trigger: str = "manual") -> dict:
        """Grow/shrink the decode mesh between scheduler iterations: a
        fresh decode compile at the new factorization (warm-start cache
        consulted, full verifier gate) followed by a verified, priced
        `migrate_state` of the live decode state — params AND the KV
        pools, whose global geometry is mesh-invariant (resolve_pool_
        blocks keys off the TRAINER's mesh), so every in-flight slot's
        cache rows move bit-exactly. The scheduler, block manager, page
        tables, and RNG are host-side and untouched — in-flight token
        streams continue exactly where they were. Returns the decision
        record (also in `self.replan_decisions` and the `replan`
        telemetry event stream)."""
        import copy as _copy

        from ..resilience.migrate import migrate_state

        axes = tuple(int(s) for s in mesh_axis_sizes)
        old_dec = self.decode_model
        with self._active():
            t0 = time.perf_counter()
            decision = {
                "trigger": str(trigger), "scope": "serving",
                "old_mesh_axes": {k: int(v)
                                  for k, v in old_dec.mesh.shape.items()},
                "new_axes": list(axes),
            }
            spec2 = _copy.copy(self.spec)
            spec2.config_overrides = dict(self.spec.config_overrides or {})
            spec2.config_overrides["mesh_axis_sizes"] = axes
            try:
                with telemetry.span("serve.replan", trigger=trigger):
                    new_dec, max_seq = build_decode_model(self.model, spec2)
                    decision["research_s"] = time.perf_counter() - t0
                    migrate_state(old_dec, new_dec)
            except Exception as e:
                decision["decision"] = "failed"
                decision["error"] = f"{type(e).__name__}: {e}"
                telemetry.event("replan", **decision)
                self.replan_decisions.append(decision)
                raise
            # swap the device surface; everything host-side (scheduler,
            # slots, block manager, stats) carries over untouched
            self.decode_model = new_dec
            self.max_seq_len = max_seq
            self._step_fn = new_dec.executor.build_decode_step()
            if self.block_manager is not None:
                self._copy_fn = new_dec.executor.build_block_copy()
            self._inject_fn = None  # rebuilt lazily on the new executor
            self.num_chips = int(new_dec.mesh.devices.size)
            trans = new_dec._transition or {}
            decision.update({
                "decision": "migrated",
                "new_mesh_axes": {k: int(v)
                                  for k, v in new_dec.mesh.shape.items()},
                "predicted_migration_s": trans.get("predicted_s"),
                "migration_measured_s": trans.get("measured_s"),
                "plan_origin": getattr(new_dec, "_plan_origin",
                                       new_dec._plan_source),
                "total_s": time.perf_counter() - t0,
            })
            telemetry.event("replan", **decision)
        self.replan_decisions.append(decision)
        return decision

    # ------------------------------------------------------------ intake

    def submit(self, prompt: Sequence[int], max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None) -> Request:
        """Queue one request (FCFS). Defaults come from the ServingSpec.
        A request the paged pool could NEVER serve (worst case exceeds
        the whole pool even capped at cache capacity) is rejected here,
        like the oversized-prompt check — not left to head-block the
        queue forever."""
        req = Request(
            prompt=[int(t) for t in prompt],
            max_new_tokens=(self.spec.max_new_tokens
                            if max_new_tokens is None else max_new_tokens),
            temperature=0.0 if temperature is None else float(temperature),
            eos_id=self.spec.eos_id if eos_id is None else eos_id,
        )
        mgr = self.block_manager
        if mgr is not None:
            needed = mgr.blocks_needed(len(req.prompt), req.max_new_tokens)
            if needed > mgr.num_blocks - 1:
                raise ValueError(
                    f"request needs {needed} KV blocks worst-case but the "
                    f"pool only has {mgr.num_blocks - 1} allocatable "
                    f"blocks; raise kv_num_blocks (or lower "
                    f"max_new_tokens / kv_block_size)")
        with self._active():
            telemetry.instant("serve.queued", trace=req.trace_id,
                              prompt_tokens=len(req.prompt))
        return self.scheduler.submit(req)

    # ------------------------------------------------------------ device step

    def _bucket(self, n: int) -> int:
        """Smallest power-of-two >= n, capped at prefill_chunk (which is
        itself the top bucket when it isn't a power of two) — the
        length-bucket set, so prompt raggedness costs O(log chunk)
        executables instead of one per distinct length."""
        b = 1
        while b < n:
            b *= 2
        return min(b, self.spec.prefill_chunk)

    def _stage_inputs(self, tokens: np.ndarray,
                      positions: np.ndarray) -> dict:
        """Stage one decode-graph call's input dict under the searched
        shardings: the token stream, positions, the page tables (paged
        layout), and the graph's constant feeds broadcast to the call's
        q width. Shared between the decode step and the speculative
        verify step (serving/speculative.py) so the two calls stage
        byte-identical feeds."""
        q = tokens.shape[1]
        dec = self.decode_model
        xs = {self._token_input: tokens, "positions": positions}
        if self.block_manager is not None:
            mgr = self.block_manager
            xs["page_table"] = np.asarray(
                [mgr.table(i) for i in range(self.spec.slots)], np.int32)
        for name, (dims, dtype, value) in self._const_inputs.items():
            from ..fftype import dtype_to_jnp

            xs[name] = np.full((dims[0], q) + tuple(dims[2:]), value,
                               dtype_to_jnp(dtype))
        specs = {}
        for name in xs:
            spec = dec._input_partition_spec(name)
            if spec is not None:
                specs[name] = spec
        return dec.executor.shard_batch(xs, specs)

    def _run_step(self, tokens: np.ndarray, positions: np.ndarray,
                  read_idx: np.ndarray) -> np.ndarray:
        """One decode-graph call: stage inputs with their searched
        shardings, run the donated step, return the sampled tokens."""
        import jax
        import jax.numpy as jnp

        dec = self.decode_model
        xs = self._stage_inputs(tokens, positions)
        if self._rng is None:
            self._rng = jax.random.key(dec.config.seed)
        self._rng, sub = jax.random.split(self._rng)
        temp = np.zeros((self.spec.slots,), np.float32)
        for s in self.scheduler.active_slots:
            temp[s.index] = s.request.temperature
        t0 = time.perf_counter()
        dec._state, next_tok = self._step_fn(
            dec._params, dec._state, xs,
            jnp.asarray(read_idx, jnp.int32), sub,
            jnp.asarray(temp))
        out = np.asarray(jax.device_get(next_tok))
        # this pair IS the serve_step_device_s measurement (observed
        # below) — a span here would double-record every decode step
        dt = time.perf_counter() - t0  # fflint: ok raw_timer_in_hot_path
        self._device_s += dt
        self._last_step_device_s = dt  # speculative decode-cost EMA feed
        self._h_step_device.observe(dt)
        if dec.config.sanitize_numerics:
            self._check_numerics()
        return out

    def _check_numerics(self):
        """Sanitizer check after a decode step (--sanitize-numerics):
        the token fetch above already drained the step, so the probe
        callbacks have fired; any new non-finite report is surfaced
        once per op as a serve.nonfinite event + error log instead of
        silently sampling from a NaN'd logits row."""
        import jax

        from ..sanitize import get_monitor
        from ..telemetry import log as fflog

        jax.effects_barrier()
        events = get_monitor().snapshot()
        seen = getattr(self, "_numerics_reported", set())
        for e in events:
            key = (e["op"], e["phase"])
            if key in seen:
                continue
            seen.add(key)
            telemetry.event("serve.nonfinite", op=e["op"],
                            phase=e["phase"])
            fflog.error(
                "serving: non-finite tensor at op %s (%s) during "
                "decode — the KV cache or weights are numerically "
                "dead", e["op"], e["phase"])
        self._numerics_reported = seen

    # ------------------------------------------------------------ paged

    def _can_admit(self, req: Request) -> bool:
        """Paged admission gate: reserve the request's worst case
        (prompt + max_new_tokens in blocks) so a decode write can never
        exhaust the pool mid-flight. A True answer IS the reservation —
        the scheduler admits exactly when the gate passes."""
        return self.block_manager.reserve(
            req.request_id, len(req.prompt), req.max_new_tokens)

    def _apply_copies(self, copies):
        """Run this iteration's COW copies on the pool state in one
        donated dispatch, padded to a power-of-two width with
        scratch→scratch no-op pairs (one cached executable per bucket)."""
        if not copies:
            return
        import jax.numpy as jnp

        b = 1
        while b < len(copies):
            b *= 2
        src = np.full((b,), SCRATCH_BLOCK, np.int32)
        dst = np.full((b,), SCRATCH_BLOCK, np.int32)
        for i, c in enumerate(copies):
            src[i], dst[i] = c.src, c.dst
        dec = self.decode_model
        self._c_cow_copies.inc(len(copies))
        with telemetry.span("serve.cow_copy", blocks=len(copies)):
            dec._state = self._copy_fn(
                dec._state, jnp.asarray(src), jnp.asarray(dst))

    def _prepare_writes(self, slot_positions: dict[int, range]):
        """Paged pre-step bookkeeping: make every block this iteration
        writes slot-owned (allocating / COW-copying via the BlockManager)
        and apply the copies to the device pools BEFORE the step runs."""
        if self.block_manager is None:
            return
        copies = []
        for idx, positions in slot_positions.items():
            copies.extend(self.block_manager.ensure_writable(idx, positions))
        self._apply_copies(copies)

    def _note_completion(self, slot, req: Request):
        hook = self._pre_release_hook
        if hook is not None:
            hook(slot, req)
        if self.block_manager is not None:
            self.block_manager.release(slot.index)
        if self._suppress_completion_events:
            # disagg prefill side: the request is not DONE, it is handed
            # off — the decode side (or the coordinator, for requests
            # that truly finish at prefill) records the completion once
            return
        self.record_completion(req)

    def record_completion(self, req: Request):
        """Request-grain completion accounting: latency histogram,
        reason counter, and the `serve.request` event the doctor's
        drained-TTFT identity counts. Split out of `_note_completion` so
        the disaggregated coordinator can record a request that finished
        at prefill (EOS on the first token) on the decode side, which
        owns completion accounting for the pair."""
        if req.e2e_s is not None:
            self._h_e2e.observe(req.e2e_s)
        c = self._c_completed.get(req.finish_reason)
        if c is None:  # unknown reason: labeled child created off-path
            c = self.metrics.counter("serve_requests_completed_total",
                                     reason=req.finish_reason or "unknown")
        c.inc()
        telemetry.instant("serve.done", request=req.request_id,
                          trace=req.trace_id, reason=req.finish_reason)
        telemetry.event(
            "serve.request", request_id=req.request_id,
            trace=req.trace_id,
            prompt_tokens=len(req.prompt), new_tokens=len(req.generated),
            finish_reason=req.finish_reason,
            ttft_s=req.ttft_s,
            queue_wait_s=req.queue_wait_s,
            matched_prefix_len=req.matched_prefix_len,
            total_s=(req.finish_t - req.submit_t
                     if req.finish_t is not None else None))

    # ------------------------------------------------------------ disagg

    def kv_pool_layers(self) -> list[str]:
        """Pool-bearing state node names in SORTED order — the layer
        axis of extract_kv / inject rows. Both handoff sides sort, so
        layer i's extracted rows land in layer i's pool."""
        return sorted(n for n, ws in self.decode_model._state.items()
                      if "pool_k" in ws)

    def extract_kv(self, slot_index: int, num_tokens: int):
        """Lift a slot's prompt-extent KV blocks off this engine's
        pools: (layers, blocks, block_size, embed) K and V row stacks.
        The disaggregated coordinator calls this from its pre-release
        hook — the completing slot's page table still maps the blocks."""
        import jax

        mgr = self.block_manager
        nblk = -(-num_tokens // mgr.block_size)
        idx = np.asarray(mgr.table(slot_index)[:nblk], np.int32)
        st = self.decode_model._state
        ks = [st[name]["pool_k"][idx] for name in self.kv_pool_layers()]
        vs = [st[name]["pool_v"][idx] for name in self.kv_pool_layers()]
        ks, vs = jax.device_get((ks, vs))
        return (np.stack([np.asarray(k) for k in ks]),
                np.stack([np.asarray(v) for v in vs]))

    def admit_prefilled(self, req: Request, first_token: int,
                        rows_k, rows_v) -> Optional[int]:
        """Decode-side admission of a request whose prompt KV was
        computed on the prefill pool: reserve the worst case, take a
        free slot with every prompt row accounted for, map any
        radix-cached prefix (the cross-pool hit path — a cached extent
        costs NO injection), COW/allocate the uncovered extent, inject
        the handed-off rows, and publish the prompt into this side's
        cache. Returns the number of blocks injected (0 = full prefix
        hit), or None when no slot or reservation is available — the
        coordinator retries next iteration, FCFS order preserved."""
        sched = self.scheduler
        mgr = self.block_manager
        if mgr is None:
            raise ValueError(
                "disaggregated admission requires the paged KV layout")
        if not sched.free_slots:
            return None
        if not mgr.reserve(req.request_id, len(req.prompt),
                           req.max_new_tokens):
            return None
        slot = sched.admit_prefilled(req, first_token)
        L = len(req.prompt)
        injected = 0
        with self._active():
            telemetry.instant("serve.admitted", trace=req.trace_id,
                              slot=slot.index, prefilled=True,
                              queue_wait_s=req.queue_wait_s)
            mgr.bind_reservation(req.request_id, slot.index)
            matched = mgr.match_prefix(req.prompt)
            skip = mgr.admit(slot.index, req.prompt)
            req.matched_prefix_len = matched
            self._h_matched_prefix.observe(matched)
            (self._c_prefix_hits if skip else self._c_prefix_misses).inc()
            if skip:
                telemetry.instant(
                    "serve.prefix_hit", slot=slot.index,
                    shared_tokens=skip, matched_prefix_len=matched,
                    prompt_tokens=L)
            bs = mgr.block_size
            nlb = -(-L // bs)
            if matched < L:
                # the partially-matched tail block (if any) COWs here,
                # so the injection below never writes a cached block
                self._apply_copies(
                    mgr.ensure_writable(slot.index, range(matched, L)))
                lb0 = matched // bs
                blocks = mgr.table(slot.index)[lb0:nlb]
                self._inject_rows(blocks, rows_k[:, lb0:nlb],
                                  rows_v[:, lb0:nlb])
                injected = nlb - lb0
            mgr.register_prompt(slot.index, req.prompt)
        return injected

    def _inject_rows(self, blocks, rows_k, rows_v):
        """One donated inject dispatch, padded to a power-of-two block
        count with (scratch, zero-rows) pairs — one cached executable
        per bucket, like the COW copies."""
        import jax.numpy as jnp

        if self._inject_fn is None:
            self._inject_fn = (
                self.decode_model.executor.build_kv_inject())
        b = 1
        while b < len(blocks):
            b *= 2
        idx = np.full((b,), SCRATCH_BLOCK, np.int32)
        idx[:len(blocks)] = blocks
        layers = rows_k.shape[0]
        pk = np.zeros((layers, b) + rows_k.shape[2:], rows_k.dtype)
        pv = np.zeros((layers, b) + rows_v.shape[2:], rows_v.dtype)
        pk[:, :len(blocks)] = rows_k
        pv[:, :len(blocks)] = rows_v
        dec = self.decode_model
        with telemetry.span("serve.kv_inject", blocks=len(blocks)):
            dec._state = self._inject_fn(
                dec._state, jnp.asarray(idx), jnp.asarray(pk),
                jnp.asarray(pv))

    # ------------------------------------------------------------ iterate

    def _publish_slot_gauges(self, prefilling, decoding):
        """Per-iteration occupancy/pool gauges — shared between the
        plain step and the speculative verify round (speculative.py), so
        both iteration shapes feed the same metrics plane."""
        sched = self.scheduler
        self._g_slots_active.set(len(prefilling) + len(decoding))
        self._g_queue_depth.set(sched.queue_depth)
        if self.block_manager is not None:
            mgr = self.block_manager
            self._g_blocks_free.set(mgr.free_blocks)
            self._g_blocks_used.set(mgr.blocks_in_use)
            self._g_blocks_reserved.set(mgr.reserved_total)
            cached_only = mgr.cached_only_blocks
            self._g_prefix_cached.set(cached_only)
            self._g_prefix_pinned.set(mgr.cached_blocks - cached_only)
            ev = mgr.stats.radix_evictions
            if ev > self._evictions_seen:
                self._c_prefix_evictions.inc(ev - self._evictions_seen)
                self._evictions_seen = ev
        telemetry.counter("serve.slots", {
            "active": len(prefilling) + len(decoding),
            "queue": sched.queue_depth,
            "occupancy": (len(prefilling) + len(decoding))
            / max(1, len(sched.slots))})

    def step(self) -> list[Request]:
        """ONE scheduler iteration (the Orca unit), ONE device call: admit
        pending requests into free slots, pick AT MOST ONE prefill chunk
        (the longest-waiting prefilling slot's next plan_chunks bucket),
        and advance every decoding slot one token in the same call — the
        chunked-prefill interleave that keeps long prompts from stalling
        the continuous batch. Returns the requests that completed during
        this iteration."""
        sched = self.scheduler
        done_before = len(sched.completed)
        self._maybe_autoscale()
        with self._active():
            gate = (self._can_admit
                    if self.block_manager is not None else None)
            for slot, req in sched.admissions(can_admit=gate):
                if self.block_manager is not None:
                    self.block_manager.bind_reservation(
                        req.request_id, slot.index)
                self._h_queue_wait.observe(req.queue_wait_s)
                telemetry.instant("serve.admitted", trace=req.trace_id,
                                  slot=slot.index,
                                  queue_wait_s=req.queue_wait_s)
            prefilling = [s for s in sched.slots if s.prefilling]
            decoding = [s for s in sched.slots if s.decoding]
            self._publish_slot_gauges(prefilling, decoding)
            if not prefilling and not decoding:
                return sched.completed[done_before:]

            # ---- choose this iteration's single prefill chunk (FCFS)
            pre = min(prefilling, key=lambda s: s.admit_seq) \
                if prefilling else None
            n = b = 0
            if pre is not None:
                mgr = self.block_manager
                if mgr is not None and pre.index not in mgr._tables:
                    # LAZY page-table build: matched against the registry
                    # at first-chunk time, so a burst of same-prefix
                    # requests still shares — the first resident computed
                    # and registered its blocks by the time the next one
                    # prefills (one chunk per iteration, FCFS)
                    matched = mgr.match_prefix(pre.request.prompt)
                    skip = mgr.admit(pre.index, pre.request.prompt)
                    pre.prefill_pos = skip
                    pre.request.matched_prefix_len = matched
                    self._h_matched_prefix.observe(matched)
                    (self._c_prefix_hits if skip
                     else self._c_prefix_misses).inc()
                    if skip:
                        telemetry.instant(
                            "serve.prefix_hit", slot=pre.index,
                            shared_tokens=skip,
                            matched_prefix_len=matched,
                            prompt_tokens=len(pre.request.prompt))
                L = len(pre.request.prompt)
                start, n = plan_chunks(
                    pre.prefill_pos, L, self.spec.prefill_chunk)[0]
                b = self._bucket(n)
            q = max(b, 1)

            tokens = np.zeros((self.spec.slots, q), np.int32)
            # scratch positions everywhere but live elements: no other
            # slot's cache state moves (row max_seq for the contiguous
            # layout; the paged op routes clipped positions to the
            # reserved scratch block)
            positions = np.full((self.spec.slots, q), self.max_seq_len,
                                np.int32)
            read_idx = np.zeros((self.spec.slots,), np.int32)
            writes: dict[int, range] = {}
            if pre is not None:
                prompt = pre.request.prompt
                tokens[pre.index, :n] = prompt[start:start + n]
                positions[pre.index, :n] = np.arange(
                    start, start + n, dtype=np.int32)
                read_idx[pre.index] = n - 1
                writes[pre.index] = range(start, start + n)
            for s in decoding:
                tokens[s.index, 0] = s.last_token
                positions[s.index, 0] = s.length
                writes[s.index] = range(s.length, s.length + 1)
            self._prepare_writes(writes)

            span = telemetry.span(
                "serve.prefill", slot=pre.index,
                trace=pre.request.trace_id,
                start=start, tokens=n,
                prompt_tokens=len(pre.request.prompt),
                decoding=len(decoding)) if pre is not None else \
                telemetry.span("serve.step", active=len(decoding))
            with span:
                next_tok = self._run_step(tokens, positions, read_idx)

            # ---- prefill bookkeeping (the chunk's writes landed)
            if pre is not None:
                self._prefill_tokens += n
                self._c_prefill_tok.inc(n)
                self._prefill_calls += 1
                pre.prefill_pos += n
                req = pre.request
                if pre.prefill_pos >= len(req.prompt):
                    pre.length = len(req.prompt)
                    pre.prefill_pos = None
                    if self.block_manager is not None:
                        self.block_manager.register_prompt(
                            pre.index, req.prompt)
                    # the final chunk's last live logits row samples the
                    # request's first token (TTFT lands here)
                    self._decode_tokens += 1
                    prev_t = req.last_token_t
                    if sched.note_token(pre, int(next_tok[pre.index])):
                        self._note_completion(pre, req)
                    self._observe_token(req, prev_t)
            # ---- decode bookkeeping
            if decoding:
                self._decode_iterations += 1
            for s in decoding:
                s.length += 1
                req = s.request
                self._decode_tokens += 1
                prev_t = req.last_token_t
                if sched.note_token(s, int(next_tok[s.index])):
                    self._note_completion(s, req)
                self._observe_token(req, prev_t)
        return sched.completed[done_before:]

    def _observe_token(self, req: Request, prev_t):
        """Latency bookkeeping for one sampled token: the request's first
        token lands TTFT, every later one lands a TBT observation."""
        self._c_tokens_out.inc()
        if prev_t is None:
            self._h_ttft.observe(req.ttft_s)
            telemetry.instant("serve.first_token", trace=req.trace_id,
                              ttft_s=req.ttft_s)
        else:
            self._h_tbt.observe(req.last_token_t - prev_t)

    @contextlib.contextmanager
    def _maybe_xprof(self):
        """--xprof-dir beyond fit: the serving step loop runs under the
        same `jax.profiler.trace` passthrough the training loop gets
        (model.py wraps fit), so decode/prefill show up in XProf and in
        ffscope attribution. No-op without the flag; a trace already
        active (e.g. a surrounding capture) wins without erroring."""
        xdir = getattr(getattr(self.model, "config", None),
                       "xprof_dir", None)
        if not xdir:
            yield
            return
        import jax

        try:
            jax.profiler.start_trace(xdir)
        except Exception:
            yield
            return
        try:
            yield
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass

    def profile_step(self) -> Optional[dict]:
        """Capture ONE scheduler iteration under `jax.profiler` and
        attribute its device time to the serving model's ops (ffscope) —
        the serving twin of `model.profile_step()`. Returns the profile
        section (also kept as `self.last_profile`), or None when the
        capture could not start (e.g. a trace is already active)."""
        import jax

        from ..scope.profile import StepProfiler

        prof = StepProfiler()
        it = self._decode_iterations
        if not prof.begin(it):
            return None
        try:
            self.step()
            jax.effects_barrier()
        except BaseException:
            prof.abandon()
            raise
        names = [n.name for n in self.model.graph.topo_order()] \
            if getattr(self.model, "graph", None) is not None else []
        section = prof.end(it, names)
        prof.close()
        if section is not None:
            section["source"] = "serving"
            with self._active():
                for row in section["ops"]:
                    if row["measured_s"] > 0:
                        telemetry.observe("op_time_s", row["measured_s"],
                                          op=row["name"])
        self.last_profile = section
        return section

    def run_until_drained(self, max_iterations: int = 0) -> list[Request]:
        """Iterate until queue and slots are empty; returns every request
        completed during the call. `max_iterations` > 0 bounds the loop
        (a safety valve for drivers)."""
        done: list[Request] = []
        t0 = time.perf_counter()
        it = 0
        with self._maybe_xprof():
            while not self.scheduler.drained:
                done.extend(self.step())
                it += 1
                if max_iterations and it >= max_iterations:
                    break
        self.note_drain(time.perf_counter() - t0)
        return done

    def note_drain(self, wall_s: float):
        """Close one measured window: record its wall-clock, emit the
        summary event, and export a drained metrics snapshot. The
        drain loop above calls this; open-loop drivers (serve_bench's
        --arrival-rate mode) step the engine themselves and call it
        directly when their trace completes."""
        self._last_wall_s = wall_s
        with self._active():
            telemetry.event("serve.summary", **self.metrics_summary())
        if self.telemetry is not None:
            self.telemetry.write_metrics_snapshot(
                reason="serve_drain", drained=bool(self.scheduler.drained))
            self.telemetry.flush()

    def generate(self, prompts: Sequence[Sequence[int]],
                 **request_kw) -> list[list[int]]:
        """Convenience batch API: submit every prompt, drain, return the
        generated token lists in submission order."""
        reqs = [self.submit(p, **request_kw) for p in prompts]
        self.run_until_drained()
        return [r.generated for r in reqs]

    # ------------------------------------------------------------ stats

    def reset_stats(self) -> None:
        """Zero the run accounting (and the completed-request list) —
        benchmark drivers call this after a warm-up drain so the measured
        window starts clean. Live slots/queue state is untouched."""
        self.scheduler.completed.clear()
        self._decode_iterations = 0
        self._decode_tokens = 0
        self._prefill_tokens = 0
        self._prefill_calls = 0
        self._device_s = 0.0
        self._last_wall_s = 0.0
        # zero the serving series (objects survive — the step loop holds
        # references); the stats_reset event marks the window boundary so
        # doctor's TTFT identity counts serve.request events after it
        self.metrics.reset(prefix="serve_")
        self._g_slots_total.set(self.spec.slots)
        with self._active():
            telemetry.event("serve.stats_reset")
        if self.block_manager is not None:
            from .paged import PagedStats

            fresh = PagedStats()
            # live blocks carry over — the measured window's peak must
            # still dominate what is resident when it opens
            fresh.blocks_in_use_peak = self.block_manager.blocks_in_use
            self.block_manager.stats = fresh
            # the eviction-delta poll restarts from the fresh counter
            self._evictions_seen = 0

    def stats(self) -> dict:
        """Aggregate run metrics; rates are per chip of the decode mesh
        over the last drain's WALL-clock window — scheduler and telemetry
        overhead included, since that is the throughput a client sees
        (`device_s` reports the device-busy slice separately;
        requests/s/chip is the ROADMAP's serving bench target)."""
        completed = self.scheduler.completed
        sched = self.scheduler
        wall = getattr(self, "_last_wall_s", 0.0) or 0.0
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        # drain-time accounting gap: requests that never emitted a token
        # (still queued, mid-prefill at shutdown, or defensively a
        # completed request with no first_token_t) are EXCLUDED from the
        # TTFT population above by design — a queue-depth artifact is not
        # a latency sample — but must not vanish: they count here.
        no_token = (len(sched.pending)
                    + sum(1 for s in sched.active_slots
                          if s.request.first_token_t is None)
                    + sum(1 for r in completed
                          if r.first_token_t is None))
        out = {
            "no_token_requests": no_token,
            "slots": self.spec.slots,
            "max_seq_len": self.max_seq_len,
            "num_chips": self.num_chips,
            "requests_completed": len(completed),
            "decode_iterations": self._decode_iterations,
            "decode_tokens": self._decode_tokens,
            "prefill_tokens": self._prefill_tokens,
            "prefill_calls": self._prefill_calls,
            "wall_s": wall,
            "device_s": self._device_s,
            "plan_source": self.decode_model._plan_source,
            "kv_layout": self.spec.kv_layout,
        }
        out["kv_hbm_bytes_per_layer"] = self.kv_bytes_per_layer()
        if self.block_manager is not None:
            mgr = self.block_manager
            out.update({
                "kv_block_size": mgr.block_size,
                "kv_pool_blocks": mgr.num_blocks,
                "kv_blocks_in_use_peak": mgr.stats.blocks_in_use_peak,
                "prefix_hit_rate": mgr.stats.prefix_hit_rate,
                "prefix_shared_tokens": mgr.stats.shared_tokens,
                "cow_copies": mgr.stats.cow_copies,
                # radix prefix-cache plane: cross-time hits are the
                # prefixes that survived their residents (the cache's
                # whole reason to exist); evictions price the budget
                "prefix_cache": bool(self.spec.prefix_cache),
                "cross_time_hits": mgr.stats.cross_time_hits,
                "radix_evictions": mgr.stats.radix_evictions,
                "radix_evicted_blocks": mgr.stats.radix_evicted_blocks,
                "prefix_cached_blocks": mgr.cached_blocks,
                "prefix_cached_only_blocks": mgr.cached_only_blocks,
                # slots-at-fixed-HBM headline: how many contiguous
                # max_seq slots the pool's PEAK working set would buy —
                # the vLLM capacity-recovery metric
                "kv_peak_vs_contiguous": (
                    self.spec.slots * (self.max_seq_len + 1)
                    / max(1, mgr.stats.blocks_in_use_peak
                          * mgr.block_size)),
            })
        if ttfts:
            out["ttft_p50_s"] = float(np.percentile(np.asarray(ttfts), 50))
            out["ttft_max_s"] = float(max(ttfts))
        if wall > 0:
            out["requests_per_sec_per_chip"] = (
                len(completed) / wall / self.num_chips)
            out["decode_tokens_per_sec_per_chip"] = (
                self._decode_tokens / wall / self.num_chips)
        return out

    def metrics_summary(self) -> dict:
        """stats() plus request-grain latency percentiles rebuilt from
        the engine's mergeable histograms — callable MID-RUN (histograms
        are cumulative; no drained completed-list needed), and the
        drain-time serve.summary event is exactly this dict. Old stats()
        keys are preserved; `ttft_p50_s`/`ttft_max_s` are re-derived from
        the histogram (estimate within one bucket width, max exact)."""
        from ..telemetry.metrics import percentile_from_hist

        out = self.stats()
        for short, h in (("queue_wait", self._h_queue_wait),
                         ("ttft", self._h_ttft),
                         ("tbt", self._h_tbt),
                         ("e2e", self._h_e2e)):
            if h.count == 0:
                continue
            hd = h.to_dict()
            for q in (50, 95, 99):
                out[f"{short}_p{q}_s"] = percentile_from_hist(hd, q)
            out[f"{short}_max_s"] = h.max
            out[f"{short}_mean_s"] = h.sum / h.count
        return out

    def kv_bytes_per_layer(self) -> int:
        """Resident KV bytes ONE attention layer holds under this
        engine's layout (fp32, unsharded): the pool for paged — counted
        once, however many page tables map its blocks — or the full
        (slots, max_seq+1) region for contiguous. The serving bench's
        slots-at-fixed-HBM comparison reads this."""
        from ..fftype import OperatorType as OT

        for n in self.decode_model.graph.topo_order():
            if n.op_type == OT.OP_PAGED_INC_MULTIHEAD_ATTENTION:
                p = n.params
                return 2 * 4 * p.num_blocks * p.block_size * p.embed_dim
            if n.op_type == OT.OP_INC_MULTIHEAD_ATTENTION:
                p = n.params
                return 2 * 4 * self.spec.slots * (p.max_seq_len + 1) \
                    * p.embed_dim
        return 0
