"""ServingEngine: continuous-batching inference on the decode PCG.

The device side of serving (scheduler.py is the policy side): one donated
jitted step (`Executor.build_decode_step`) threads (params, kv-cache
state, tokens, positions) and returns the next token per slot, sampled
in-program (greedy / temperature-Gumbel per slot). Prefill reuses the
pipelined engine's chunk planner (engine/chunking.plan_chunks) to walk a
prompt through the SAME step in power-of-two length buckets — each bucket
one cached executable — writing the prompt's K/V rows into the admitted
slot's cache while every other slot's writes land on the scratch row
(position redirection, ops/inc_attention.py), so a fixed-shape executable
serves slots at arbitrary, different sequence positions.

Invariants the tests pin down (tests/test_serving.py):
  - greedy decode is token-identical to the teacher-forced training
    forward's argmax at every position;
  - an interleaved continuous batch is token-identical to serving each
    request alone (slot rows are computed independently);
  - the engine compile is a normal Unity compile: warm-start plan-cache
    hits apply (second serving compile of the same (model, slots,
    max_seq, mesh) = 0 search evaluations).

Telemetry (when the trained model has a session): `serve.compile` /
`serve.prefill` / `serve.step` spans, per-iteration queue-depth and
slot-occupancy counters, a `serve.request` event per completion carrying
time-to-first-token, and a `serve.summary` event with requests/s/chip and
decode tokens/s/chip.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional, Sequence

import numpy as np

from .. import telemetry
from ..engine.chunking import plan_chunks
from .decode_graph import ServingSpec, adopt_params, build_decode_model
from .scheduler import ContinuousBatchingScheduler, Request


class ServingEngine:
    def __init__(self, model, **overrides):
        import jax

        if jax.process_count() > 1:
            raise NotImplementedError(
                "serving runs single-controller for now (multi-host "
                "serving is the prefill/decode disaggregation item, "
                "ROADMAP)")
        cfg = model.config
        spec = ServingSpec(
            slots=cfg.serve_slots,
            max_seq_len=cfg.serve_max_seq_len,
            prefill_chunk=cfg.serve_prefill_chunk,
        )
        for k, v in overrides.items():
            if not hasattr(spec, k):
                raise ValueError(f"serve(): unknown option {k!r}")
            setattr(spec, k, v)
        if spec.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.model = model
        self.spec = spec
        self.telemetry = model._telemetry
        with self._active():
            t0 = time.perf_counter()
            with telemetry.span("serve.compile", slots=spec.slots):
                self.decode_model, self.max_seq_len = build_decode_model(
                    model, spec)
                self.adopted = adopt_params(self.decode_model, model)
                self._step_fn = (
                    self.decode_model.executor.build_decode_step())
            telemetry.event(
                "serve.compile",
                duration_s=time.perf_counter() - t0,
                slots=spec.slots, max_seq_len=self.max_seq_len,
                prefill_chunk=spec.prefill_chunk,
                plan_source=self.decode_model._plan_source,
                weights_adopted=self.adopted,
                mesh_axes={k: int(v) for k, v
                           in self.decode_model.mesh.shape.items()})
            if self.telemetry is not None:
                self.telemetry.flush()
        self.scheduler = ContinuousBatchingScheduler(
            spec.slots, self.max_seq_len)
        self.num_chips = int(self.decode_model.mesh.devices.size)
        self._rng = None  # lazily split jax PRNG for sampling steps
        # graph input roles: exactly one token stream + the positions feed
        # (+ constants, which the engine materializes itself)
        self._token_input = None
        self._const_inputs = {}
        for t in self.decode_model._input_tensors:
            if t.name == "positions":
                continue
            if hasattr(t, "constant_value"):
                self._const_inputs[t.name] = (
                    tuple(t.dims), t.dtype, t.constant_value)
            elif self._token_input is None:
                self._token_input = t.name
            else:
                raise ValueError(
                    f"serving needs exactly one token input; model has "
                    f"{self._token_input!r} and {t.name!r}")
        if self._token_input is None:
            raise ValueError("serving: model has no token input")
        # sanitizer baseline: events reported before this engine existed
        # (e.g. a training NaN earlier in the process) are not decode
        # corruption — only NEW reports surface as serve.nonfinite
        if self.decode_model.config.sanitize_numerics:
            from ..sanitize import get_monitor

            self._numerics_reported = {
                (e["op"], e["phase"]) for e in get_monitor().snapshot()}
        # run accounting (stats())
        self._decode_iterations = 0
        self._decode_tokens = 0
        self._prefill_tokens = 0
        self._prefill_calls = 0
        self._device_s = 0.0

    # ------------------------------------------------------------ session

    @contextlib.contextmanager
    def _active(self):
        """Route module-level telemetry to the trained model's session for
        the duration of one engine operation. No flush here — step() runs
        once per generated token, and a per-iteration flush would rewrite
        the whole trace buffer each time (quadratic I/O in the hot loop);
        the trace persists at compile end, drain end, and session close."""
        tel = self.telemetry
        if tel is None:
            yield
            return
        telemetry.activate(tel)
        try:
            yield
        finally:
            telemetry.deactivate(tel)

    # ------------------------------------------------------------ intake

    def submit(self, prompt: Sequence[int], max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None) -> Request:
        """Queue one request (FCFS). Defaults come from the ServingSpec."""
        req = Request(
            prompt=[int(t) for t in prompt],
            max_new_tokens=(self.spec.max_new_tokens
                            if max_new_tokens is None else max_new_tokens),
            temperature=0.0 if temperature is None else float(temperature),
            eos_id=self.spec.eos_id if eos_id is None else eos_id,
        )
        return self.scheduler.submit(req)

    # ------------------------------------------------------------ device step

    def _bucket(self, n: int) -> int:
        """Smallest power-of-two >= n, capped at prefill_chunk (which is
        itself the top bucket when it isn't a power of two) — the
        length-bucket set, so prompt raggedness costs O(log chunk)
        executables instead of one per distinct length."""
        b = 1
        while b < n:
            b *= 2
        return min(b, self.spec.prefill_chunk)

    def _run_step(self, tokens: np.ndarray, positions: np.ndarray,
                  read_idx: np.ndarray) -> np.ndarray:
        """One decode-graph call: stage inputs with their searched
        shardings, run the donated step, return the sampled tokens."""
        import jax
        import jax.numpy as jnp

        dec = self.decode_model
        q = tokens.shape[1]
        xs = {self._token_input: tokens, "positions": positions}
        for name, (dims, dtype, value) in self._const_inputs.items():
            from ..fftype import dtype_to_jnp

            xs[name] = np.full((dims[0], q) + tuple(dims[2:]), value,
                               dtype_to_jnp(dtype))
        specs = {}
        for name in xs:
            spec = dec._input_partition_spec(name)
            if spec is not None:
                specs[name] = spec
        xs = dec.executor.shard_batch(xs, specs)
        if self._rng is None:
            self._rng = jax.random.key(dec.config.seed)
        self._rng, sub = jax.random.split(self._rng)
        temp = np.zeros((self.spec.slots,), np.float32)
        for s in self.scheduler.active_slots:
            temp[s.index] = s.request.temperature
        t0 = time.perf_counter()
        dec._state, next_tok = self._step_fn(
            dec._params, dec._state, xs,
            jnp.asarray(read_idx, jnp.int32), sub,
            jnp.asarray(temp))
        out = np.asarray(jax.device_get(next_tok))
        self._device_s += time.perf_counter() - t0
        if dec.config.sanitize_numerics:
            self._check_numerics()
        return out

    def _check_numerics(self):
        """Sanitizer check after a decode step (--sanitize-numerics):
        the token fetch above already drained the step, so the probe
        callbacks have fired; any new non-finite report is surfaced
        once per op as a serve.nonfinite event + error log instead of
        silently sampling from a NaN'd logits row."""
        import jax

        from ..sanitize import get_monitor
        from ..telemetry import log as fflog

        jax.effects_barrier()
        events = get_monitor().snapshot()
        seen = getattr(self, "_numerics_reported", set())
        for e in events:
            key = (e["op"], e["phase"])
            if key in seen:
                continue
            seen.add(key)
            telemetry.event("serve.nonfinite", op=e["op"],
                            phase=e["phase"])
            fflog.error(
                "serving: non-finite tensor at op %s (%s) during "
                "decode — the KV cache or weights are numerically "
                "dead", e["op"], e["phase"])
        self._numerics_reported = seen

    # ------------------------------------------------------------ prefill

    def _prefill(self, slot, req: Request):
        """Walk the prompt through the decode step in bucketed chunks,
        filling `slot`'s cache rows; the final chunk's last live logits
        row samples the request's first token (TTFT lands here)."""
        prompt = req.prompt
        L = len(prompt)
        chunks = plan_chunks(0, L, self.spec.prefill_chunk)
        with telemetry.span("serve.prefill", slot=slot.index,
                            prompt_tokens=L, chunks=len(chunks)):
            for start, n in chunks:
                b = self._bucket(n)
                tokens = np.zeros((self.spec.slots, b), np.int32)
                # scratch-row positions everywhere but the admitted slot's
                # live elements: no other slot's cache state moves
                positions = np.full((self.spec.slots, b), self.max_seq_len,
                                    np.int32)
                read_idx = np.zeros((self.spec.slots,), np.int32)
                tokens[slot.index, :n] = prompt[start:start + n]
                positions[slot.index, :n] = np.arange(
                    start, start + n, dtype=np.int32)
                read_idx[slot.index] = n - 1
                next_tok = self._run_step(tokens, positions, read_idx)
        self._prefill_tokens += L
        self._prefill_calls += len(chunks)
        slot.length = L
        first = int(next_tok[slot.index])
        self._decode_tokens += 1
        if not self.scheduler.note_token(slot, first):
            return
        self._note_completion(slot, req)

    def _note_completion(self, slot, req: Request):
        telemetry.instant("serve.done", request=req.request_id,
                          reason=req.finish_reason)
        telemetry.event(
            "serve.request", request_id=req.request_id,
            prompt_tokens=len(req.prompt), new_tokens=len(req.generated),
            finish_reason=req.finish_reason,
            ttft_s=req.ttft_s,
            total_s=(req.finish_t - req.submit_t
                     if req.finish_t is not None else None))

    # ------------------------------------------------------------ iterate

    def step(self) -> list[Request]:
        """ONE scheduler iteration (the Orca unit): admit pending requests
        into free slots (prefilling each), then run one decode step for
        every active slot. Returns the requests that completed during this
        iteration."""
        sched = self.scheduler
        done_before = len(sched.completed)
        with self._active():
            for slot, req in sched.admissions():
                self._prefill(slot, req)
            active = sched.active_slots
            telemetry.counter("serve.slots", {
                "active": len(active), "queue": sched.queue_depth,
                "occupancy": len(active) / max(1, len(sched.slots))})
            if active:
                tokens = np.zeros((self.spec.slots, 1), np.int32)
                positions = np.full((self.spec.slots, 1), self.max_seq_len,
                                    np.int32)
                read_idx = np.zeros((self.spec.slots,), np.int32)
                for s in active:
                    tokens[s.index, 0] = s.last_token
                    positions[s.index, 0] = s.length
                with telemetry.span("serve.step", active=len(active)):
                    next_tok = self._run_step(tokens, positions, read_idx)
                self._decode_iterations += 1
                for s in active:
                    s.length += 1
                    req = s.request
                    self._decode_tokens += 1
                    if self.scheduler.note_token(s, int(next_tok[s.index])):
                        self._note_completion(s, req)
        return sched.completed[done_before:]

    def run_until_drained(self, max_iterations: int = 0) -> list[Request]:
        """Iterate until queue and slots are empty; returns every request
        completed during the call. `max_iterations` > 0 bounds the loop
        (a safety valve for drivers)."""
        done: list[Request] = []
        t0 = time.perf_counter()
        it = 0
        while not self.scheduler.drained:
            done.extend(self.step())
            it += 1
            if max_iterations and it >= max_iterations:
                break
        self._last_wall_s = time.perf_counter() - t0
        with self._active():
            telemetry.event("serve.summary", **self.stats())
        if self.telemetry is not None:
            self.telemetry.flush()
        return done

    def generate(self, prompts: Sequence[Sequence[int]],
                 **request_kw) -> list[list[int]]:
        """Convenience batch API: submit every prompt, drain, return the
        generated token lists in submission order."""
        reqs = [self.submit(p, **request_kw) for p in prompts]
        self.run_until_drained()
        return [r.generated for r in reqs]

    # ------------------------------------------------------------ stats

    def reset_stats(self) -> None:
        """Zero the run accounting (and the completed-request list) —
        benchmark drivers call this after a warm-up drain so the measured
        window starts clean. Live slots/queue state is untouched."""
        self.scheduler.completed.clear()
        self._decode_iterations = 0
        self._decode_tokens = 0
        self._prefill_tokens = 0
        self._prefill_calls = 0
        self._device_s = 0.0
        self._last_wall_s = 0.0

    def stats(self) -> dict:
        """Aggregate run metrics; rates are per chip of the decode mesh
        over the last drain's WALL-clock window — scheduler and telemetry
        overhead included, since that is the throughput a client sees
        (`device_s` reports the device-busy slice separately;
        requests/s/chip is the ROADMAP's serving bench target)."""
        completed = self.scheduler.completed
        wall = getattr(self, "_last_wall_s", 0.0) or 0.0
        ttfts = [r.ttft_s for r in completed if r.ttft_s is not None]
        out = {
            "slots": self.spec.slots,
            "max_seq_len": self.max_seq_len,
            "num_chips": self.num_chips,
            "requests_completed": len(completed),
            "decode_iterations": self._decode_iterations,
            "decode_tokens": self._decode_tokens,
            "prefill_tokens": self._prefill_tokens,
            "prefill_calls": self._prefill_calls,
            "wall_s": wall,
            "device_s": self._device_s,
            "plan_source": self.decode_model._plan_source,
        }
        if ttfts:
            out["ttft_p50_s"] = float(np.percentile(np.asarray(ttfts), 50))
            out["ttft_max_s"] = float(max(ttfts))
        if wall > 0:
            out["requests_per_sec_per_chip"] = (
                len(completed) / wall / self.num_chips)
            out["decode_tokens_per_sec_per_chip"] = (
                self._decode_tokens / wall / self.num_chips)
        return out
