"""Paged KV-cache management: block pool, page tables, COW prefix reuse.

The host-side policy half of the paged serving layout (the device half is
ops/inc_attention.py's paged op + kernels/flash_attention.py's paged
decode kernel). vLLM/PagedAttention (SOSP '23, PAPERS.md) is the
grounding: KV rows live in fixed-size BLOCKS drawn from one shared pool;
each slot owns a PAGE TABLE mapping its logical block index to a physical
block. Three consequences this module implements:

- **allocation at block granularity** — a slot holds ceil(length/bs)
  blocks, not max_seq rows, so short generations stop paying long-context
  HBM and the pool (not slots × max_seq) bounds concurrency;
- **prefix sharing with refcounts** — prompt blocks are registered under
  the FULL token prefix they encode (K/V of a row depends on every token
  before it, so the key is the whole prefix, not the block's own tokens);
  a new request whose prompt extends a registered prefix maps the shared
  blocks into its own table (refcount++) and skips recomputing them —
  N requests with one system prompt store and prefill it once;
- **copy-on-write** — a write (decode append, or a prompt tail diverging
  inside a shared partial block) targeting a block with refcount > 1
  first copies it to a fresh block (`CopyPlan` — the engine runs the
  device-side block copy), so divergence is paid only at the first
  divergent write and only for the one block it lands in.

Physical block 0 is the RESERVED SCRATCH BLOCK (never allocated, never
freed): unallocated page-table entries point at it, and the device op
routes position-clipped writes there — the paged equivalent of the
contiguous layout's scratch row.

Sharing is among LIVE residents: releasing a slot decrements its blocks'
refcounts and a block returning to refcount 0 is freed and unregistered
(refcount-exact reclamation — tested). There is no cross-time cache; the
continuous batch's overlap is what the shared-prefix bench measures.

Pure host code (no jax): unit-testable without a mesh.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

SCRATCH_BLOCK = 0


def _chain(digest: bytes, tokens) -> bytes:
    """One prefix-hash chaining step: digest of (parent digest, the next
    run of tokens). K/V rows depend on the ENTIRE prefix before them, so
    a block's content address must encode every earlier token — chaining
    from the parent block's digest does that in O(block) per block
    (vLLM's hash-based prefix caching scheme) instead of hashing the
    whole O(L) prefix tuple per block."""
    h = hashlib.sha256(digest)
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


@dataclass
class CopyPlan:
    """One COW copy the engine must run on the pool state BEFORE the next
    device step writes: physical block `src` duplicated into `dst`."""

    src: int
    dst: int


@dataclass
class PagedStats:
    prefix_queries: int = 0        # admissions that attempted a match
    prefix_hits: int = 0           # admissions that shared >= 1 block
    shared_tokens: int = 0         # prompt tokens served from shared blocks
    prompt_tokens: int = 0         # total prompt tokens admitted
    cow_copies: int = 0
    blocks_in_use_peak: int = 0

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admitted prompt tokens whose K/V came from a shared
        block instead of being recomputed and re-stored."""
        if self.prompt_tokens == 0:
            return 0.0
        return self.shared_tokens / self.prompt_tokens


class BlockManager:
    """Refcounted block pool + per-slot page tables + prefix registry."""

    def __init__(self, num_blocks: int, block_size: int, table_width: int,
                 sharing: bool = True):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (scratch + 1 allocatable), got "
                f"{num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.table_width = int(table_width)
        self.sharing = bool(sharing)  # False = paged-without-reuse ablation
        # LIFO free list: hot blocks are reused while still cached
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refcount: dict[int, int] = {}
        # admission reservations (worst-case fresh blocks per resident),
        # keyed by request id until bind_reservation moves the key to the
        # slot index: Σ reservations <= free blocks at all times, so a
        # decode write can NEVER exhaust the pool mid-flight — admission
        # is the only place pool pressure is felt (FCFS head-blocking)
        self._reserved: dict = {}
        # slot index -> logical->physical list (allocated prefix only)
        self._tables: dict[int, list[int]] = {}
        # prefix registry: chained digest of prompt[:end] (see _chain) ->
        # physical block holding rows [end - fill, end); a partial tail's
        # digest covers its exact extent
        self._registry: dict[bytes, int] = {}
        self._block_key: dict[int, bytes] = {}  # reverse map for unregister
        self.stats = PagedStats()

    # ------------------------------------------------------------ queries

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    def table(self, slot: int) -> list[int]:
        """The slot's page table padded to table_width with SCRATCH (the
        row the engine feeds the device op)."""
        t = self._tables.get(slot, [])
        return t + [SCRATCH_BLOCK] * (self.table_width - len(t))

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case fresh blocks a request can consume over its life:
        every block of [0, prompt + new), CAPPED at the logical capacity
        (table_width) — generation physically stops at max_seq rows (the
        scheduler's `length` completion rule), so a huge max_new_tokens
        must not inflate the reservation past what the slot can ever
        write. Prefix sharing only ever LOWERS the real draw (a slot's
        shared blocks cost nothing, and at most one COW replaces a shared
        block with a fresh one), so reserving this at admission makes
        mid-flight exhaustion impossible."""
        return min(-(-(prompt_len + max_new_tokens) // self.block_size),
                   self.table_width)

    @property
    def reserved_total(self) -> int:
        return sum(self._reserved.values())

    def reserve(self, request_id, prompt_len: int,
                max_new_tokens: int) -> bool:
        """Admission gate: reserve the request's worst case against the
        pool. False = not enough headroom (the caller keeps the request
        queued — FCFS head-blocking, so admission order never depends on
        pool pressure in a way that could reorder token streams)."""
        needed = self.blocks_needed(prompt_len, max_new_tokens)
        if self.free_blocks - self.reserved_total < needed:
            return False
        self._reserved[("req", request_id)] = needed
        return True

    def bind_reservation(self, request_id, slot: int):
        """Move an admission reservation onto the slot that won it (the
        scheduler assigns slots after the gate passes)."""
        n = self._reserved.pop(("req", request_id), None)
        if n is not None:
            self._reserved[slot] = n

    # ------------------------------------------------------------ intake

    def _match(self, prompt: list[int]):
        """(covered, [block digests]): the longest registered prefix of
        `prompt` at block granularity — full blocks at every block_size
        boundary (digest chained per block), then the longest registered
        PARTIAL extent inside the next block (its digest covers the exact
        extent — a prompt of 6 registered tokens serves both its twin and
        a longer prompt extending it, the latter COWing on its first tail
        write). Digests are returned so admit() maps without rehashing."""
        bs = self.block_size
        L = len(prompt)
        covered = 0
        keys: list[bytes] = []
        if not self.sharing:
            return 0, keys
        digest = b""
        for end in range(bs, L + 1, bs):
            nxt = _chain(digest, prompt[end - bs:end])
            if nxt not in self._registry:
                break
            digest = nxt
            keys.append(nxt)
            covered = end
        best = None
        for end in range(covered + 1, min(covered + bs - 1, L) + 1):
            part = _chain(digest, prompt[covered:end])
            if part in self._registry:
                best = (end, part)
        if best is not None:
            covered = best[0]
            keys.append(best[1])
        return covered, keys

    def match_prefix(self, prompt: list[int]) -> int:
        """Covered token count of the longest registered prefix (see
        `_match`)."""
        return self._match(prompt)[0]

    def admit(self, slot: int, prompt: list[int]) -> int:
        """Build `slot`'s page table: map every shared prefix block
        (refcount++), leave the rest for prefill writes to allocate.
        Called LAZILY — at the slot's first prefill chunk, not at
        admission — so a burst of same-prefix requests still shares: by
        the time the second request prefills, the first has computed and
        registered its blocks. Returns the prefill cursor: prompt tokens
        whose K/V need no recomputation, capped at len(prompt) - 1
        because the final token's logits row samples the first generated
        token (its re-write into a fully-shared block is the first
        COW)."""
        if slot in self._tables:
            raise ValueError(f"slot {slot} already holds a table")
        L = len(prompt)
        covered, keys = self._match(prompt)
        self.stats.prefix_queries += 1
        table: list[int] = []
        for key in keys:
            # full blocks, plus the shared partial tail (mapped
            # read-only; the first write into it COWs)
            blk = self._registry[key]
            self._refcount[blk] += 1
            table.append(blk)
        self._tables[slot] = table
        skip = min(covered, L - 1)
        self.stats.prompt_tokens += L
        self.stats.shared_tokens += skip
        if skip:
            self.stats.prefix_hits += 1
        return skip

    # ------------------------------------------------------------ writes

    def _alloc(self, slot: int) -> int:
        if not self._free:
            raise RuntimeError(
                "paged KV pool exhausted — the admission reservations "
                "(reserve/blocks_needed) must prevent this")
        blk = self._free.pop()
        self._refcount[blk] = 1
        if slot in self._reserved:
            self._reserved[slot] = max(0, self._reserved[slot] - 1)
        self.stats.blocks_in_use_peak = max(
            self.stats.blocks_in_use_peak, self.blocks_in_use)
        return blk

    def ensure_writable(self, slot: int, positions) -> list[CopyPlan]:
        """Guarantee every logical block covering `positions` is owned
        (refcount 1) by `slot`, allocating fresh blocks past the table end
        and COW-copying shared ones. Returns the copies the engine must
        apply to the device pool BEFORE the step that writes. Also
        unregisters any owned block about to be written (its content — and
        therefore its prefix key — is changing)."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError(f"slot {slot} has no table")
        bs = self.block_size
        copies: list[CopyPlan] = []
        for lb in sorted({int(p) // bs for p in positions}):
            if lb >= self.table_width:
                raise ValueError(
                    f"position past the logical capacity "
                    f"({self.table_width * bs} rows)")
            while len(table) <= lb:
                table.append(self._alloc(slot))
            blk = table[lb]
            if self._refcount.get(blk, 0) > 1:
                fresh = self._alloc(slot)
                self._refcount[blk] -= 1
                table[lb] = fresh
                copies.append(CopyPlan(src=blk, dst=fresh))
                self.stats.cow_copies += 1
            elif blk in self._block_key:
                # sole owner writing into a registered block: future
                # prompts must not match stale content
                self._registry.pop(self._block_key.pop(blk), None)
        return copies

    def register_prompt(self, slot: int, prompt: list[int]):
        """Publish `slot`'s prompt blocks for prefix sharing (called once
        when its prefill completes): every full block under the full-
        prefix key, plus the partial tail. Blocks already registered (the
        shared source) keep their entry."""
        if not self.sharing:
            return
        table = self._tables.get(slot, [])
        bs = self.block_size
        L = len(prompt)
        digest = b""
        for lb in range(len(table)):
            end = min((lb + 1) * bs, L)
            if end <= lb * bs:
                break
            key = _chain(digest, prompt[lb * bs:end])
            if end == (lb + 1) * bs:
                digest = key  # full block: the next block chains from it
            if key not in self._registry:
                blk = table[lb]
                if blk in self._block_key:
                    continue  # already published under another key
                self._registry[key] = blk
                self._block_key[blk] = key

    # ------------------------------------------------------------ release

    def release(self, slot: int):
        """Drop the slot's table; refcounts decrement and blocks reaching
        zero return to the free list (and leave the prefix registry)."""
        self._reserved.pop(slot, None)
        table = self._tables.pop(slot, None)
        if table is None:
            return
        for blk in table:
            n = self._refcount.get(blk, 0) - 1
            if n > 0:
                self._refcount[blk] = n
                continue
            self._refcount.pop(blk, None)
            self._registry.pop(self._block_key.pop(blk, None), None)
            self._free.append(blk)

    def check_invariants(self):
        """Debug/test hook: every block is free xor refcounted, the
        scratch block is neither, and table entries are refcounted."""
        free = set(self._free)
        assert SCRATCH_BLOCK not in free
        assert SCRATCH_BLOCK not in self._refcount
        assert not (free & set(self._refcount)), "block both free and live"
        for slot, table in self._tables.items():
            for blk in table:
                assert self._refcount.get(blk, 0) >= 1, \
                    f"slot {slot} maps unrefcounted block {blk}"
        counted = sum(1 for _ in self._refcount)
        assert counted + len(free) == self.num_blocks - 1, \
            "pool accounting leak"
        assert self.reserved_total <= self.free_blocks, \
            "reservations exceed the free pool"
