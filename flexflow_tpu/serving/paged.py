"""Paged KV-cache management: block pool, page tables, COW prefix reuse.

The host-side policy half of the paged serving layout (the device half is
ops/inc_attention.py's paged op + kernels/flash_attention.py's paged
decode kernel). vLLM/PagedAttention (SOSP '23, PAPERS.md) is the
grounding: KV rows live in fixed-size BLOCKS drawn from one shared pool;
each slot owns a PAGE TABLE mapping its logical block index to a physical
block. Three consequences this module implements:

- **allocation at block granularity** — a slot holds ceil(length/bs)
  blocks, not max_seq rows, so short generations stop paying long-context
  HBM and the pool (not slots × max_seq) bounds concurrency;
- **prefix sharing via a radix tree** (radix.RadixPrefixCache) — prompt
  blocks are published into a token-labelled radix tree at prefill
  completion, keyed on the PROMPT extent only (K/V of a row depends on
  every token before it, so tree position is the content address); a new
  request maps the longest cached extent — including a partial match
  inside one block — into its own table (refcount++) and skips
  recomputing it. Each cached node holds one refcount on its block (the
  CACHE PIN), so prefixes SURVIVE their residents: sharing is
  cross-time, not just among live slots;
- **copy-on-write** — a write (decode append, or a prompt tail diverging
  inside a shared block) targeting a block with more than one reference
  first copies it to a fresh block (`CopyPlan` — the engine runs the
  device-side block copy). The pin makes every cached block
  COW-protected: a decode extending past its prompt can never overwrite
  cached prompt content (the poisoning the old full-prefix registry
  allowed), it pays one copy and owns the fresh block.

Physical block 0 is the RESERVED SCRATCH BLOCK (never allocated, never
freed): unallocated page-table entries point at it, and the device op
routes position-clipped writes there — the paged equivalent of the
contiguous layout's scratch row.

Pool pressure: admission reserves each request's worst case against the
FREE list (Σ reservations <= free blocks at all times, so a decode write
can NEVER exhaust the pool mid-flight); when the free list is too small,
`reserve` first EVICTS cold cache leaves LRU-first (radix.evict_lru) —
an evicted node only frees its block when the pin was the last
reference; a block a live slot still maps merely leaves the cache.
`cross_time=False` reproduces the old live-residents-only sharing (the
pin is dropped as the last holder releases) — the bench ablation.

Pure host code (no jax): unit-testable without a mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

from .radix import RadixPrefixCache

SCRATCH_BLOCK = 0


@dataclass
class CopyPlan:
    """One COW copy the engine must run on the pool state BEFORE the next
    device step writes: physical block `src` duplicated into `dst`."""

    src: int
    dst: int


@dataclass
class PagedStats:
    prefix_queries: int = 0        # admissions that attempted a match
    prefix_hits: int = 0           # admissions that shared >= 1 token
    shared_tokens: int = 0         # prompt tokens served from shared blocks
    prompt_tokens: int = 0         # total prompt tokens admitted
    cow_copies: int = 0
    blocks_in_use_peak: int = 0    # peak LIVE blocks (cache-only excluded)
    cross_time_hits: int = 0       # hits where a matched block had no
    #                                live holder — served from the cache
    #                                after its residents exited
    radix_evictions: int = 0       # nodes evicted (LRU or pin-drop)
    radix_evicted_blocks: int = 0  # blocks actually freed by eviction

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admitted prompt tokens whose K/V came from a shared
        block instead of being recomputed and re-stored."""
        if self.prompt_tokens == 0:
            return 0.0
        return self.shared_tokens / self.prompt_tokens


class BlockManager:
    """Refcounted block pool + per-slot page tables + radix prefix cache.

    `refcount(blk)` reports LIVE holders (slots mapping the block); the
    cache pin is internal bookkeeping and excluded. `blocks_in_use`
    likewise counts live blocks only — a drained pool reads 0 even while
    the cache retains (evictable) blocks.
    """

    def __init__(self, num_blocks: int, block_size: int, table_width: int,
                 sharing: bool = True, cross_time: bool = False):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (scratch + 1 allocatable), got "
                f"{num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.table_width = int(table_width)
        self.sharing = bool(sharing)  # False = paged-without-reuse ablation
        self.cross_time = bool(cross_time)  # False = live sharing only
        # LIFO free list: hot blocks are reused while still cached
        self._free = list(range(num_blocks - 1, 0, -1))
        # RAW references: live slot mappings + (if cached) one cache pin
        self._refcount: dict[int, int] = {}
        self._live = 0  # blocks with >= 1 live (non-pin) reference
        # admission reservations (worst-case fresh blocks per resident),
        # keyed by request id until bind_reservation moves the key to the
        # slot index: Σ reservations <= free blocks at all times, so a
        # decode write can NEVER exhaust the pool mid-flight — admission
        # is the only place pool pressure is felt (FCFS head-blocking)
        self._reserved: dict = {}
        # slot index -> logical->physical list (allocated prefix only)
        self._tables: dict[int, list[int]] = {}
        self.cache = RadixPrefixCache(block_size) if self.sharing else None
        self.stats = PagedStats()

    # ------------------------------------------------------------ queries

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Blocks held by at least one live slot (cache-only excluded)."""
        return self._live

    @property
    def cached_blocks(self) -> int:
        """Blocks the radix cache holds a pin on (live-shared or not)."""
        return 0 if self.cache is None else len(self.cache.pinned)

    @property
    def cached_only_blocks(self) -> int:
        """Cached blocks whose pin is the sole reference — the evictable
        set the admission gate can reclaim."""
        if self.cache is None:
            return 0
        return sum(1 for b in self.cache.pinned
                   if self._refcount.get(b, 0) == 1)

    def table(self, slot: int) -> list[int]:
        """The slot's page table padded to table_width with SCRATCH (the
        row the engine feeds the device op)."""
        t = self._tables.get(slot, [])
        return t + [SCRATCH_BLOCK] * (self.table_width - len(t))

    def _pinned(self, block: int) -> bool:
        return self.cache is not None and block in self.cache.pinned

    def refcount(self, block: int) -> int:
        """LIVE holders of `block` (the cache pin is excluded)."""
        rc = self._refcount.get(block, 0)
        return rc - 1 if rc and self._pinned(block) else rc

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case fresh blocks a request can consume over its life:
        every block of [0, prompt + new), CAPPED at the logical capacity
        (table_width) — generation physically stops at max_seq rows (the
        scheduler's `length` completion rule), so a huge max_new_tokens
        must not inflate the reservation past what the slot can ever
        write. Prefix sharing only ever LOWERS the real draw (a slot's
        shared blocks cost nothing, and at most one COW replaces a shared
        block with a fresh one), so reserving this at admission makes
        mid-flight exhaustion impossible."""
        return min(-(-(prompt_len + max_new_tokens) // self.block_size),
                   self.table_width)

    @property
    def reserved_total(self) -> int:
        return sum(self._reserved.values())

    def reserve(self, request_id, prompt_len: int,
                max_new_tokens: int) -> bool:
        """Admission gate: reserve the request's worst case against the
        pool, evicting cold cache leaves first when the free list alone
        cannot cover it. False = not enough headroom even after eviction
        (the caller keeps the request queued — FCFS head-blocking, so
        admission order never depends on pool pressure in a way that
        could reorder token streams)."""
        needed = self.blocks_needed(prompt_len, max_new_tokens)
        headroom = self.free_blocks - self.reserved_total
        if headroom < needed:
            self._evict_blocks(needed - headroom)
        if self.free_blocks - self.reserved_total < needed:
            return False
        self._reserved[("req", request_id)] = needed
        return True

    def bind_reservation(self, request_id, slot: int):
        """Move an admission reservation onto the slot that won it (the
        scheduler assigns slots after the gate passes)."""
        n = self._reserved.pop(("req", request_id), None)
        if n is not None:
            self._reserved[slot] = n

    # --------------------------------------------------------- refcounts

    def _map(self, block: int):
        """One more live holder of `block`."""
        if self.refcount(block) == 0:
            self._live += 1
        self._refcount[block] = self._refcount.get(block, 0) + 1

    def _unmap(self, block: int):
        """One live holder of `block` gone; frees at zero references."""
        if self.refcount(block) == 1:
            self._live -= 1
        n = self._refcount[block] - 1
        if n == 0:
            del self._refcount[block]
            self._free.append(block)
        else:
            self._refcount[block] = n

    def _unpin_free(self, block: int):
        """Drop the cache pin's reference (the node is already out of the
        cache); frees at zero."""
        n = self._refcount[block] - 1
        if n == 0:
            del self._refcount[block]
            self._free.append(block)
        else:
            self._refcount[block] = n

    def _evict_blocks(self, need: int) -> int:
        """Evict LRU cache leaves until `need` blocks are freed (or the
        cache runs out of freeable nodes). A victim whose block a live
        slot still maps frees nothing — it only leaves the cache (and
        unblocks a freeable ancestor)."""
        if self.cache is None or need <= 0:
            return 0
        freed = 0
        while freed < need:
            before = len(self._free)
            blk = self.cache.evict_lru(
                lambda b: self._refcount.get(b, 0) == 1)
            if blk is None:
                break
            self._unpin_free(blk)
            self.stats.radix_evictions += 1
            if len(self._free) > before:
                freed += 1
                self.stats.radix_evicted_blocks += 1
        return freed

    # ------------------------------------------------------------ intake

    def match_prefix(self, prompt) -> int:
        """Covered token count of the longest cached extent of `prompt`
        (a pure peek: no stats, no LRU touch)."""
        if self.cache is None:
            return 0
        return self.cache.match(prompt, peek=True)[0]

    def admit(self, slot: int, prompt: list[int]) -> int:
        """Build `slot`'s page table: map every block of the longest
        cached extent (refcount++), leave the rest for prefill writes to
        allocate. Called LAZILY — at the slot's first prefill chunk, not
        at admission — so a burst of same-prefix requests still shares:
        by the time the second request prefills, the first has computed
        and registered its blocks. Returns the prefill cursor: prompt
        tokens whose K/V need no recomputation, capped at len(prompt) - 1
        because the final token's logits row samples the first generated
        token (its re-write into a fully-shared block is the first
        COW)."""
        if slot in self._tables:
            raise ValueError(f"slot {slot} already holds a table")
        L = len(prompt)
        self.stats.prefix_queries += 1
        if self.cache is not None:
            covered, blocks = self.cache.match(prompt)
        else:
            covered, blocks = 0, []
        # a matched block with no live holder was served across time —
        # its residents exited and only the cache pin kept it
        cross = any(self._refcount.get(b, 0) == 1 for b in blocks)
        table: list[int] = []
        for blk in blocks:
            # full blocks, plus a partially-matched tail (mapped
            # read-only; the first write into it COWs under the pin)
            self._map(blk)
            table.append(blk)
        self._tables[slot] = table
        skip = min(covered, L - 1)
        self.stats.prompt_tokens += L
        self.stats.shared_tokens += skip
        if skip:
            self.stats.prefix_hits += 1
            if cross:
                self.stats.cross_time_hits += 1
        self._note_peak()
        return skip

    # ------------------------------------------------------------ writes

    def _note_peak(self):
        if self._live > self.stats.blocks_in_use_peak:
            self.stats.blocks_in_use_peak = self._live

    def _alloc(self, slot: int) -> int:
        if not self._free:
            # the admission reservations make this unreachable; evict
            # rather than die if an embedder drives the manager directly
            self._evict_blocks(1)
        if not self._free:
            raise RuntimeError(
                "paged KV pool exhausted — the admission reservations "
                "(reserve/blocks_needed) must prevent this")
        blk = self._free.pop()
        self._refcount[blk] = 1
        self._live += 1
        if slot in self._reserved:
            self._reserved[slot] = max(0, self._reserved[slot] - 1)
        self._note_peak()
        return blk

    def ensure_writable(self, slot: int, positions) -> list[CopyPlan]:
        """Guarantee every logical block covering `positions` is owned
        solely (one live reference, no pin) by `slot`, allocating fresh
        blocks past the table end and COW-copying referenced ones.
        Returns the copies the engine must apply to the device pool
        BEFORE the step that writes. A CACHED block always COWs (the pin
        keeps its raw count above one), so published prompt content is
        immutable — decode extension can never poison the cache."""
        table = self._tables.get(slot)
        if table is None:
            raise ValueError(f"slot {slot} has no table")
        bs = self.block_size
        copies: list[CopyPlan] = []
        for lb in sorted({int(p) // bs for p in positions}):
            if lb >= self.table_width:
                raise ValueError(
                    f"position past the logical capacity "
                    f"({self.table_width * bs} rows)")
            while len(table) <= lb:
                table.append(self._alloc(slot))
            blk = table[lb]
            if self._refcount.get(blk, 0) > 1:
                fresh = self._alloc(slot)
                self._unmap(blk)
                table[lb] = fresh
                copies.append(CopyPlan(src=blk, dst=fresh))
                self.stats.cow_copies += 1
                self._maybe_drop_cached(blk)
        return copies

    def register_prompt(self, slot: int, prompt: list[int]):
        """Publish `slot`'s prompt blocks into the radix cache (called
        once when its prefill completes), keyed on the PROMPT extent only
        — decode-written rows are never published (any later write into a
        published block COWs away from it). Exact-run incumbents keep
        their entry; newly inserted nodes pin their blocks."""
        if self.cache is None:
            return
        table = self._tables.get(slot, [])
        for blk in self.cache.insert(prompt, table):
            self._refcount[blk] = self._refcount.get(blk, 0) + 1

    # ------------------------------------------------------------ release

    def release(self, slot: int):
        """Drop the slot's table; refcounts decrement and blocks reaching
        zero references return to the free list. With `cross_time` the
        cache keeps its pinned blocks (that is the point — the prefix
        outlives the resident); without it, a block left holding only its
        pin is dropped from the cache and freed immediately (the old
        live-residents-only semantics)."""
        self._reserved.pop(slot, None)
        table = self._tables.pop(slot, None)
        if table is None:
            return
        for blk in table:
            self._unmap(blk)
            self._maybe_drop_cached(blk)

    def _maybe_drop_cached(self, block: int):
        """Without `cross_time`, a block left holding only its cache pin
        is dropped and freed on the spot — the old live-residents-only
        sharing semantics (a prefix dies with its last holder)."""
        if (not self.cross_time and self.cache is not None
                and self._refcount.get(block, 0) == 1
                and block in self.cache.pinned):
            self.cache.drop_block(block)
            self.stats.radix_evictions += 1
            self.stats.radix_evicted_blocks += 1
            self._unpin_free(block)

    def check_invariants(self):
        """Debug/test hook: every block is free xor referenced, the
        scratch block is neither, table entries have a live reference,
        the live-block counter reproduces from the raw counts, and the
        radix tree agrees with the pin accounting."""
        free = set(self._free)
        assert SCRATCH_BLOCK not in free
        assert SCRATCH_BLOCK not in self._refcount
        assert not (free & set(self._refcount)), "block both free and live"
        for slot, table in self._tables.items():
            for blk in table:
                assert self.refcount(blk) >= 1, \
                    f"slot {slot} maps block {blk} with no live reference"
        counted = sum(1 for _ in self._refcount)
        assert counted + len(free) == self.num_blocks - 1, \
            "pool accounting leak"
        live = sum(1 for b in self._refcount if self.refcount(b) > 0)
        assert live == self._live, \
            f"live counter drifted: cached {self._live}, actual {live}"
        assert self.reserved_total <= self.free_blocks, \
            "reservations exceed the free pool"
        if self.cache is not None:
            self.cache.check_invariants()
            for blk in self.cache.pinned:
                assert self._refcount.get(blk, 0) >= 1, \
                    f"cache pins unreferenced block {blk}"
            if not self.cross_time:
                assert self.cached_only_blocks == 0, \
                    "cross_time off but cache retains resident-free blocks"
