"""Radix tree over the paged block pool: cross-request prefix cache.

SGLang-style upgrade of the flat chained-digest registry paged.py shipped
with: cached prompt prefixes are held in a token-labelled radix tree whose
nodes each own ONE physical block, so

- **lookup matches the longest cached extent** — not just exact
  block-aligned prefixes: a prompt that diverges mid-block still maps the
  node's block read-only for the tokens that do match (the slot's length
  masks the unread tail rows, and the cache pin below forces the first
  divergent write to COW), so partial-block overlap is shared too;
- **prefixes survive their residents** — every cached node holds one
  refcount (the CACHE PIN) on its block, so a block stays allocated after
  its last live slot releases; a burst of same-system-prompt requests
  after a quiet period hits warm KV instead of re-prefilling;
- **LRU eviction under the pool budget** — when admission cannot reserve
  against the free list, cold leaves are evicted oldest-first until the
  reservation fits; an evicted node only FREES its block when the pin was
  the last reference (a block a live slot still maps merely leaves the
  cache and is reclaimed by that slot's own release).

Node shape: a node's `run` is the run of tokens (<= block_size) its block
encodes, and its ROWS depend on the entire root->node token path (KV of a
row attends over every earlier token), so tree position is part of the
content address — two identical runs under different parents are
different cache entries. Children only ever hang off full-run nodes
(a partial tail is terminal until a longer prompt re-registers the
extent); siblings are a scanned list, which handles same-first-token
divergence without node splits at serving fan-outs.

Pure host code (no jax), like paged.py: unit-testable without a mesh.
"""

from __future__ import annotations

__all__ = ["RadixNode", "RadixPrefixCache"]


class RadixNode:
    """One cached block: `run` tokens at this tree depth, stored in
    physical `block`. `last_used` is the cache's logical LRU clock."""

    __slots__ = ("run", "block", "children", "parent", "last_used")

    def __init__(self, run: tuple, block: int, parent: "RadixNode | None"):
        self.run = run
        self.block = block
        self.children: list[RadixNode] = []
        self.parent = parent
        self.last_used = 0

    def __repr__(self):  # debug only
        return (f"RadixNode(run={list(self.run)!r}, block={self.block}, "
                f"children={len(self.children)})")


def _common_len(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class RadixPrefixCache:
    """The tree. Owns NO refcounts — the BlockManager increments a
    block's refcount when a node is inserted (the pin) and decrements it
    when the node is evicted; this class only tracks which blocks are
    pinned (`pinned`: block -> node) and picks eviction victims."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        self.root = RadixNode((), -1, None)
        self._pinned: dict[int, RadixNode] = {}
        self._clock = 0

    # ------------------------------------------------------------ queries

    @property
    def pinned(self) -> dict:
        """block -> node for every cached block (read-only by convention)."""
        return self._pinned

    @property
    def node_count(self) -> int:
        return len(self._pinned)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, prompt, peek: bool = False):
        """(covered, blocks): the longest cached extent of `prompt` and
        the physical blocks encoding it, in logical order. The last block
        may be only partially covered (divergence inside its run — mapped
        read-only, first write COWs under the pin). `peek` skips the LRU
        touch for pure queries."""
        bs = self.block_size
        node = self.root
        covered = 0
        blocks: list[int] = []
        now = 0 if peek else self._tick()
        while covered < len(prompt):
            tail = prompt[covered:covered + bs]
            best = None
            best_len = 0
            for child in node.children:
                k = _common_len(child.run, tail)
                if k > best_len:
                    best, best_len = child, k
            if best is None:
                break
            if not peek:
                best.last_used = now
            blocks.append(best.block)
            covered += best_len
            if best_len < len(best.run) or len(best.run) < bs:
                # diverged inside the run, or a terminal partial tail:
                # nothing deeper can match
                break
            node = best
        return covered, blocks

    # ------------------------------------------------------------ insert

    def insert(self, prompt, table) -> list[int]:
        """Publish a completed prompt's blocks: one node per logical block
        of `prompt` (full runs, then the partial tail), taking the block
        from the slot's page `table`. Exact-run incumbents win (the
        earlier request already cached identical content — its block and
        the slot's COWed twin encode the same rows); divergent runs become
        siblings. Returns the blocks NEWLY pinned — the caller must
        increment each one's refcount (the cache pin)."""
        bs = self.block_size
        node = self.root
        now = self._tick()
        pinned: list[int] = []
        L = len(prompt)
        for lb in range(min(len(table), -(-L // bs))):
            run = tuple(prompt[lb * bs:min((lb + 1) * bs, L)])
            if not run:
                break
            incumbent = None
            for child in node.children:
                if child.run == run:
                    incumbent = child
                    break
            if incumbent is None:
                blk = table[lb]
                if blk in self._pinned:
                    # the slot's block is already cached (as another
                    # node) — never double-pin a block
                    break
                incumbent = RadixNode(run, blk, node)
                node.children.append(incumbent)
                self._pinned[blk] = incumbent
                pinned.append(blk)
            incumbent.last_used = now
            if len(run) < bs:
                break  # partial tail is terminal
            node = incumbent
        return pinned

    # ------------------------------------------------------------ evict

    def _leaves(self):
        return [n for n in self._pinned.values() if not n.children]

    def _detach(self, node: RadixNode) -> int:
        if node.children:
            raise ValueError("evicting an interior node would strand its "
                             "subtree — evict leaves")
        parent = node.parent
        if parent is not None and node in parent.children:
            parent.children.remove(node)
        node.parent = None
        self._pinned.pop(node.block, None)
        return node.block

    def evict_lru(self, freeable) -> int | None:
        """Evict one leaf, LRU-first, and return its block (pin dropped —
        the caller decrements the refcount). Prefers leaves whose block
        `freeable(block)` says would actually free (refcount == pin);
        falls back to the globally-LRU leaf only when a freeable block
        exists deeper in the tree blocked behind non-freeable leaves
        (evicting the leaf frees nothing now but unblocks the ancestor).
        Returns None when nothing can be evicted."""
        leaves = self._leaves()
        if not leaves:
            return None
        free_leaves = [n for n in leaves if freeable(n.block)]
        if free_leaves:
            victim = min(free_leaves, key=lambda n: n.last_used)
            return self._detach(victim)
        if any(freeable(b) for b in self._pinned):
            victim = min(leaves, key=lambda n: n.last_used)
            return self._detach(victim)
        return None

    def drop_block(self, block: int) -> bool:
        """Evict the node pinning `block` without cascading (children, if
        any, stay pinned but become unmatchable and are dropped by their
        own holders' releases — the no-cross-time compatibility path).
        Returns True when a pin was dropped."""
        node = self._pinned.pop(block, None)
        if node is None:
            return False
        parent = node.parent
        if parent is not None and node in parent.children:
            parent.children.remove(node)
        node.parent = None
        return True

    # ------------------------------------------------------------ debug

    def check_invariants(self):
        """Every pinned block maps to a reachable-or-detached node whose
        block field agrees; reachable tree nodes are exactly pinned."""
        seen = {}
        stack = list(self.root.children)
        while stack:
            n = stack.pop()
            assert n.block not in seen, f"block {n.block} cached twice"
            seen[n.block] = n
            assert len(n.run) >= 1
            if n.children:
                assert len(n.run) == self.block_size, \
                    "children under a partial-run node"
            stack.extend(n.children)
        for blk, node in seen.items():
            assert self._pinned.get(blk) is node, \
                f"reachable node for block {blk} is not pinned"
        for blk, node in self._pinned.items():
            if blk not in seen:
                # detached by drop_block (or a descendant of one) but
                # still pinned: must NOT be reachable from the root
                p, hops = node, 0
                while p is not None and hops <= len(self._pinned) + 1:
                    assert p is not self.root, \
                        f"block {blk} pinned, parent-linked to root, " \
                        f"but not reachable"
                    p, hops = p.parent, hops + 1
