"""Continuous-batching scheduler: iteration-level request admission.

Orca (OSDI '22, PAPERS.md) is the grounding: the unit of scheduling is ONE
decode iteration, not one request. The engine keeps a fixed set of `slots`
(the decode graph's batch dim); every iteration the scheduler admits
pending requests into free slots (prefill) and evicts completed ones, so
a long generation never holds short requests hostage behind a static
batch — the throughput lever serving systems live on.

This module is pure host-side policy (no jax): Request/Slot bookkeeping,
admission order (FCFS), and completion rules (EOS token, per-request
max_new_tokens, KV-cache capacity). The device work lives in engine.py.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

_request_ids = itertools.count(1)


@dataclass
class Request:
    """One generation request and, after completion, its result."""

    prompt: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0  # 0 → greedy
    eos_id: Optional[int] = None
    request_id: int = field(default_factory=lambda: next(_request_ids))
    # lifecycle (filled by the engine)
    generated: list[int] = field(default_factory=list)
    finished: bool = False
    finish_reason: str = ""  # eos | max_tokens | length
    submit_t: float = field(default_factory=time.perf_counter)
    admit_t: Optional[float] = None  # slot assignment (queue wait ends)
    first_token_t: Optional[float] = None  # TTFT anchor
    last_token_t: Optional[float] = None  # previous token (TBT anchor)
    finish_t: Optional[float] = None
    # longest cached prefix extent the radix cache matched at admission
    # (block-granular; 0 on a cold miss, None before admission)
    matched_prefix_len: Optional[int] = None

    @property
    def trace_id(self) -> str:
        """Request-grain trace id threaded through every span/event of
        this request's lifecycle (queued→admitted→prefill→tokens→done)."""
        return f"req-{self.request_id}"

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t

    @property
    def tokens(self) -> list[int]:
        """prompt + generated — the full sequence as the model saw it."""
        return list(self.prompt) + list(self.generated)


class Slot:
    """One row of the fixed decode batch."""

    def __init__(self, index: int):
        self.index = index
        self.request: Optional[Request] = None
        self.length = 0  # cache rows filled (prompt + generated fed back)
        self.last_token = 0  # next decode iteration's input token
        # chunked prefill cursor: prompt tokens already written to the
        # cache (admission sets it — nonzero when a shared prefix was
        # mapped instead of recomputed); None once decoding
        self.prefill_pos: Optional[int] = None
        self.admit_seq = 0  # admission order (prefill scheduling is FCFS)

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefilling(self) -> bool:
        return self.request is not None and self.prefill_pos is not None

    @property
    def decoding(self) -> bool:
        return self.request is not None and self.prefill_pos is None

    def assign(self, request: Request):
        self.request = request
        self.length = 0
        self.last_token = 0
        self.prefill_pos = 0

    def release(self) -> Request:
        req = self.request
        self.request = None
        self.length = 0
        self.prefill_pos = None
        return req


class ContinuousBatchingScheduler:
    """Fixed-slot FCFS admission + per-iteration completion policy."""

    def __init__(self, num_slots: int, max_seq_len: int):
        if num_slots < 1:
            raise ValueError(f"need at least 1 slot, got {num_slots}")
        self.slots = [Slot(i) for i in range(num_slots)]
        self.max_seq_len = int(max_seq_len)
        self.pending: list[Request] = []
        self.completed: list[Request] = []
        self._admit_counter = 0  # admission order (prefill FCFS key)

    # ------------------------------------------------------------ intake

    def submit(self, request: Request) -> Request:
        if not request.prompt:
            raise ValueError("empty prompt")
        if len(request.prompt) > self.max_seq_len:
            raise ValueError(
                f"prompt of {len(request.prompt)} tokens exceeds the KV "
                f"cache ({self.max_seq_len} rows); raise max_seq_len")
        self.pending.append(request)
        return request

    # ------------------------------------------------------------ state

    @property
    def active_slots(self) -> list[Slot]:
        return [s for s in self.slots if not s.free]

    @property
    def free_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.free]

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    @property
    def drained(self) -> bool:
        return not self.pending and not self.active_slots

    def admissions(self, can_admit=None) -> list[tuple[Slot, Request]]:
        """Admit pending requests into free slots (FCFS), one batch of
        admissions per iteration — the Orca admission point. `can_admit`
        (optional callable Request -> bool) is the engine's resource gate
        (paged: enough free pool blocks for the request's worst case); a
        False answer BLOCKS the queue head rather than admitting a later
        request past it, so admission order — and therefore slot
        assignment and token streams — never depends on pool pressure."""
        out = []
        for slot in self.free_slots:
            if not self.pending:
                break
            if can_admit is not None and not can_admit(self.pending[0]):
                break
            req = self.pending.pop(0)
            slot.assign(req)
            req.admit_t = time.perf_counter()
            self._admit_counter += 1
            slot.admit_seq = self._admit_counter
            out.append((slot, req))
        return out

    def admit_prefilled(self, request: Request,
                        first_token: int) -> Optional[Slot]:
        """Admit a request whose prompt KV was computed ELSEWHERE (the
        disaggregated prefill pool) straight into decode: the slot starts
        with every prompt row accounted for (`length = len(prompt)`) and
        the prefill-sampled first token as the next decode input —
        `prefill_pos` stays None so the engine never re-prefills. Returns
        None when no slot is free (the coordinator retries next step)."""
        free = self.free_slots
        if not free:
            return None
        slot = free[0]
        slot.request = request
        slot.length = len(request.prompt)
        slot.last_token = int(first_token)
        slot.prefill_pos = None
        if request.admit_t is None:
            request.admit_t = time.perf_counter()
        self._admit_counter += 1
        slot.admit_seq = self._admit_counter
        return slot

    # ------------------------------------------------------------ completion

    def note_token(self, slot: Slot, token: int) -> bool:
        """Record one sampled token for `slot`'s request; apply the
        completion rules and release the slot when any fires. Returns
        whether the request finished. The engine owns `slot.length` (cache
        rows already written); this only decides continue-vs-finish.
        Rules, in order:
          - eos: the request's eos_id was sampled (the eos token is kept
            in `generated` so the caller sees why decoding stopped)
          - max_tokens: the request hit its max_new_tokens budget
          - length: the KV cache is full — feeding this token back would
            write past the last real cache row
        """
        req = slot.request
        req.generated.append(int(token))
        now = time.perf_counter()
        if req.first_token_t is None:
            req.first_token_t = now
        req.last_token_t = now
        reason = ""
        if req.eos_id is not None and int(token) == int(req.eos_id):
            reason = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            reason = "max_tokens"
        elif slot.length >= self.max_seq_len:
            reason = "length"
        if reason:
            req.finished = True
            req.finish_reason = reason
            req.finish_t = now
            self.completed.append(slot.release())
            return True
        slot.last_token = int(token)
        return False

    def note_tokens(self, slot: Slot, tokens: list[int]) -> tuple[int, bool]:
        """Record a RUN of sampled tokens for `slot`'s request — the
        speculative-decoding acceptance path, where one verify call
        emits up to K+1 tokens at once. Applies the same per-token
        completion rules as `note_token`, in the same order, stopping at
        the first one that fires: plain decode would never have sampled
        past it, so dropping the tail is exactly what keeps speculative
        streams bit-identical. The engine advances `slot.length` before
        each token lands, mirroring its one-token loop. Returns
        (tokens_applied, finished)."""
        applied = 0
        for tok in tokens:
            slot.length += 1
            applied += 1
            if self.note_token(slot, int(tok)):
                return applied, True
        return applied, False
