"""Speculative decoding: drafter/target co-placement + batched verify.

Decode is the serving latency floor — every generated token is one full
target-model device call, so TBT cannot drop below one forward pass no
matter how well the batch is packed. Speculative decoding (Leviathan et
al., ICML '23 — PAPERS.md "Speculative decoding") breaks that floor: a
small DRAFTER proposes K tokens and the target verifies all of them in
ONE multi-token call; SpecInfer (Miao et al., ASPLOS '24, the FlexFlow
lineage this repo reproduces) shows the drafter/target pair is itself a
placement problem, which this module treats exactly that way.

`model.serve(speculate=True, draft_model=...)` builds a
SpeculativeServingEngine: the TARGET is a normal ServingEngine, the
drafter a second decode compile of a small `TRANSFORMER_LM_ZOO`-tier LM
sharing the tokenizer/vocab — its OWN Unity plan (role "draft" joins the
warm-start plan fingerprint, so drafter and target executables cache
independently and both warm-start to 0-eval hits), placed either
COLOCATED on the full mesh or on a DISJOINT sub-mesh via the
`mesh_device_offset` machinery (`--serve-draft-chips D` gives the
drafter the last D chips, the target the rest — disagg.sub_mesh_axes
carves the windows).

**The round.** For an all-greedy decode-only batch, each slot at cursor
L feeds the drafter its uncovered true-token suffix (one uniform
catch-up mechanism covering prompt prefill, tokens generated in plain
rounds, and rejection bookkeeping), then proposes k_s tokens with q=1
greedy calls. The target then runs ONE donated verify call
(`Executor.build_verify_step`, bucketed by draft length) feeding
q = 1 + max(k_s) tokens [last_token, d_1..d_k] at positions [L..L+k]
against the SAME KV cache — the incremental-attention ops already take
(slots, q) positions (the chunked-prefill multi-token path) — and
returns every row's greedy argmax. Acceptance is the greedy
longest-matching-prefix + 1 correction token: row j is exactly the
token plain decode would sample after [.., d_1..d_j], so the emitted
run out[0..m] is **bit-identical** to the unified engine's stream by
construction (the repo's signature invariant; tests/test_speculative.py
pins both acceptance extremes).

**Rollback is a host-side cursor rewind.** Rejected tokens' KV rows are
never erased on device: reads mask by position, and every row at or
below a later call's query frontier is overwritten by that same call
before it becomes readable — stale rows beyond the frontier are masked
out. Paged safety: `ensure_writable` COWs any shared/pinned block
before a verify write, the per-slot caps keep every written row inside
the slot's admission reservation, and `register_prompt` publishes only
the prompt extent — so a verify never touches a refcount>1 block and
rejected rows die with the slot.

**Priced, not hardcoded.** A per-(target, drafter) acceptance-rate EMA
— calibrated online, persisted in the warm-start calibration DB under a
reserved key like the r20 migration-fidelity ratios — feeds the payoff
inequality

    draft_cost + verify_cost  <  E[accepted] x decode_cost
    K·draft_step_s + verify_step_s(K)  <  (Σ_{i=1..K} a^i) · decode_step_s

evaluated per round over K = 1..k_max (measured per-bucket verify EMAs,
with a cost_model prior for unmeasured buckets): the net-maximizing K
wins, and the engine falls back to plain decode when speculation stops
paying. Every decision lands in `strategy_report.json`'s `speculation`
section and `run_doctor --check` re-verifies the inequality from the
artifact alone.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .. import telemetry
from ..telemetry import log as fflog
from .disagg import sub_mesh_axes
from .engine import ServingEngine

# reserved calibration-DB key family (never produced by _params_key: no
# real op carries this params repr). Value is stored in the [fwd, bwd]
# slots as [acceptance_rate, sample_count], keyed per (target, drafter)
# decode-graph pair — the same reserved-key idiom as the migration
# fidelity ratio (elastic/payoff.py).
_ACCEPT_PARAMS = "__spec_acceptance__"
_ACCEPT_SHAPES = ((1,),)

DEFAULT_ACCEPTANCE = 0.5
_ACCEPT_ALPHA = 0.1  # acceptance observations are plentiful (per slot/round)
_COST_ALPHA = 0.25   # step-cost EMAs: smooth but responsive
_PERSIST_EVERY = 64  # rounds between calibration-DB writes (+ note_drain)
_MAX_DECISIONS = 256  # bounded decision log in the strategy report


def _acceptance_key(pair: str):
    from ..fftype import OperatorType as OT

    return (OT.OP_NOOP, f"{_ACCEPT_PARAMS}:{pair}", _ACCEPT_SHAPES)


def pair_fingerprint(target_dec, draft_dec) -> str:
    """Content address of the (target, drafter) pair the acceptance EMA
    is calibrated FOR: hash of both decode graphs' signatures. A new
    drafter tier (or a retier of the target) misses conservatively and
    recalibrates from the default, like every warm-start address."""
    from ..warmstart.fingerprint import _sha, graph_signature

    return _sha([graph_signature(target_dec.graph),
                 graph_signature(draft_dec.graph)])[:16]


def load_acceptance(model, pair: str) -> tuple[float, int]:
    """The (acceptance_rate, samples) for this pair: the in-process EMA
    when one exists, else the persisted calibration-DB entry for this
    device kind, else (DEFAULT_ACCEPTANCE, 0)."""
    mem = getattr(model, "_spec_acceptance", {}).get(pair)
    if mem is not None:
        return float(mem[0]), int(mem[1])
    from ..elastic.payoff import _calibration_db

    db = _calibration_db(model)
    if db is not None:
        from ..warmstart.calibration_db import device_key, serialize_key

        entry = (db._read().get("devices", {}).get(device_key(), {})
                 .get(serialize_key(_acceptance_key(pair))))
        if entry is not None:
            try:
                rate, samples = float(entry[0]), int(entry[1])
                if 0.0 <= rate <= 1.0:
                    model._spec_acceptance = getattr(
                        model, "_spec_acceptance", {})
                    model._spec_acceptance[pair] = (rate, samples)
                    return rate, samples
            except (TypeError, ValueError, IndexError):
                pass
    return DEFAULT_ACCEPTANCE, 0


def persist_acceptance(model, pair: str, rate: float, samples: int):
    """Write the pair's acceptance EMA through to the warm-start
    calibration DB (coordinator-only, fail-soft — a calibration write
    must never fail a serving round)."""
    model._spec_acceptance = getattr(model, "_spec_acceptance", {})
    model._spec_acceptance[pair] = (float(rate), int(samples))
    try:
        from ..elastic.payoff import _calibration_db

        db = _calibration_db(model)
        if db is not None:
            from ..distributed import is_coordinator

            if is_coordinator():
                import types

                shim = types.SimpleNamespace(_calibration={
                    _acceptance_key(pair): (float(rate), float(samples))})
                db.save_from(shim)
    except Exception as e:  # pragma: no cover - persistence is best-effort
        fflog.warning("speculative: could not persist acceptance: %s", e)


def expected_accepted(acceptance: float, k: int) -> float:
    """E[accepted tokens | K drafted] under the i.i.d. per-token
    acceptance model: Σ_{i=1..K} a^i. run_doctor --check recomputes this
    with the SAME accumulation order, so recorded decisions reproduce to
    the float."""
    expected = 0.0
    x = 1.0
    for _ in range(int(k)):
        x *= float(acceptance)
        expected += x
    return expected


class DrafterPlane:
    """The drafter side of speculative decoding: a second ServingEngine
    over the draft model (contiguous KV — every slot's drafter cache is
    private, so the plane needs no pool bookkeeping), driven directly at
    the device-call level. The scheduler state of record stays the
    TARGET's; this plane only mirrors it through a per-slot cursor
    `dlen` = drafter cache rows that hold true-sequence KV. One uniform
    catch-up mechanism (feed tokens[dlen : L+1] in chunked calls) covers
    prompt prefill, tokens generated in non-speculative rounds, slot
    reuse, AND rejection bookkeeping — a rejected proposal just leaves
    `dlen` lower, and the stale rows beyond it are overwritten before
    any later query can attend them (same cursor-rewind argument as the
    target's verify rollback)."""

    def __init__(self, target: ServingEngine, draft_model,
                 config_overrides: dict):
        self.target = target
        slots = target.spec.slots
        from .decode_graph import infer_max_seq_len

        draft_seq = infer_max_seq_len(draft_model)
        if draft_seq < target.max_seq_len:
            raise ValueError(
                f"draft_model's positional table covers {draft_seq} "
                f"rows but the target serves max_seq_len="
                f"{target.max_seq_len}; the drafter must reach every "
                f"position the target can decode at")
        self.engine = ServingEngine(
            draft_model, slots=slots, max_seq_len=target.max_seq_len,
            prefill_chunk=target.spec.prefill_chunk,
            kv_layout="contiguous", role="draft",
            config_overrides=dict(config_overrides or {}))
        self.slots = slots
        # per-slot drafter cursor: cache rows holding true-sequence KV
        self.dlen = np.zeros((slots,), np.int64)
        # per-slot request id the cursor belongs to (slot reuse under
        # continuous batching resets the cursor, not the cache — stale
        # rows are overwritten before they are readable)
        self.owner = np.full((slots,), -1, np.int64)
        self._rng = None
        self.step_calls = 0
        self.device_s = 0.0
        self.last_step_s = 0.0

    def _step(self, tokens: np.ndarray, positions: np.ndarray,
              read_idx: np.ndarray) -> np.ndarray:
        """One drafter decode call: temperature pinned to zero (greedy
        proposals — acceptance compares argmax to argmax), read row per
        slot from `read_idx`."""
        import jax
        import jax.numpy as jnp

        eng = self.engine
        dec = eng.decode_model
        xs = eng._stage_inputs(tokens, positions)
        if self._rng is None:
            self._rng = jax.random.key(dec.config.seed)
        self._rng, sub = jax.random.split(self._rng)
        temp = np.zeros((self.slots,), np.float32)
        t0 = time.perf_counter()
        dec._state, next_tok = eng._step_fn(
            dec._params, dec._state, xs,
            jnp.asarray(read_idx, jnp.int32), sub, jnp.asarray(temp))
        out = np.asarray(jax.device_get(next_tok))
        # this pair IS the drafter-cost measurement the payoff gate
        # consumes; a span here would fire once per proposal token
        dt = time.perf_counter() - t0  # fflint: ok raw_timer_in_hot_path
        self.step_calls += 1
        self.device_s += dt
        self.last_step_s = dt
        return out

    def propose(self, decoding, ks: dict[int, int]) -> tuple[dict, float]:
        """Draft ks[i] tokens for every decoding slot i in `ks`: chunked
        catch-up of the uncovered true-token suffix (the final fed token
        — the target's last_token at position L — yields proposal d_1),
        then k-1 batched single-token greedy calls. Returns
        ({slot_index: [d_1..d_k]}, draft_device_seconds)."""
        eng = self.engine
        scratch = eng.max_seq_len  # contiguous scratch row
        t_start = self.device_s
        pending: dict[int, list[int]] = {}
        offs: dict[int, int] = {}
        for s in decoding:
            if s.index not in ks:
                continue
            req = s.request
            if self.owner[s.index] != req.request_id:
                self.owner[s.index] = req.request_id
                self.dlen[s.index] = 0
            start = int(self.dlen[s.index])
            pending[s.index] = [int(t) for t in req.tokens[start:s.length + 1]]
            offs[s.index] = start
        proposals: dict[int, list[int]] = {i: [] for i in pending}
        # ---- catch-up: every slot advances together, one bucketed call
        # per chunk; a slot whose feed drains mid-loop idles on scratch
        # rows until the stragglers finish
        while any(pending.values()):
            widths = {i: min(len(p), eng.spec.prefill_chunk)
                      for i, p in pending.items() if p}
            q = eng._bucket(max(widths.values()))
            tokens = np.zeros((self.slots, q), np.int32)
            positions = np.full((self.slots, q), scratch, np.int32)
            read_idx = np.zeros((self.slots,), np.int32)
            took: dict[int, int] = {}
            for i, p in pending.items():
                n = min(len(p), q)
                if n == 0:
                    continue
                tokens[i, :n] = p[:n]
                positions[i, :n] = np.arange(offs[i], offs[i] + n,
                                             dtype=np.int32)
                read_idx[i] = n - 1
                took[i] = n
            out = self._step(tokens, positions, read_idx)
            for i, n in took.items():
                offs[i] += n
                del pending[i][:n]
                self.dlen[i] = offs[i]
                if not pending[i]:
                    # the call's read row was this slot's last TRUE token
                    # (position L) — its greedy sample is proposal d_1
                    proposals[i].append(int(out[i]))
        # ---- proposals d_2..d_k: q=1 greedy calls, batched across the
        # slots still drafting (k_s varies per slot)
        kmax = max(ks.values())
        for j in range(1, kmax):
            tokens = np.zeros((self.slots, 1), np.int32)
            positions = np.full((self.slots, 1), scratch, np.int32)
            read_idx = np.zeros((self.slots,), np.int32)
            live = []
            for s in decoding:
                i = s.index
                if i not in ks or ks[i] <= j:
                    continue
                tokens[i, 0] = proposals[i][j - 1]
                positions[i, 0] = s.length + j
                live.append(i)
            if not live:
                break
            out = self._step(tokens, positions, read_idx)
            for i in live:
                proposals[i].append(int(out[i]))
        return proposals, self.device_s - t_start

    def commit(self, slot, accepted: int, drafted: int, finished: bool):
        """Post-verify cursor bookkeeping for one slot: rows holding
        proposals d_1..d_{drafted-1} were written during this round's
        proposal calls, and the first `accepted` of them are now TRUE
        tokens — the cursor advances to L + 1 + min(accepted, drafted-1)
        (the catch-up path re-feeds whatever the proposals missed:
        correction and bonus tokens, like any other plain-round token).
        A finished request releases the slot: drop ownership so the next
        resident starts from a zero cursor."""
        i = slot.index
        if finished:
            self.owner[i] = -1
            self.dlen[i] = 0
            return
        # slot.length already advanced past the emitted run; the round's
        # pre-verify cursor L is length - emitted = dlen - 1 by the
        # catch-up invariant (dlen was L + 1 after propose)
        base = int(self.dlen[i]) - 1
        self.dlen[i] = base + 1 + min(int(accepted), max(0, drafted - 1))


class SpeculativeServingEngine(ServingEngine):
    """ServingEngine whose all-greedy decode-only rounds may run as
    speculative rounds: drafter proposals + one batched verify call,
    gated per round by the acceptance-calibrated payoff inequality (see
    module docstring). Any round with admissions, an in-flight prefill
    chunk, or a temperature>0 slot falls back to the base step verbatim
    — the per-request token streams are order-identical either way, so
    bit-identity holds across arbitrary interleavings."""

    def __init__(self, model, draft_model=None, draft_chips=None,
                 spec_k=None, **overrides):
        if draft_model is None:
            raise ValueError(
                "serve(speculate=True) needs draft_model=<a compiled "
                "FFModel sharing the target's tokenizer/vocab>")
        cfg = model.config
        if draft_chips is None:
            draft_chips = int(getattr(cfg, "serve_draft_chips", 0) or 0)
        self.draft_chips = int(draft_chips)
        k_max = int(spec_k if spec_k is not None
                    else getattr(cfg, "serve_spec_k", 4) or 4)
        if k_max < 1:
            raise ValueError(f"--serve-spec-k must be >= 1, got {k_max}")
        self.k_max = k_max
        user_over = dict(overrides.pop("config_overrides", None) or {})
        draft_over: dict = {}
        if self.draft_chips:
            import jax

            total = len(jax.devices())
            if not 0 < self.draft_chips < total:
                raise ValueError(
                    f"--serve-draft-chips must leave both the drafter "
                    f"and the target at least one chip: got "
                    f"{self.draft_chips} with {total} visible device(s)")
            # disjoint windows: target on the leading chips, drafter on
            # the trailing ones — the r23 mesh_device_offset machinery
            user_over.setdefault(
                "mesh_axis_sizes",
                sub_mesh_axes(model, total - self.draft_chips))
            user_over.setdefault("mesh_device_offset", 0)
            draft_over = {
                "mesh_axis_sizes": sub_mesh_axes(draft_model,
                                                 self.draft_chips),
                "mesh_device_offset": total - self.draft_chips,
            }
        # colocated (draft_chips=0): no target overrides at all, so the
        # target's plan shares the PLAIN serving engine's warm-start
        # address — speculate=True costs no extra target search
        super().__init__(model, config_overrides=user_over, **overrides)
        with self._active():
            t0 = time.perf_counter()
            self.drafter = DrafterPlane(self, draft_model, draft_over)
            self._verify_fn = self.decode_model.executor.build_verify_step()
            telemetry.event(
                "serve.speculate_compile",
                duration_s=time.perf_counter() - t0,
                draft_chips=self.draft_chips, k_max=self.k_max,
                draft_plan_source=(
                    self.drafter.engine.decode_model._plan_source),
                draft_mesh_axes={
                    k: int(v) for k, v in
                    self.drafter.engine.decode_model.mesh.shape.items()})
        self._check_vocab(draft_model)
        # acceptance EMA, keyed per (target, drafter) decode-graph pair
        # and persisted in the warm-start calibration DB
        self.pair_key = pair_fingerprint(
            self.decode_model, self.drafter.engine.decode_model)
        self.acceptance_ema, self.acceptance_samples = load_acceptance(
            model, self.pair_key)
        # online step-cost EMAs feeding the payoff inequality; verify is
        # bucketed by call width q (distinct widths are distinct
        # executables with distinct costs)
        self._decode_cost_s: Optional[float] = None
        self._draft_cost_s: Optional[float] = None
        self._verify_cost_s: dict[int, float] = {}
        self._rounds_since_persist = 0
        self.decisions: list[dict] = []
        self._decision_counts = {"speculate": 0, "decode": 0}
        self._spec_rounds = 0
        self._spec_draft_tokens = 0
        self._spec_accepted_tokens = 0
        self._spec_emitted_tokens = 0
        # metrics pre-created here — speculative rounds allocate no
        # metric objects (the engine's overhead-guard invariant)
        reg = self.metrics
        self._h_spec_accept_rate = reg.histogram("serve_spec_accept_rate")
        self._c_spec_rounds = reg.counter("serve_spec_rounds_total")
        self._c_spec_draft_tok = reg.counter("serve_spec_draft_tokens_total")
        self._c_spec_accepted_tok = reg.counter(
            "serve_spec_accepted_tokens_total")

    def _check_vocab(self, draft_model):
        """The drafter must share the target's vocabulary — acceptance
        compares token ids. The decode graphs' logits extents are the
        ground truth for both."""
        def vocab(dec):
            node = dec.graph.topo_order()[-1]
            return int(list(node.outputs[0].shape.logical_shape)[-1])

        try:
            tv, dv = vocab(self.decode_model), \
                vocab(self.drafter.engine.decode_model)
        except Exception:
            return  # exotic head shapes: let the verify compare tokens
        if tv != dv:
            raise ValueError(
                f"draft_model vocab {dv} != target vocab {tv}; "
                f"speculative decoding needs a shared tokenizer")

    # ------------------------------------------------------------ replan

    def replan_mesh(self, mesh_axis_sizes, trigger: str = "manual") -> dict:
        out = super().replan_mesh(mesh_axis_sizes, trigger=trigger)
        # the base replan rebinds the decode/copy executables; the
        # verify step compiles against the new executor too
        self._verify_fn = self.decode_model.executor.build_verify_step()
        return out

    # ------------------------------------------------------------ payoff

    def _slot_draft_caps(self, decoding) -> dict[int, int]:
        """Per-slot draft-length cap: never draft past the KV cache's
        last real row or the request's remaining token budget (the +1
        correction token is part of the budget), so every verify write
        stays inside the slot's admission reservation."""
        caps: dict[int, int] = {}
        for s in decoding:
            req = s.request
            room_cache = self.max_seq_len - 1 - s.length
            room_budget = req.max_new_tokens - len(req.generated) - 1
            k = min(self.k_max, room_cache, room_budget)
            if k > 0:
                caps[s.index] = int(k)
        return caps

    def _verify_cost(self, k: int) -> tuple[float, str]:
        """verify_step_s for a K-token draft (call width q = K+1): the
        measured per-bucket EMA when the bucket has run, else the
        cost_model prior scaled off the measured decode cost."""
        q = 1 + int(k)
        got = self._verify_cost_s.get(q)
        if got is not None:
            return got, "measured"
        from ..search.cost_model import price_verify_scale

        return float(self._decode_cost_s) * price_verify_scale(q), "assumed"

    def _decide(self, k_cap: int) -> dict:
        """One round's payoff decision. `no_headroom` (every decoding
        slot at its cache edge or one token from its budget) forces
        plain decode. Bootstrap phases: the first round always runs
        plain decode to measure decode_step_s (`calibrate_decode`), the
        next speculates unconditionally at the cap to measure
        draft/verify costs (`bootstrap`); from then on the inequality
        gates (`payoff`), evaluated at every K = 1..cap with the
        net-maximizing candidate recorded. The record carries every
        factor, so run_doctor --check reproduces lhs/rhs/chosen from
        the artifact alone."""
        a = float(self.acceptance_ema)
        d = {
            "k": 0, "acceptance_ema": a,
            "acceptance_samples": int(self.acceptance_samples),
        }
        if k_cap < 1:
            d.update(reason="no_headroom", chosen="decode",
                     would_speculate=False)
        elif self._decode_cost_s is None:
            d.update(reason="calibrate_decode", chosen="decode",
                     would_speculate=False)
        elif self._draft_cost_s is None:
            d.update(k=min(self.k_max, k_cap), reason="bootstrap",
                     chosen="speculate", would_speculate=True,
                     decode_cost_s=float(self._decode_cost_s))
        else:
            best = None
            for k in range(1, min(self.k_max, k_cap) + 1):
                vcost, vsrc = self._verify_cost(k)
                lhs = k * float(self._draft_cost_s) + vcost
                exp = expected_accepted(a, k)
                rhs = exp * float(self._decode_cost_s)
                cand = {
                    "k": k, "expected_accepted": exp,
                    "draft_cost_s": float(self._draft_cost_s),
                    "verify_cost_s": vcost, "verify_cost_source": vsrc,
                    "decode_cost_s": float(self._decode_cost_s),
                    "lhs_s": lhs, "rhs_s": rhs,
                    "would_speculate": bool(lhs < rhs),
                }
                if best is None or (rhs - lhs) > (best["rhs_s"]
                                                  - best["lhs_s"]):
                    best = cand
            d.update(best)
            d.update(reason="payoff",
                     chosen=("speculate" if d["would_speculate"]
                             else "decode"))
        self._decision_counts[d["chosen"]] += 1
        self.decisions.append(d)
        if len(self.decisions) > _MAX_DECISIONS:
            del self.decisions[:len(self.decisions) - _MAX_DECISIONS]
        return d

    def _update_decode_cost(self, dt: float):
        if dt <= 0:
            return
        if self._decode_cost_s is None:
            self._decode_cost_s = float(dt)
        else:
            self._decode_cost_s = ((1 - _COST_ALPHA) * self._decode_cost_s
                                   + _COST_ALPHA * float(dt))

    def _update_draft_cost(self, per_call_s: float):
        if per_call_s <= 0:
            return
        if self._draft_cost_s is None:
            self._draft_cost_s = float(per_call_s)
        else:
            self._draft_cost_s = ((1 - _COST_ALPHA) * self._draft_cost_s
                                  + _COST_ALPHA * float(per_call_s))

    def _update_verify_cost(self, q: int, dt: float):
        if dt <= 0:
            return
        cur = self._verify_cost_s.get(q)
        self._verify_cost_s[q] = (float(dt) if cur is None else
                                  (1 - _COST_ALPHA) * cur
                                  + _COST_ALPHA * float(dt))

    def _record_acceptance(self, rate: float):
        rate = min(1.0, max(0.0, float(rate)))
        if self.acceptance_samples == 0:
            self.acceptance_ema = rate
        else:
            self.acceptance_ema = ((1 - _ACCEPT_ALPHA) * self.acceptance_ema
                                   + _ACCEPT_ALPHA * rate)
        self.acceptance_samples += 1
        self._h_spec_accept_rate.observe(rate)

    def _maybe_persist(self, force: bool = False):
        self._rounds_since_persist += 1
        if force or self._rounds_since_persist >= _PERSIST_EVERY:
            self._rounds_since_persist = 0
            if self.acceptance_samples > 0:
                persist_acceptance(self.model, self.pair_key,
                                   self.acceptance_ema,
                                   self.acceptance_samples)

    # ------------------------------------------------------------ iterate

    def step(self) -> list:
        """One scheduler iteration: speculative when the batch is an
        all-greedy decode-only round AND the payoff gate approves; the
        base chunked-prefill/admission/sampling step otherwise."""
        sched = self.scheduler
        decoding = [s for s in sched.slots if s.decoding]
        plain = ((sched.pending and sched.free_slots)
                 or any(s.prefilling for s in sched.slots)
                 or not decoding
                 or any(s.request.temperature > 0 for s in decoding))
        if plain:
            return super().step()
        caps = self._slot_draft_caps(decoding)
        decision = self._decide(max(caps.values()) if caps else 0)
        if decision["chosen"] == "decode":
            out = super().step()
            # the round we just ran was decode-only at q=1 — exactly the
            # decode_step_s the payoff inequality prices
            self._update_decode_cost(self._last_step_device_s)
            return out
        return self._speculative_round(decoding, caps, decision)

    def _run_verify(self, tokens: np.ndarray,
                    positions: np.ndarray) -> np.ndarray:
        """One batched verify call: stage the q = K+1 feeds exactly like
        a decode step, run the donated verify executable, return every
        row's greedy argmax (slots, q)."""
        import jax

        dec = self.decode_model
        xs = self._stage_inputs(tokens, positions)
        t0 = time.perf_counter()
        dec._state, toks = self._verify_fn(dec._params, dec._state, xs)
        out = np.asarray(jax.device_get(toks))
        # this pair IS the verify-cost measurement (and the
        # serve_step_device_s observation below); a span would
        # double-record every speculative round
        dt = time.perf_counter() - t0  # fflint: ok raw_timer_in_hot_path
        self._device_s += dt
        self._last_step_device_s = dt
        self._h_step_device.observe(dt)
        self._update_verify_cost(tokens.shape[1], dt)
        if dec.config.sanitize_numerics:
            self._check_numerics()
        return out

    def _speculative_round(self, decoding, caps: dict[int, int],
                           decision: dict) -> list:
        sched = self.scheduler
        done_before = len(sched.completed)
        self._maybe_autoscale()
        with self._active():
            self._publish_slot_gauges([], decoding)
            k_round = int(decision["k"])
            ks = {i: min(c, k_round) for i, c in caps.items()}
            drafts, draft_s = self.drafter.propose(decoding, ks)
            total_drafted = sum(len(d) for d in drafts.values())
            if total_drafted:
                # per-proposal drafter cost: the payoff lhs prices
                # draft_cost_s per drafted token (catch-up + proposal
                # calls are all q=1 in steady state)
                self._update_draft_cost(draft_s / total_drafted)
            kmax = max((len(d) for d in drafts.values()), default=0)
            q = 1 + kmax
            tokens = np.zeros((self.spec.slots, q), np.int32)
            positions = np.full((self.spec.slots, q), self.max_seq_len,
                                np.int32)
            writes: dict[int, range] = {}
            pre_len: dict[int, int] = {}
            for s in decoding:
                d = drafts.get(s.index, ())
                n = 1 + len(d)
                tokens[s.index, 0] = s.last_token
                if d:
                    tokens[s.index, 1:n] = d
                positions[s.index, :n] = np.arange(
                    s.length, s.length + n, dtype=np.int32)
                writes[s.index] = range(s.length, s.length + n)
                pre_len[s.index] = s.length
            # COW/allocate every written row BEFORE the call — a verify
            # write can therefore never land on a refcount>1 or pinned
            # block (the paged rollback-safety half of the invariant)
            self._prepare_writes(writes)
            with telemetry.span("serve.verify", active=len(decoding),
                                draft_len=kmax):
                out = self._run_verify(tokens, positions)
            self._decode_iterations += 1
            self._spec_rounds += 1
            self._c_spec_rounds.inc()
            round_accepted = 0
            round_emitted = 0
            for s in decoding:
                req = s.request
                d = drafts.get(s.index, ())
                row = out[s.index]
                m = 0
                while m < len(d) and int(d[m]) == int(row[m]):
                    m += 1
                # greedy longest-matching-prefix + 1: rows 0..m-1 confirm
                # the accepted proposals, row m is the correction (or the
                # bonus token when every proposal matched) — exactly the
                # tokens plain decode would sample, in order
                emit = [int(row[j]) for j in range(m + 1)]
                prev_t = req.last_token_t
                applied, finished = sched.note_tokens(s, emit)
                if finished:
                    self._note_completion(s, req)
                self._observe_spec_tokens(req, prev_t, applied)
                self._decode_tokens += applied
                round_emitted += applied
                if d:
                    round_accepted += m
                    self._spec_draft_tokens += len(d)
                    self._spec_accepted_tokens += m
                    self._c_spec_draft_tok.inc(len(d))
                    self._c_spec_accepted_tok.inc(m)
                    self._record_acceptance(m / len(d))
                    self.drafter.commit(s, m, len(d), finished)
            self._spec_emitted_tokens += round_emitted
            telemetry.event(
                "serve.speculate", k=k_round, draft_len=kmax,
                slots=len(decoding), draft_tokens=total_drafted,
                accepted_tokens=round_accepted,
                emitted_tokens=round_emitted,
                acceptance_ema=self.acceptance_ema,
                draft_device_s=draft_s,
                verify_device_s=self._last_step_device_s)
            self._maybe_persist()
        return sched.completed[done_before:]

    def _observe_spec_tokens(self, req, prev_t, n: int):
        """TBT attribution for a verify-call run: the round emitted `n`
        tokens for this slot in ONE device call, so the inter-token gap
        divides evenly across them — n observations of gap/n, keeping
        the TBT histogram's token count and total time both honest."""
        if n <= 0:
            return
        self._c_tokens_out.inc(n)
        if prev_t is None:  # defensive: decoding slots always have one
            self._h_ttft.observe(req.ttft_s)
            telemetry.instant("serve.first_token", trace=req.trace_id,
                              ttft_s=req.ttft_s)
            n -= 1
            prev_t = req.first_token_t
            if n <= 0:
                return
        gap = (req.last_token_t - prev_t) / n
        for _ in range(n):
            self._h_tbt.observe(gap)

    # ------------------------------------------------------------ drain

    def note_drain(self, wall_s: float):
        super().note_drain(wall_s)
        self._maybe_persist(force=True)
        self._update_report()

    def _update_report(self):
        """Land the speculation section in strategy_report.json (the
        disagg section's idiom): run_doctor --check re-verifies every
        payoff decision's arithmetic from this artifact alone."""
        self.model._serving_speculation = self.speculation_section()
        diag = getattr(self.model, "_diagnostics", None)
        if diag is not None and getattr(diag, "report", None):
            from ..diagnostics.explain import rewrite_strategy_report

            diag.report["speculation"] = self.model._serving_speculation
            rewrite_strategy_report(diag.report, diag.directory)

    # ------------------------------------------------------------ stats

    def speculation_section(self) -> dict:
        dec = self.drafter.engine.decode_model
        return {
            "draft_chips": self.draft_chips,
            "colocated": self.draft_chips == 0,
            "k_max": self.k_max,
            "pair_key": self.pair_key,
            "acceptance_ema": float(self.acceptance_ema),
            "acceptance_samples": int(self.acceptance_samples),
            "costs": {
                "decode_step_s": self._decode_cost_s,
                "draft_step_s": self._draft_cost_s,
                "verify_step_s": {str(q): v for q, v
                                  in sorted(self._verify_cost_s.items())},
            },
            "rounds": self._spec_rounds,
            "draft_tokens": self._spec_draft_tokens,
            "accepted_tokens": self._spec_accepted_tokens,
            "emitted_tokens": self._spec_emitted_tokens,
            "decision_counts": dict(self._decision_counts),
            "decisions": list(self.decisions),
            "drafter": {
                "plan_source": dec._plan_source,
                "mesh_axes": {k: int(v)
                              for k, v in dec.mesh.shape.items()},
                "device_s": self.drafter.device_s,
                "step_calls": self.drafter.step_calls,
            },
        }

    def stats(self) -> dict:
        out = super().stats()
        drafted = self._spec_draft_tokens
        out["speculation"] = {
            "rounds": self._spec_rounds,
            "draft_tokens": drafted,
            "accepted_tokens": self._spec_accepted_tokens,
            "emitted_tokens": self._spec_emitted_tokens,
            "acceptance_rate": (self._spec_accepted_tokens / drafted
                                if drafted else 0.0),
            "acceptance_ema": float(self.acceptance_ema),
            "draft_chips": self.draft_chips,
            "k_max": self.k_max,
            "decision_counts": dict(self._decision_counts),
        }
        return out

    def reset_stats(self) -> None:
        super().reset_stats()
        # window tallies restart; the CALIBRATION state (acceptance EMA,
        # step-cost EMAs, decision log) persists — a measured window
        # should run on a warmed-up gate, not a cold one
        self._spec_rounds = 0
        self._spec_draft_tokens = 0
        self._spec_accepted_tokens = 0
        self._spec_emitted_tokens = 0
        self.drafter.step_calls = 0
        self.drafter.device_s = 0.0
