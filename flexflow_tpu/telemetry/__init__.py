"""Run-wide observability: tracer spans, structured metrics, leveled logs.

Three coordinated pieces (docs/observability.md):

1. **Tracer** (tracer.py) — host-side span/counter/instant events dumped as
   Chrome trace-event JSON (Perfetto / chrome://tracing), plus an opt-in
   `jax.profiler.trace` passthrough (`--xprof-dir`) for device timelines.
2. **MetricsRecorder** (recorder.py) — JSONL event log with a run manifest
   and derived rates; `summary` record carries p50/p95 step time.
3. **Instrumentation hooks** — model compile/fit, search/, resilience/,
   dataloader call the module-level `span`/`instant`/`counter`/`event`
   helpers below. They dispatch to the ACTIVE session when one exists and
   cost one global read + one `is None` test when telemetry is off, so the
   hooks can live permanently in hot paths.

Enable with `--telemetry-dir DIR` (FFConfig), `model.enable_telemetry(DIR)`,
or the keras `Telemetry` callback; read back via `model.get_telemetry()`.
"""

from __future__ import annotations

from typing import Optional

from . import log  # noqa: F401  (flexflow_tpu.telemetry.log)
from .metrics import MetricsRegistry  # noqa: F401  (re-export)
from .recorder import MetricsRecorder, read_jsonl
from .session import TelemetrySession
from .tracer import Tracer

# ffscope flight recorder (scope/flightrec.py): stdlib-only, always-on
# bounded ring fed from the dispatchers below.  Its own hot path is the
# same one-global-read discipline — when disabled, _flight.record is a
# global load + `is None` test.
from ..scope import flightrec as _flight

__all__ = [
    "Tracer", "MetricsRecorder", "MetricsRegistry", "TelemetrySession",
    "read_jsonl", "log",
    "activate", "deactivate", "active_session",
    "span", "instant", "counter", "event",
    "inc", "observe", "set_gauge",
]

_active: Optional[TelemetrySession] = None
# same-session nesting depth: the disaggregated serving coordinator
# holds one activation across an overlapped step while both engines'
# inner _active() blocks enter and exit on their own threads — an
# unbalanced deactivate must not tear the session down mid-step
_depth: int = 0


class _NoopSpan:
    """Shared do-nothing context manager — the entire cost of a disabled
    `with telemetry.span(...)` block is returning this singleton."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


def activate(session: TelemetrySession) -> TelemetrySession:
    """Install `session` as the process-wide telemetry sink. Activating
    the session that is already active nests: the sink stays installed
    until the matching number of deactivate(session) calls."""
    global _active, _depth
    if _active is session:
        _depth += 1
    else:
        _active = session
        _depth = 1
    return session


def deactivate(session: Optional[TelemetrySession] = None):
    """Remove the active session (or only `session`, if it is active).
    Same-session activations nest — only the outermost deactivate
    removes the sink; deactivate(None) always tears down."""
    global _active, _depth
    if session is None:
        _active = None
        _depth = 0
    elif _active is session:
        _depth -= 1
        if _depth <= 0:
            _active = None
            _depth = 0


def active_session() -> Optional[TelemetrySession]:
    return _active


# ---------------------------------------------------------------- dispatch
# Hot-path helpers: cheap no-ops when no session is active.

def span(name: str, **args):
    _flight.record("span", name)
    s = _active
    if s is None:
        return _NOOP
    return s.tracer.span(name, **args)


def instant(name: str, **args):
    _flight.record("instant", name)
    s = _active
    if s is not None:
        s.tracer.instant(name, **args)


def counter(name: str, values: dict):
    _flight.record("counter", name)
    s = _active
    if s is not None:
        s.tracer.counter(name, values)


def event(kind: str, **fields):
    """Structured JSONL record into the active session's metrics log."""
    _flight.record("event", kind, fields.get("step"))
    s = _active
    if s is not None:
        s.recorder.record(kind, **fields)


# ffpulse registry dispatch (metrics.py): same one-global-read no-op
# contract as span/instant — with telemetry off, no registry (and no
# metric object) is ever touched or created.

def inc(name: str, value: float = 1.0, **labels):
    """Counter increment on the active session's registry."""
    s = _active
    if s is not None:
        s.metrics.counter(name, **labels).inc(value)


def observe(name: str, value: float, **labels):
    """Histogram observation on the active session's registry."""
    s = _active
    if s is not None:
        s.metrics.histogram(name, **labels).observe(value)


def set_gauge(name: str, value: float, **labels):
    """Gauge set on the active session's registry."""
    s = _active
    if s is not None:
        s.metrics.gauge(name, **labels).set(value)
