"""ffpulse continuous export: rolling snapshots, a Prometheus file, /metrics.

While a run is alive the exporter periodically (``--metrics-interval``):

1. merges every attached registry into one snapshot (`merge_snapshots` —
   the same code path a cross-host merge uses, so a single-process run
   still exercises the merge invariants),
2. appends a ``metrics_snapshot`` record to `metrics.jsonl` (rolling — one
   record per interval, each self-contained), and
3. atomically rewrites ``<dir>/metrics.prom`` in text exposition format.

``--metrics-port`` additionally serves the LATEST rendered exposition at
``/metrics`` and liveness at ``/healthz`` from a stdlib ThreadingHTTPServer
daemon thread — no third-party dependency, read-only, coordinator-only
(non-coordinator processes never construct an exporter; see
`TelemetrySession.start_exporter`).

Everything here runs on a daemon thread and must therefore never call into
collectives: snapshots are process-local; cross-host merges happen at
explicit barrier points (`distributed.gather_json`) where every process
participates.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from .metrics import to_prometheus

__all__ = ["MetricsExporter"]


class MetricsExporter:
    """Interval snapshot writer + optional /metrics endpoint.

    `collect` returns a merged snapshot dict; `record` appends one
    metrics_snapshot JSONL record (both supplied by the session so the
    exporter stays free of session internals)."""

    def __init__(self, directory: str, collect: Callable[[], dict],
                 record: Callable[..., None],
                 interval_s: float = 0.0, port: int = 0):
        self.directory = directory
        self._collect = collect
        self._record = record
        self.interval_s = float(interval_s)
        self.port = int(port)
        self.prom_path = os.path.join(directory, "metrics.prom")
        self._latest_prom = ""
        self._latest_t: Optional[float] = None
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None

    # ------------------------------------------------------------ snapshot

    def snapshot_now(self, reason: str = "interval", **flags) -> dict:
        """One export cycle: collect -> JSONL record -> prom file. Safe to
        call from any thread; also the drain/final hook."""
        snap = self._collect()
        with self._lock:
            self._seq += 1
            seq = self._seq
        self._record("metrics_snapshot", reason=reason, seq=seq,
                     metrics=snap, **flags)
        text = to_prometheus(snap)
        tmp = self.prom_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, self.prom_path)
        except OSError:
            pass
        with self._lock:
            self._latest_prom = text
            self._latest_t = time.monotonic()
        return snap

    # ------------------------------------------------------------ lifecycle

    def start(self):
        if self.interval_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ffpulse-export", daemon=True)
            self._thread.start()
        if self.port and self._server is None:
            self._start_server()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot_now("interval")
            except Exception:  # never kill the run from the export thread
                pass

    def stop(self, final_reason: Optional[str] = "final", **flags):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
            self._server = None
        if final_reason:
            try:
                self.snapshot_now(final_reason, **flags)
            except Exception:
                pass

    # ------------------------------------------------------------ HTTP

    def _start_server(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # keep stderr clean
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    with exporter._lock:
                        text = exporter._latest_prom
                    if not text:
                        # first scrape before the first interval tick:
                        # render on demand so /metrics is never empty
                        try:
                            text = to_prometheus(exporter._collect())
                        except Exception:
                            text = ""
                    self._send(200, text.encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    with exporter._lock:
                        age = (None if exporter._latest_t is None
                               else time.monotonic() - exporter._latest_t)
                    body = json.dumps({
                        "status": "ok",
                        "snapshots": exporter._seq,
                        "last_snapshot_age_s": age,
                    }).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")

        try:
            self._server = ThreadingHTTPServer(("127.0.0.1", self.port),
                                               Handler)
        except OSError:
            self._server = None
            return
        self.port = self._server.server_address[1]  # resolve port 0
        t = threading.Thread(target=self._server.serve_forever,
                             name="ffpulse-http", daemon=True)
        t.start()
