"""Leveled, multihost-aware logging for the framework.

Replaces bare `print(...)` progress reporting (the reference prints from
every rank; a 64-host pod interleaves 64 copies of every epoch line).

- Levels: debug < info < warning < error. The threshold comes from
  `FF_LOG_LEVEL` (name or number; default "info") and can be changed at
  runtime with `set_level`.
- Multihost: by default only process 0 emits (`FF_LOG_ALL_HOSTS=1` opts
  every host in; warnings and errors always emit everywhere — a rank-3
  failure must not be invisible).
- Output goes to stdout for info/debug (the reference's epoch lines are
  stdout, and AE scripts grep them there) and stderr for warning/error.

Usage: `from flexflow_tpu.telemetry import log; log.info("epoch %d", e)`.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

DEBUG, INFO, WARNING, ERROR = 10, 20, 30, 40
_NAMES = {"debug": DEBUG, "info": INFO, "warning": WARNING, "error": ERROR}
_LABELS = {DEBUG: "DEBUG", INFO: "INFO", WARNING: "WARNING", ERROR: "ERROR"}

_level: Optional[int] = None  # resolved lazily so env set after import works


def _resolve_level() -> int:
    global _level
    if _level is None:
        raw = os.environ.get("FF_LOG_LEVEL", "info").strip().lower()
        _level = _NAMES.get(raw)
        if _level is None:
            try:
                _level = int(raw)
            except ValueError:
                _level = INFO
    return _level


def set_level(level) -> None:
    """Set the threshold: a name ("debug") or a numeric level."""
    global _level
    if isinstance(level, str):
        _level = _NAMES.get(level.strip().lower(), INFO)
    else:
        _level = int(level)


def _is_host0() -> bool:
    # lazy: importing jax at module import time would pin the backend
    # before tests/conftest.py can force the CPU platform
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _emit(level: int, msg: str, *args) -> None:
    if level < _resolve_level():
        return
    if (level < WARNING and not _is_host0()
            and os.environ.get("FF_LOG_ALL_HOSTS", "") != "1"):
        return
    if args:
        try:
            msg = msg % args
        except (TypeError, ValueError):
            msg = " ".join([msg] + [str(a) for a in args])
    stream = sys.stderr if level >= WARNING else sys.stdout
    if level == INFO:
        print(msg, file=stream)  # epoch lines stay grep-compatible
    else:
        print(f"[{_LABELS.get(level, level)}] {msg}", file=stream)
    stream.flush()


def debug(msg: str, *args) -> None:
    _emit(DEBUG, msg, *args)


def info(msg: str, *args) -> None:
    _emit(INFO, msg, *args)


def warning(msg: str, *args) -> None:
    _emit(WARNING, msg, *args)


def error(msg: str, *args) -> None:
    _emit(ERROR, msg, *args)
