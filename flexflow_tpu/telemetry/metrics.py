"""ffpulse metrics plane: typed Counter/Gauge/Histogram registry.

Design rules (docs/observability.md "metrics plane"):

1. **Fixed bucket boundaries.** Every histogram in the fleet uses the same
   log-spaced boundary table (``LOG4_BOUNDS``, 4 buckets per decade from
   1e-6 s to 1e4 s), identified by a ``bounds_id``. Because boundaries are
   shared *by construction*, merging two snapshots — across hosts, across
   time, across engines — is bucket-wise summation and nothing else.
2. **Snapshots are plain JSON.** ``MetricsRegistry.snapshot()`` returns a
   dict that round-trips through json.dumps; ``merge_snapshots`` operates
   on those dicts, so a coordinator can merge snapshots gathered over the
   wire (`distributed.gather_json`) or read back from `metrics.jsonl`
   without reconstructing metric objects.
3. **Percentiles are bucket estimates.** ``percentile_from_hist`` walks the
   cumulative counts and linearly interpolates inside the target bucket;
   the error is bounded by one bucket width (~1.78x ratio), and estimates
   are clamped to the exact observed [min, max].
4. **Merge semantics.** Counters and histogram counts/sums add; histogram
   min/max take min/max; gauges ADD as well — a merged gauge is a fleet
   total (e.g. active slots across hosts), so ratios must be recorded as
   separate numerator/denominator gauges, never pre-divided.

Thread safety: one registry-wide lock guards both child creation and value
updates. The lock is uncontended host-side work (~100ns), far below the
device-step costs it measures. Hot paths that must be zero-cost when
telemetry is off go through `telemetry.inc/observe/set_gauge`, which do a
single global read before touching any registry.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Iterable, Optional

__all__ = [
    "LOG4_BOUNDS", "BUCKET_SCHEMES", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "merge_snapshots", "percentile_from_hist",
    "to_prometheus", "parse_prometheus",
]

# 4 buckets per decade, 1e-6 .. 1e4 (seconds scale covers ns-rounded host
# timings up to multi-hour checkpoints); adjacent-bound ratio 10^0.25.
LOG4_BOUNDS: tuple = tuple(
    round(10.0 ** (k / 4.0), 10) for k in range(-24, 17))

# bounds_id -> boundary table; snapshots reference tables by id so a
# 41-float list is written once per snapshot, not once per histogram.
BUCKET_SCHEMES = {"log4": LOG4_BOUNDS}


def _key(name: str, labels: dict) -> str:
    """Canonical series key: `name` or `name{k="v",...}` with sorted keys
    (Prometheus notation, so snapshot keys read like exposition lines)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


_KEY_RE = re.compile(r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?$')
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_key(key: str):
    """Inverse of `_key`: -> (name, labels dict)."""
    m = _KEY_RE.match(key)
    if not m:
        raise ValueError(f"unparseable series key {key!r}")
    name, raw = m.group(1), m.group(2)
    labels = {}
    if raw:
        for lm in _LABEL_RE.finditer(raw):
            labels[lm.group(1)] = lm.group(2).replace('\\"', '"')
    return name, labels


class Counter:
    """Monotonic accumulator. Merge = sum."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, value: float = 1.0):
        with self._lock:
            self.value += value


class Gauge:
    """Last-set point value. Merge = sum (fleet total)."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock):
        self.value = 0.0
        self._lock = lock

    def set(self, value: float):
        with self._lock:
            self.value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self.value += value


class Histogram:
    """Fixed-boundary histogram; merge = element-wise count summation.

    `counts[i]` counts observations in (bounds[i-1], bounds[i]];
    `counts[-1]` is the +Inf overflow bucket. Exact sum/min/max ride
    along so means are exact and percentile estimates are clamped."""

    __slots__ = ("bounds_id", "bounds", "counts", "sum", "count",
                 "min", "max", "_lock")

    def __init__(self, lock: threading.Lock, bounds_id: str = "log4"):
        self.bounds_id = bounds_id
        self.bounds = BUCKET_SCHEMES[bounds_id]
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = lock

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self.counts[bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    # -- read-side helpers (also accept snapshot dicts via the module
    #    functions below; these are the object fast path) --

    def percentile(self, q: float) -> float:
        return percentile_from_hist(self.to_dict(), q)

    def to_dict(self) -> dict:
        return {"bounds_id": self.bounds_id, "counts": list(self.counts),
                "sum": self.sum, "count": self.count,
                "min": self.min, "max": self.max}


class MetricsRegistry:
    """Label-keyed home of every live metric object in one process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}  # key -> (kind, obj)

    # -- creation (idempotent; first call wins) --

    def _child(self, kind: str, cls, key: str, **kw):
        with self._lock:
            got = self._metrics.get(key)
            if got is not None:
                if got[0] != kind:
                    raise TypeError(
                        f"metric {key!r} already registered as {got[0]}")
                return got[1]
            obj = cls(self._lock, **kw)
            self._metrics[key] = (kind, obj)
            return obj

    def counter(self, name: str, **labels) -> Counter:
        return self._child("counter", Counter, _key(name, labels))

    def gauge(self, name: str, **labels) -> Gauge:
        return self._child("gauge", Gauge, _key(name, labels))

    def histogram(self, name: str, bounds_id: str = "log4",
                  **labels) -> Histogram:
        return self._child("histogram", Histogram, _key(name, labels),
                           bounds_id=bounds_id)

    def get(self, name: str, **labels):
        """Peek without creating (returns None when absent) — read paths
        use this so summaries never allocate series as a side effect."""
        got = self._metrics.get(_key(name, labels))
        return None if got is None else got[1]

    def __len__(self):
        return len(self._metrics)

    def reset(self, prefix: str = ""):
        """Zero every series whose name starts with `prefix` (objects are
        kept — callers hold references)."""
        with self._lock:
            for key, (kind, obj) in self._metrics.items():
                if not key.startswith(prefix):
                    continue
                if kind == "histogram":
                    obj.counts = [0] * len(obj.counts)
                    obj.sum = 0.0
                    obj.count = 0
                    obj.min = None
                    obj.max = None
                else:
                    obj.value = 0.0

    # -- snapshot --

    def snapshot(self) -> dict:
        """Plain-JSON point-in-time copy (see module docstring, rule 2)."""
        with self._lock:
            counters, gauges, hists = {}, {}, {}
            bounds_used = set()
            for key, (kind, obj) in sorted(self._metrics.items()):
                if kind == "counter":
                    counters[key] = obj.value
                elif kind == "gauge":
                    gauges[key] = obj.value
                else:
                    hists[key] = obj.to_dict()
                    bounds_used.add(obj.bounds_id)
        return {
            "schema": 1,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "bucket_bounds": {bid: list(BUCKET_SCHEMES[bid])
                              for bid in sorted(bounds_used)},
        }


def _empty_snapshot() -> dict:
    return {"schema": 1, "counters": {}, "gauges": {}, "histograms": {},
            "bucket_bounds": {}}


def merge_snapshots(snapshots: Iterable[dict]) -> dict:
    """Bucket-wise / element-wise merge. Associative and order-independent
    because every operation is commutative addition (or min/max)."""
    out = _empty_snapshot()
    for snap in snapshots:
        if not snap:
            continue
        for key, v in snap.get("counters", {}).items():
            out["counters"][key] = out["counters"].get(key, 0.0) + v
        for key, v in snap.get("gauges", {}).items():
            out["gauges"][key] = out["gauges"].get(key, 0.0) + v
        out["bucket_bounds"].update(snap.get("bucket_bounds", {}))
        for key, h in snap.get("histograms", {}).items():
            acc = out["histograms"].get(key)
            if acc is None:
                out["histograms"][key] = {
                    "bounds_id": h["bounds_id"],
                    "counts": list(h["counts"]),
                    "sum": h["sum"], "count": h["count"],
                    "min": h.get("min"), "max": h.get("max")}
                continue
            if acc["bounds_id"] != h["bounds_id"]:
                raise ValueError(
                    f"histogram {key!r}: cannot merge bounds "
                    f"{acc['bounds_id']!r} with {h['bounds_id']!r}")
            acc["counts"] = [a + b for a, b in zip(acc["counts"],
                                                   h["counts"])]
            acc["sum"] += h["sum"]
            acc["count"] += h["count"]
            for fld, pick in (("min", min), ("max", max)):
                a, b = acc.get(fld), h.get(fld)
                acc[fld] = (pick(a, b) if a is not None and b is not None
                            else (a if a is not None else b))
    # sort for deterministic artifacts regardless of merge order
    for section in ("counters", "gauges", "histograms"):
        out[section] = dict(sorted(out[section].items()))
    return out


def percentile_from_hist(h: dict, q: float,
                         bounds: Optional[tuple] = None) -> float:
    """Estimate the q-th percentile (0..100) from bucket counts.

    Linear interpolation inside the target bucket bounds the error by one
    bucket width; results clamp to the exact observed [min, max] so p100
    is exact and estimates never leave the data range."""
    count = h.get("count", 0)
    if count <= 0:
        return 0.0
    if bounds is None:
        bounds = BUCKET_SCHEMES[h["bounds_id"]]
    target = (q / 100.0) * count
    seen = 0.0
    lo = 0.0
    for i, c in enumerate(h["counts"]):
        if c <= 0:
            lo = bounds[i] if i < len(bounds) else lo
            continue
        if seen + c >= target:
            hi = bounds[i] if i < len(bounds) else (
                h.get("max") if h.get("max") is not None else lo)
            frac = (target - seen) / c
            est = lo + (hi - lo) * max(0.0, min(1.0, frac))
            break
        seen += c
        lo = bounds[i] if i < len(bounds) else lo
    else:  # pragma: no cover — count>0 guarantees a break
        est = lo
    mn, mx = h.get("min"), h.get("max")
    if mn is not None:
        est = max(est, mn)
    if mx is not None:
        est = min(est, mx)
    return est


def hist_quantiles(h: Optional[dict],
                   qs=(50, 95, 99)) -> dict:
    """{p50: ..., p95: ...} convenience for summary builders; empty/None
    histogram -> zeros so summaries stay key-stable."""
    if h is None:
        return {f"p{q:g}": 0.0 for q in qs}
    if isinstance(h, Histogram):
        h = h.to_dict()
    return {f"p{q:g}": percentile_from_hist(h, q) for q in qs}


# ---------------------------------------------------------------- Prometheus

def _prom_line_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _prom_series(key: str, extra_labels: dict = None,
                 suffix: str = "") -> str:
    name, labels = parse_key(key)
    if extra_labels:
        labels = dict(labels, **extra_labels)
    return _key(name + suffix, labels)


def to_prometheus(snapshot: dict) -> str:
    """Render a snapshot dict in Prometheus text exposition format 0.0.4.

    Histograms emit cumulative `_bucket{le=...}` series plus `_sum` and
    `_count`; `parse_prometheus` inverts this exactly (round-trip tested)."""
    lines = []
    typed = set()

    def _type(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for key, v in snapshot.get("counters", {}).items():
        _type(parse_key(key)[0], "counter")
        lines.append(f"{key} {_prom_line_value(v)}")
    for key, v in snapshot.get("gauges", {}).items():
        _type(parse_key(key)[0], "gauge")
        lines.append(f"{key} {_prom_line_value(v)}")
    bounds_map = snapshot.get("bucket_bounds", {})
    for key, h in snapshot.get("histograms", {}).items():
        name = parse_key(key)[0]
        _type(name, "histogram")
        bounds = bounds_map.get(h["bounds_id"],
                                BUCKET_SCHEMES.get(h["bounds_id"], ()))
        cum = 0
        for i, c in enumerate(h["counts"]):
            cum += c
            le = bounds[i] if i < len(bounds) else math.inf
            lines.append(
                f"{_prom_series(key, {'le': _prom_line_value(le)}, '_bucket')}"
                f" {cum}")
        lines.append(f"{_prom_series(key, suffix='_sum')} "
                     f"{_prom_line_value(h['sum'])}")
        lines.append(f"{_prom_series(key, suffix='_count')} {cum}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict:
    """Parse exposition text back into a snapshot-shaped dict.

    Only what `to_prometheus` emits is supported (the round-trip
    contract); histogram min/max/exactness are lost by design — they are
    not part of the exposition format — so round-trip equality is checked
    on counters, gauges, and histogram counts/sum/count."""
    out = _empty_snapshot()
    types: dict = {}
    hist_raw: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        key, _, val = line.rpartition(" ")
        key = key.strip()
        v = math.inf if val == "+Inf" else float(val)
        name, labels = parse_key(key)
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and types.get(
                    name[: -len(suffix)]) == "histogram":
                base = name[: -len(suffix)]
                part = suffix[1:]
                break
        if base is not None:
            le = labels.pop("le", None)
            hkey = _key(base, labels)
            slot = hist_raw.setdefault(hkey, {"buckets": [], "sum": 0.0,
                                              "count": 0})
            if part == "bucket":
                slot["buckets"].append(
                    (math.inf if le == "+Inf" else float(le), v))
            elif part == "sum":
                slot["sum"] = v
            else:
                slot["count"] = int(v)
            continue
        kind = types.get(name, "gauge")
        section = "counters" if kind == "counter" else "gauges"
        out[section][key] = v
    for hkey, raw in hist_raw.items():
        raw["buckets"].sort(key=lambda b: b[0])
        finite = [b[0] for b in raw["buckets"] if b[0] != math.inf]
        bounds_id = None
        for bid, bounds in BUCKET_SCHEMES.items():
            if list(bounds) == finite:
                bounds_id = bid
                break
        counts, prev = [], 0
        for _, cum in raw["buckets"]:
            counts.append(int(cum) - prev)
            prev = int(cum)
        out["histograms"][hkey] = {
            "bounds_id": bounds_id or "custom",
            "counts": counts, "sum": raw["sum"], "count": raw["count"],
            "min": None, "max": None}
        if bounds_id is None:
            out["bucket_bounds"]["custom"] = finite
        else:
            out["bucket_bounds"][bounds_id] = list(
                BUCKET_SCHEMES[bounds_id])
    return out
