"""Structured JSONL run-metrics log.

One JSON object per line; the first record of a run is the manifest (mesh
shape, config snapshot, git sha), then one record per
step/epoch/save/compile/search event, plus `summary` records with
percentile step times and throughput — the machine-readable counterpart of
the epoch print lines, following CheckFreq's "measure the save pipeline to
tune it" (PAPERS.md, FAST '21). Summaries are CUMULATIVE snapshots (one
per fit() call); consumers take the last one as the run's numbers.

Schema (stable fields; producers may add more):
  every record: {"kind": str, "t": unix seconds}
  manifest:   mesh_axes, config, git_sha, jax_backend, process_index
  compile:    duration_s, num_nodes, searched
  step:       step, epoch, step_time_s, data_wait_s, save_latency_s, ema_step_time_s
  epoch:      epoch, duration_s, examples_per_sec
  checkpoint: step, serialize_s, commit_s, bytes, staleness_s
  search:     evals, cache_hits, best_cost_s
  summary:    steps, p50_step_time_s, p95_step_time_s, examples_per_sec
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
from typing import Any, Optional


def git_sha(repo_dir: Optional[str] = None) -> str:
    """Best-effort short sha of the enclosing repo ('' when unavailable)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo_dir or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


class MetricsRecorder:
    """Append-only JSONL writer; one flush per record keeps the log live
    (a preempted run's partial log is still readable up to the kill)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "a")
        # late-write accounting: records arriving after close() (e.g. from
        # the async checkpoint writer outliving the session) are dropped on
        # purpose, but COUNTED — a nonzero count means the log is missing
        # events it was asked to carry, which run_doctor can surface
        self.dropped_after_close = 0

    def record(self, kind: str, **fields: Any):
        rec = {"kind": kind, "t": time.time()}
        rec.update(fields)
        line = json.dumps(rec, default=_json_default)
        with self._lock:
            if self._f.closed:  # late writer-thread event after close
                self.dropped_after_close += 1
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


def _json_default(o):
    """Tolerate numpy scalars and other simple objects in fields."""
    try:
        return float(o)
    except Exception:
        return repr(o)


def read_jsonl(path: str, strict: bool = False) -> list[dict]:
    """Parse a metrics log back into records (validation / tests / CI).

    A mid-write SIGKILL (real preemptions, fault-injection tests) leaves a
    truncated final line; that partial record is dropped rather than making
    the whole log unreadable — exactly the log a post-mortem most needs to
    read. A malformed record anywhere ELSE still raises (the file is
    corrupt, not merely torn); strict=True raises on any undecodable line,
    including the last."""
    out = []
    with open(path) as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if strict or i < len(lines) - 1:
                raise
            # torn final record from a mid-write kill: ignore
    return out
