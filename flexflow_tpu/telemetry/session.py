"""TelemetrySession: one run's Tracer + MetricsRecorder under a directory.

Artifacts under `--telemetry-dir`:

    <dir>/trace.json      Chrome trace-event JSON (Perfetto / chrome://tracing)
    <dir>/metrics.jsonl   structured run metrics (recorder.py schema)

The session owns the step-time accounting (EMA, percentile summary,
examples/sec) so the fit loop only reports raw timings. `flush()` rewrites
trace.json from the tracer buffer — called at the end of every fit (and on
preemption), so artifacts exist the moment training stops for any reason.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .metrics import MetricsRegistry, merge_snapshots, percentile_from_hist
from .recorder import MetricsRecorder, git_sha
from .tracer import Tracer


# FFConfig fields worth reproducing a run from; everything else is either
# derived or irrelevant to performance forensics.
_MANIFEST_CONFIG_FIELDS = (
    "epochs", "batch_size", "learning_rate", "num_nodes",
    "workers_per_node", "search_budget", "search_calibrate",
    "search_mesh_shapes", "only_data_parallel", "enable_substitutions",
    "profiling", "computation_dtype", "checkpoint_dir", "checkpoint_every",
    "checkpoint_every_seconds", "auto_resume", "seed",
    "diagnostics", "drift_threshold", "pipeline_steps",
    "health_sample_every", "warmstart_dir",
    "metrics_interval", "metrics_port",
    "profile_every", "watchdog_timeout", "watchdog_multiplier",
    "watchdog_abort", "flight_events",
)


def _is_coordinator() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


class TelemetrySession:
    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.tracer = Tracer()
        self.recorder = MetricsRecorder(
            os.path.join(self.directory, "metrics.jsonl"))
        self.trace_path = os.path.join(self.directory, "trace.json")
        self._manifest_written = False
        # ffpulse registry: session-owned metrics plus any attached
        # registries (e.g. a serving engine's); snapshots merge them all
        self.metrics = MetricsRegistry()
        self._registries: list = [self.metrics]
        self.exporter = None
        # step accounting — histogram-backed (bounded, mergeable); the
        # histogram is pre-created so record_step never allocates series
        self._h_step = self.metrics.histogram("train_step_time_s")
        self._g_tokens_per_sec = self.metrics.gauge("train_tokens_per_sec")
        self._g_examples_per_sec = self.metrics.gauge(
            "train_examples_per_sec")
        self._g_mfu = self.metrics.gauge("train_mfu")
        self._c_tokens = self.metrics.counter("train_tokens_total")
        # goodput anchors (set_goodput): cost-model FLOPs per optimizer
        # step and the machine-model aggregate chip peak, for MFU
        self._flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._ema: Optional[float] = None
        self._examples = 0
        self._tokens = 0
        self._train_seconds = 0.0
        self._last_summary_steps = -1
        self._dropped_warned = False
        self._closed = False
        # time-to-first-step: compile start (note_compile_start) → first
        # step completion, the cold-vs-warm restart metric (warmstart/)
        self._compile_t0: Optional[float] = None
        self._time_to_first_step: Optional[float] = None

    # ------------------------------------------------------------ manifest

    def write_manifest(self, model=None):
        """First record of the log: everything needed to interpret the
        numbers (mesh, strategy, config, git sha). Idempotent — a second
        compile on the same session records a fresh manifest only if the
        first one never happened."""
        if self._manifest_written:
            return
        self._manifest_written = True
        fields: dict = {"git_sha": git_sha()}
        try:
            import jax

            fields["jax_backend"] = jax.default_backend()
            fields["process_index"] = jax.process_index()
            fields["process_count"] = jax.process_count()
        except Exception:
            pass
        if model is not None:
            mesh = getattr(model, "mesh", None)
            cfg = getattr(model, "config", None)
            if mesh is not None:
                fields["mesh_axes"] = {
                    k: int(v) for k, v in mesh.shape.items()}
            elif cfg is not None:
                # pre-compile (the manifest leads even search events): the
                # CONFIGURED mesh; a mesh-shape search's winner lands in
                # the compile record
                ms = cfg.mesh_shape()
                fields["mesh_axes"] = {
                    a: int(s) for a, s in zip(ms.axis_names, ms.axis_sizes)}
            if cfg is not None:
                fields["config"] = {
                    k: _plain(getattr(cfg, k, None))
                    for k in _MANIFEST_CONFIG_FIELDS
                }
        self.recorder.record("manifest", **fields)

    # ------------------------------------------------------------ metrics

    def attach_registry(self, registry: MetricsRegistry):
        """Fold another registry (e.g. a serving engine's) into every
        snapshot this session exports."""
        if registry not in self._registries:
            self._registries.append(registry)

    def collect_snapshot(self) -> dict:
        """Merged point-in-time snapshot of every attached registry —
        the same merge a cross-host gather would apply."""
        return merge_snapshots([r.snapshot() for r in self._registries])

    def _get_exporter(self):
        if self.exporter is None:
            from .export import MetricsExporter

            self.exporter = MetricsExporter(
                self.directory, collect=self.collect_snapshot,
                record=self.recorder.record)
        return self.exporter

    def start_exporter(self, interval_s: float = 0.0, port: int = 0):
        """Begin continuous export (interval snapshot writer and/or the
        /metrics endpoint). Coordinator-only: non-coordinator processes
        get a no-op so one file/port exists per fleet."""
        if not _is_coordinator():
            return None
        exp = self._get_exporter()
        if interval_s > 0:
            exp.interval_s = float(interval_s)
        if port:
            exp.port = int(port)
        exp.start()
        return exp

    def write_metrics_snapshot(self, reason: str = "manual",
                               **flags) -> Optional[dict]:
        """Export one snapshot now (JSONL record + metrics.prom)."""
        if self._closed or not _is_coordinator():
            return None
        return self._get_exporter().snapshot_now(reason, **flags)

    def set_goodput(self, flops_per_step: Optional[float],
                    peak_flops: Optional[float]):
        """Anchor MFU: `flops_per_step` from the search cost model over
        the compiled graph, `peak_flops` = chip peak × chips from the
        machine model. Either None disables the MFU gauge."""
        if flops_per_step and flops_per_step > 0:
            self._flops_per_step = float(flops_per_step)
        if peak_flops and peak_flops > 0:
            self._peak_flops = float(peak_flops)

    # ------------------------------------------------------------ steps

    def note_compile_start(self, t: Optional[float] = None):
        """Anchor for time_to_first_step_s (the first compile's start
        wins — that is the cold-start instant a restart pays for)."""
        if self._compile_t0 is None:
            self._compile_t0 = time.perf_counter() if t is None else t

    def record_step(self, step: int, epoch: int, step_time: float,
                    data_wait: float, save_latency: float,
                    batch_size: int, tokens_per_example: int = 1):
        """One optimizer step's host-side timing split. `step_time` is
        wall-clock between step dispatches — with one step in flight it
        converges to true device step time under backpressure."""
        if self._time_to_first_step is None and self._compile_t0 is not None:
            # completion of the run's FIRST step relative to compile
            # start: search + calibration + executor build + first-batch
            # staging + the step itself — the restart latency warm start
            # exists to collapse
            self._time_to_first_step = time.perf_counter() - self._compile_t0
        self._h_step.observe(step_time)
        self._ema = (step_time if self._ema is None
                     else 0.9 * self._ema + 0.1 * step_time)
        step_tokens = batch_size * tokens_per_example
        self._examples += batch_size
        self._tokens += step_tokens
        self._train_seconds += step_time
        # goodput gauges: instantaneous per-step rates + MFU against the
        # cost-model/machine-model anchor (set_goodput)
        self._c_tokens.inc(step_tokens)
        mfu = None
        if step_time > 0:
            self._g_tokens_per_sec.set(step_tokens / step_time)
            self._g_examples_per_sec.set(batch_size / step_time)
            if self._flops_per_step and self._peak_flops:
                mfu = self._flops_per_step / (step_time * self._peak_flops)
                self._g_mfu.set(mfu)
        extra = {} if mfu is None else {"mfu": mfu}
        self.recorder.record(
            "step", step=int(step), epoch=int(epoch),
            step_time_s=step_time, data_wait_s=data_wait,
            save_latency_s=save_latency,
            device_time_s=max(0.0, step_time - data_wait - save_latency),
            ema_step_time_s=self._ema, **extra)

    def write_summary(self):
        """Cumulative percentile summary over every step recorded so far.
        Each fit() call writes one on exit, so consumers take the LAST
        summary record as the run's numbers; a call with no new steps
        since the previous summary writes nothing (no duplicates from
        e.g. the keras Telemetry callback's train-end).

        Percentiles come from the bounded step-time histogram (one-bucket
        estimation error, ~1.78x width) instead of an unbounded list of
        every step time — summary keys unchanged for existing readers."""
        h = self._h_step
        if h.count == 0 or h.count == self._last_summary_steps:
            return
        self._last_summary_steps = h.count
        hd = h.to_dict()
        fields = {
            "steps": int(h.count),
            "p50_step_time_s": percentile_from_hist(hd, 50),
            "p95_step_time_s": percentile_from_hist(hd, 95),
            "mean_step_time_s": h.sum / h.count,
            "examples_per_sec": (self._examples / self._train_seconds
                                 if self._train_seconds > 0 else 0.0),
        }
        if self._flops_per_step and self._peak_flops and h.sum > 0:
            # run-average MFU over measured train seconds
            fields["mfu"] = (self._flops_per_step * h.count
                             / (h.sum * self._peak_flops))
        if self._tokens > self._examples:
            fields["tokens_per_sec"] = (
                self._tokens / self._train_seconds
                if self._train_seconds > 0 else 0.0)
        if self._time_to_first_step is not None:
            fields["time_to_first_step_s"] = self._time_to_first_step
        dropped = self.tracer.dropped
        if dropped:
            # a capped trace is NOT a complete trace: say so in the summary
            # record AND out loud — buried as a counter inside trace.json
            # (tracer.to_dict) the drop looks like a complete timeline
            fields["trace_dropped_events"] = int(dropped)
            if not self._dropped_warned:
                self._dropped_warned = True
                from . import log

                log.warning(
                    "telemetry: trace buffer cap reached — %d event(s) "
                    "dropped; %s is truncated (raise Tracer max_events or "
                    "shorten the run)", dropped, self.trace_path)
        self.recorder.record("summary", **fields)

    # ------------------------------------------------------------ lifecycle

    def flush(self):
        """Persist the trace buffer; the JSONL is already on disk."""
        if not self._closed:
            self.tracer.dump(self.trace_path)

    def close(self):
        if self._closed:
            return
        # final snapshot: any run that produced metrics leaves a
        # self-contained last metrics_snapshot record + metrics.prom
        if _is_coordinator() and (
                self.exporter is not None or self._h_step.count > 0
                or len(self._registries) > 1):
            try:
                exp = self._get_exporter()
                exp.stop(final_reason="final")
            except Exception:
                pass
        self.flush()
        self.recorder.close()
        self._closed = True


def _plain(v):
    """Manifest values must be JSON-native."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)
