"""TelemetrySession: one run's Tracer + MetricsRecorder under a directory.

Artifacts under `--telemetry-dir`:

    <dir>/trace.json      Chrome trace-event JSON (Perfetto / chrome://tracing)
    <dir>/metrics.jsonl   structured run metrics (recorder.py schema)

The session owns the step-time accounting (EMA, percentile summary,
examples/sec) so the fit loop only reports raw timings. `flush()` rewrites
trace.json from the tracer buffer — called at the end of every fit (and on
preemption), so artifacts exist the moment training stops for any reason.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .recorder import MetricsRecorder, git_sha
from .tracer import Tracer


# FFConfig fields worth reproducing a run from; everything else is either
# derived or irrelevant to performance forensics.
_MANIFEST_CONFIG_FIELDS = (
    "epochs", "batch_size", "learning_rate", "num_nodes",
    "workers_per_node", "search_budget", "search_calibrate",
    "search_mesh_shapes", "only_data_parallel", "enable_substitutions",
    "profiling", "computation_dtype", "checkpoint_dir", "checkpoint_every",
    "checkpoint_every_seconds", "auto_resume", "seed",
    "diagnostics", "drift_threshold", "pipeline_steps",
    "health_sample_every", "warmstart_dir",
)


class TelemetrySession:
    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.tracer = Tracer()
        self.recorder = MetricsRecorder(
            os.path.join(self.directory, "metrics.jsonl"))
        self.trace_path = os.path.join(self.directory, "trace.json")
        self._manifest_written = False
        # step accounting
        self._step_times: list[float] = []
        self._ema: Optional[float] = None
        self._examples = 0
        self._tokens = 0
        self._train_seconds = 0.0
        self._last_summary_steps = -1
        self._dropped_warned = False
        self._closed = False
        # time-to-first-step: compile start (note_compile_start) → first
        # step completion, the cold-vs-warm restart metric (warmstart/)
        self._compile_t0: Optional[float] = None
        self._time_to_first_step: Optional[float] = None

    # ------------------------------------------------------------ manifest

    def write_manifest(self, model=None):
        """First record of the log: everything needed to interpret the
        numbers (mesh, strategy, config, git sha). Idempotent — a second
        compile on the same session records a fresh manifest only if the
        first one never happened."""
        if self._manifest_written:
            return
        self._manifest_written = True
        fields: dict = {"git_sha": git_sha()}
        try:
            import jax

            fields["jax_backend"] = jax.default_backend()
            fields["process_index"] = jax.process_index()
            fields["process_count"] = jax.process_count()
        except Exception:
            pass
        if model is not None:
            mesh = getattr(model, "mesh", None)
            cfg = getattr(model, "config", None)
            if mesh is not None:
                fields["mesh_axes"] = {
                    k: int(v) for k, v in mesh.shape.items()}
            elif cfg is not None:
                # pre-compile (the manifest leads even search events): the
                # CONFIGURED mesh; a mesh-shape search's winner lands in
                # the compile record
                ms = cfg.mesh_shape()
                fields["mesh_axes"] = {
                    a: int(s) for a, s in zip(ms.axis_names, ms.axis_sizes)}
            if cfg is not None:
                fields["config"] = {
                    k: _plain(getattr(cfg, k, None))
                    for k in _MANIFEST_CONFIG_FIELDS
                }
        self.recorder.record("manifest", **fields)

    # ------------------------------------------------------------ steps

    def note_compile_start(self, t: Optional[float] = None):
        """Anchor for time_to_first_step_s (the first compile's start
        wins — that is the cold-start instant a restart pays for)."""
        if self._compile_t0 is None:
            self._compile_t0 = time.perf_counter() if t is None else t

    def record_step(self, step: int, epoch: int, step_time: float,
                    data_wait: float, save_latency: float,
                    batch_size: int, tokens_per_example: int = 1):
        """One optimizer step's host-side timing split. `step_time` is
        wall-clock between step dispatches — with one step in flight it
        converges to true device step time under backpressure."""
        if self._time_to_first_step is None and self._compile_t0 is not None:
            # completion of the run's FIRST step relative to compile
            # start: search + calibration + executor build + first-batch
            # staging + the step itself — the restart latency warm start
            # exists to collapse
            self._time_to_first_step = time.perf_counter() - self._compile_t0
        self._step_times.append(step_time)
        self._ema = (step_time if self._ema is None
                     else 0.9 * self._ema + 0.1 * step_time)
        self._examples += batch_size
        self._tokens += batch_size * tokens_per_example
        self._train_seconds += step_time
        self.recorder.record(
            "step", step=int(step), epoch=int(epoch),
            step_time_s=step_time, data_wait_s=data_wait,
            save_latency_s=save_latency,
            device_time_s=max(0.0, step_time - data_wait - save_latency),
            ema_step_time_s=self._ema)

    def write_summary(self):
        """Cumulative percentile summary over every step recorded so far.
        Each fit() call writes one on exit, so consumers take the LAST
        summary record as the run's numbers; a call with no new steps
        since the previous summary writes nothing (no duplicates from
        e.g. the keras Telemetry callback's train-end)."""
        if not self._step_times or len(self._step_times) == self._last_summary_steps:
            return
        self._last_summary_steps = len(self._step_times)
        import numpy as np

        ts = np.asarray(self._step_times)
        fields = {
            "steps": int(len(ts)),
            "p50_step_time_s": float(np.percentile(ts, 50)),
            "p95_step_time_s": float(np.percentile(ts, 95)),
            "mean_step_time_s": float(ts.mean()),
            "examples_per_sec": (self._examples / self._train_seconds
                                 if self._train_seconds > 0 else 0.0),
        }
        if self._tokens > self._examples:
            fields["tokens_per_sec"] = (
                self._tokens / self._train_seconds
                if self._train_seconds > 0 else 0.0)
        if self._time_to_first_step is not None:
            fields["time_to_first_step_s"] = self._time_to_first_step
        dropped = self.tracer.dropped
        if dropped:
            # a capped trace is NOT a complete trace: say so in the summary
            # record AND out loud — buried as a counter inside trace.json
            # (tracer.to_dict) the drop looks like a complete timeline
            fields["trace_dropped_events"] = int(dropped)
            if not self._dropped_warned:
                self._dropped_warned = True
                from . import log

                log.warning(
                    "telemetry: trace buffer cap reached — %d event(s) "
                    "dropped; %s is truncated (raise Tracer max_events or "
                    "shorten the run)", dropped, self.trace_path)
        self.recorder.record("summary", **fields)

    # ------------------------------------------------------------ lifecycle

    def flush(self):
        """Persist the trace buffer; the JSONL is already on disk."""
        if not self._closed:
            self.tracer.dump(self.trace_path)

    def close(self):
        if self._closed:
            return
        self.flush()
        self.recorder.close()
        self._closed = True


def _plain(v):
    """Manifest values must be JSON-native."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)
