"""Chrome trace-event tracer: host-side spans, counters, instant events.

The reference's observability is per-kernel prints under `m->profiling`
(linear_kernels.cu:95-117) plus the Unity simulator's cost breakdown; what
dominated a *run* (compile, search, input stalls, checkpoint saves) was
invisible. This tracer records host-side phases as Chrome trace events —
the `chrome://tracing` / Perfetto JSON array format, the same format
`jax.profiler` and TensorFlow emit — so run-level timelines load in the
exact tool used for device-level XProf dumps.

Design constraints:
- low overhead ON: one `perf_counter` pair + one dict append per span, no
  I/O until `dump()`;
- near-zero overhead OFF: callers go through `telemetry.span(...)` which
  short-circuits to a shared no-op context manager before any Tracer code
  runs (see __init__.py);
- thread-safe: the resilience writer thread emits serialize/commit spans
  concurrently with the train loop's step spans; events carry the emitting
  thread's id and the buffer append happens under a lock;
- bounded memory: the buffer caps at `max_events` (drops are counted and
  surfaced as a final counter event rather than silently lost).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional


class _Span:
    """Context manager recording one complete ("ph": "X") event."""

    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        self.tracer._complete(self.name, self.t0, t1, self.args)
        return False


class Tracer:
    def __init__(self, pid: int = 0, max_events: int = 500_000):
        self.pid = int(pid)
        self.max_events = int(max_events)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._dropped = 0
        self._named_threads: set[int] = set()

    # ------------------------------------------------------------ emit

    def _us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def _append(self, ev: dict):
        tid = threading.get_ident()
        ev["pid"] = self.pid
        ev["tid"] = tid
        with self._lock:
            if tid not in self._named_threads:
                self._named_threads.add(tid)
                self._events.append({
                    "name": "thread_name", "ph": "M", "pid": self.pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
            if len(self._events) >= self.max_events:
                self._dropped += 1
                return
            self._events.append(ev)

    @property
    def dropped(self) -> int:
        """Events lost to the `max_events` cap — surfaced by the session's
        end-of-fit summary and a telemetry.log warning so a silently
        truncated trace never masquerades as a complete one."""
        with self._lock:
            return self._dropped

    def span(self, name: str, **args) -> _Span:
        """`with tracer.span("compile"): ...` — one X event on exit."""
        return _Span(self, name, args or None)

    def _complete(self, name: str, t0: float, t1: float,
                  args: Optional[dict]):
        ev = {
            "name": name, "ph": "X",
            "ts": self._us(t0), "dur": (t1 - t0) * 1e6,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def complete(self, name: str, t0: float, t1: float, **args):
        """Record a completed span from explicit `perf_counter`
        timestamps — for synthesized events whose window was not
        measured by a live `with span(...)` block (the pipelined engine
        reconstructs per-step spans from one chunk's wall window)."""
        self._complete(name, t0, t1, args or None)

    def instant(self, name: str, **args):
        """Zero-duration marker (preemption notice, resume, best-cost)."""
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": self._us(time.perf_counter())}
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, values: dict[str, Any]):
        """Counter sample — Perfetto renders these as stacked time series."""
        self._append({
            "name": name, "ph": "C",
            "ts": self._us(time.perf_counter()),
            "args": {k: float(v) for k, v in values.items()},
        })

    # ------------------------------------------------------------ dump

    def to_dict(self) -> dict:
        """Chrome trace-event JSON object ({"traceEvents": [...]})."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        head = [{
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": "flexflow_tpu"},
        }]
        if dropped:
            head.append({
                "name": "tracer.dropped_events", "ph": "C", "pid": self.pid,
                "tid": 0, "ts": 0.0, "args": {"dropped": float(dropped)},
            })
        return {"traceEvents": head + events, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> str:
        """Write the trace atomically (tmp + rename) so a reader never sees
        a torn file; returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)
        return path

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)
