"""Tensor IR: lazy frontend tensors and parallel tensors.

Mirrors the reference's two-level tensor world
(include/flexflow/tensor.h, include/flexflow/parallel_tensor.h:36-198):

- `Tensor`: plain shape+dtype handle produced by layer-builder calls before
  `compile()`; owns no data.
- `ParallelTensor`: post-compile tensor whose dims carry parallelization state
  (`ParallelDim {size, degree, parallel_idx, is_replica_dim}`). In the
  reference the degree/parallel_idx drive Legion partitions; here they drive a
  `PartitionSpec` over the global TPU mesh, and data movement is performed by
  XLA collectives over ICI instead of region copies.

Unlike the reference we do not materialize replica dims as extra array axes at
runtime: replication is expressed by *not* sharding a dim and partial-sum
state by GSPMD's psum insertion. The replica dim still exists in the IR (shape
level) so Unity-style rewrites stay expressible.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Optional

from jax.sharding import PartitionSpec

from .fftype import DataType, ParameterSyncType
from .machine import MachineView

MAX_TENSOR_DIM = 5

_tensor_guid = itertools.count(3000000)  # TENSOR_GUID_FIRST_VALID
_parallel_tensor_guid = itertools.count(4000000)


class Tensor:
    """Lazy frontend tensor handle (reference tensor.h TensorBase).

    `dims` are stored outer-to-inner (NumPy order), unlike the reference's
    Legion-order innermost-first; the Python API of the reference also
    presents NumPy order, so user-visible semantics match.
    """

    def __init__(
        self,
        dims: tuple[int, ...],
        dtype: DataType,
        owner_layer=None,
        owner_idx: int = 0,
        name: str = "",
        create_gradients: bool = True,
    ):
        self.tensor_guid = next(_tensor_guid)
        self.dims = tuple(int(d) for d in dims)
        self.dtype = DataType(dtype)
        self.owner_layer = owner_layer
        self.owner_idx = owner_idx
        self.name = name or f"tensor_{self.tensor_guid}"
        self.create_gradients = create_gradients

    @property
    def num_dims(self) -> int:
        return len(self.dims)

    def get_shape(self) -> tuple[int, ...]:
        return self.dims

    def __repr__(self):
        return f"Tensor({self.name}, dims={self.dims}, dtype={self.dtype.name})"


@dataclass(frozen=True)
class ParallelDim:
    """Per-dim parallelization state (parallel_tensor.h:36-71).

    size: logical extent of the dim (replica dims: size == degree)
    degree: number of shards along this dim
    parallel_idx: index into the op's machine-view dims (-1 if unsharded)
    is_replica_dim: true for dims that exist only to count replicas
    axes: mesh axes this degree rides, when the rewrite that introduced it
      declared them (the MachineView device-grid binding recast as named
      mesh axes); empty = infer from degree (legacy). Threading the axes
      removes the degree→axis ambiguity on meshes where several axes share
      a size (a degree-2 Combine on a dcn=2, model=2 mesh).
    """

    size: int
    degree: int = 1
    parallel_idx: int = -1
    is_replica_dim: bool = False
    axes: tuple = ()

    def __post_init__(self):
        if self.degree < 1:
            raise ValueError(f"degree must be >= 1, got {self.degree}")
        if not self.is_replica_dim and self.size % self.degree != 0:
            raise ValueError(
                f"dim size {self.size} not divisible by degree {self.degree}"
            )


@dataclass(frozen=True)
class ParallelTensorShape:
    """Shape + parallelization annotation; the value the PCG/search reasons
    about (parallel_tensor.h:96-135)."""

    dims: tuple[ParallelDim, ...]
    dtype: DataType

    @staticmethod
    def from_shape(shape: tuple[int, ...], dtype: DataType) -> "ParallelTensorShape":
        return ParallelTensorShape(tuple(ParallelDim(int(s)) for s in shape), dtype)

    @property
    def logical_shape(self) -> tuple[int, ...]:
        """Shape without replica dims — what the runtime array looks like
        globally."""
        return tuple(d.size for d in self.dims if not d.is_replica_dim)

    @property
    def num_replica_dims(self) -> int:
        return sum(1 for d in self.dims if d.is_replica_dim)

    @property
    def total_degree(self) -> int:
        deg = 1
        for d in self.dims:
            deg *= d.degree
        return deg

    def piece_shape(self) -> tuple[int, ...]:
        """Per-device shard shape (logical dims only)."""
        return tuple(
            d.size // d.degree for d in self.dims if not d.is_replica_dim
        )

    def num_elements(self) -> int:
        n = 1
        for s in self.logical_shape:
            n *= s
        return n

    def piece_elements(self) -> int:
        n = 1
        for s in self.piece_shape():
            n *= s
        return n

    def with_degree(self, dim: int, degree: int) -> "ParallelTensorShape":
        dims = list(self.dims)
        dims[dim] = replace(dims[dim], degree=degree)
        return ParallelTensorShape(tuple(dims), self.dtype)

    def __repr__(self):
        parts = []
        for d in self.dims:
            tag = "R" if d.is_replica_dim else ""
            if d.degree > 1 or d.is_replica_dim:
                s = f"{d.size}{tag}/{d.degree}"
                if d.axes:
                    # axes are part of the cost surface (segment-cache keys
                    # hash this repr) — two shapes differing only in which
                    # mesh axis carries a degree price differently
                    s += f"@{','.join(d.axes)}"
                parts.append(s)
            else:
                parts.append(str(d.size))
        return f"PTShape[{' x '.join(parts)}, {self.dtype.name}]"


class ParallelTensor:
    """Post-compile tensor: parallel shape + mesh-axis assignment + (at run
    time) the jax.Array it names (parallel_tensor.h:139-198).

    `axis_assignment[i]` is the tuple of mesh axis names sharding dim i
    (empty tuple = replicated along that dim). The PartitionSpec fed to
    `with_sharding_constraint` / `device_put` is derived from it, restricted
    to logical (non-replica) dims.
    """

    def __init__(
        self,
        shape: ParallelTensorShape,
        name: str = "",
        sync_type: ParameterSyncType = ParameterSyncType.NONE,
        create_gradients: bool = True,
    ):
        self.parallel_tensor_guid = next(_parallel_tensor_guid)
        self.shape = shape
        self.name = name or f"ptensor_{self.parallel_tensor_guid}"
        if sync_type == ParameterSyncType.PS:
            # the reference's parameter-server sync (gather to one GPU,
            # optimizer_kernel.cu:48-76) is deliberately not implemented:
            # on TPU gradient sync is a GSPMD-inserted psum riding ICI,
            # which strictly dominates a hub-and-spoke PS exchange. Reject
            # loudly rather than silently run NCCL-equivalent sync under a
            # PS label (SURVEY §7 decision).
            raise NotImplementedError(
                "ParameterSyncType.PS is not supported on TPU: gradient "
                "synchronization is an XLA psum over the data mesh axes "
                "(the NCCL-mode equivalent); use ParameterSyncType.NCCL "
                "or NONE")
        self.sync_type = sync_type
        self.create_gradients = create_gradients
        self.axis_assignment: tuple[tuple[str, ...], ...] = tuple(
            () for _ in shape.dims
        )
        self.machine_view: Optional[MachineView] = None
        self.owner_op = None
        self.owner_idx: int = 0

    @property
    def dtype(self) -> DataType:
        return self.shape.dtype

    def assign_axes(self, assignment: tuple[tuple[str, ...], ...]):
        if len(assignment) != len(self.shape.dims):
            raise ValueError(
                f"assignment rank {len(assignment)} != tensor rank "
                f"{len(self.shape.dims)}"
            )
        self.axis_assignment = tuple(tuple(a) for a in assignment)

    def partition_spec(self) -> PartitionSpec:
        """PartitionSpec over logical dims only (replica dims replicate by
        omission — GSPMD treats unnamed axes as replicated)."""
        entries = []
        for d, axes in zip(self.shape.dims, self.axis_assignment):
            if d.is_replica_dim:
                continue
            if not axes:
                entries.append(None)
            elif len(axes) == 1:
                entries.append(axes[0])
            else:
                entries.append(tuple(axes))
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def __repr__(self):
        return f"ParallelTensor({self.name}, {self.shape}, spec={self.partition_spec()})"
