"""PyTorch frontend (reference python/flexflow/torch/model.py, SURVEY §2.5).

`torch.fx`-symbolic-traces an `nn.Module`, propagates shapes from the
FFModel input tensors, and rebuilds the graph with FFModel builder calls
(`PyTorchModel.torch_to_ff`); `torch_to_flexflow` serializes the traced
graph to a `.ff` file that `PyTorchModel(filename)` can replay without
torch installed — the same two paths the reference offers.
"""

from .model import PyTorchModel, torch_to_flexflow
