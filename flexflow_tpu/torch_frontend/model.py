"""torch.fx → FFModel conversion.

Reference behavior (python/flexflow/torch/model.py): symbolic-trace the
module, emit one IR record per fx node (`IR_DELIMITER`-joined fields), and
rebuild FFModel layers from records (`PyTorchModel.torch_to_ff`, the ~60
Node subclasses). Here the per-node translation table is `_module_handlers`
/ `_function_handlers` / `_method_handlers`; shape propagation runs with
torch.fx.passes.shape_prop so view/reshape/flatten get concrete shapes.

Weight transfer: torch Linear stores (out, in) — transposed into our (in,
out) kernels; `install_weights(ff)` copies trained torch parameters into
the compiled FFModel for numerics-preserving migration (beyond the
reference, which only rebuilds architecture).
"""

from __future__ import annotations

import operator
from typing import Optional

import numpy as np

from ..fftype import ActiMode, DataType

IR_DELIMITER = "; "


class PyTorchModel:
    def __init__(self, source, batch_size: Optional[int] = None):
        """source: an nn.Module, or a path to a .ff file produced by
        torch_to_flexflow."""
        self.source = source
        self.batch_size = batch_size
        self._weight_transfers = []  # (layer_name, weight_name, np array)

    # ------------------------------------------------------------ public

    def torch_to_ff(self, ffmodel, input_tensors, verbose=False):
        """Build the model on `ffmodel` from `input_tensors` (FF Tensors);
        returns the list of output Tensors."""
        if isinstance(self.source, str):
            return self._replay_file(ffmodel, input_tensors)
        return self._trace_module(ffmodel, input_tensors, verbose)

    apply = torch_to_ff

    def install_weights(self, ffmodel):
        """Copy the torch module's trained parameters into the compiled
        FFModel (call after ffmodel.compile())."""
        for lname, wname, arr in self._weight_transfers:
            if lname in ffmodel._params and wname in ffmodel._params[lname]:
                ffmodel.set_weight(lname, wname, arr)

    # ------------------------------------------------------------ fx path

    def _trace_module(self, ffmodel, input_tensors, verbose):
        import torch
        import torch.fx
        from torch.fx.passes.shape_prop import ShapeProp

        module = self.source.eval()
        traced = torch.fx.symbolic_trace(module)
        example = [
            torch.zeros(
                tuple(t.dims),
                dtype=torch.int64 if "INT" in t.dtype.name else torch.float32,
            )
            for t in input_tensors
        ]
        ShapeProp(traced).propagate(*example)

        env = {}
        inputs_iter = iter(input_tensors)
        outputs = []
        for node in traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(inputs_iter)
            elif node.op == "call_module":
                sub = traced.get_submodule(node.target)
                env[node.name] = self._handle_module(
                    ffmodel, node, sub, env)
            elif node.op == "call_function":
                env[node.name] = self._handle_function(ffmodel, node, env)
            elif node.op == "call_method":
                env[node.name] = self._handle_method(ffmodel, node, env)
            elif node.op == "get_attr":
                env[node.name] = _fetch_attr(module, node.target)
            elif node.op == "output":
                args = node.args[0]
                outs = args if isinstance(args, (tuple, list)) else [args]
                outputs = [env[a.name] for a in outs]
            if verbose and node.op != "output":
                print(f"{node.op} {node.name} -> {env.get(node.name)}")
        return outputs

    # ---------------------------------------------------------- handlers

    def _handle_module(self, ff, node, sub, env):
        import torch.nn as nn

        x = lambda i=0: env[node.args[i].name]
        name = node.target.replace(".", "_")
        if isinstance(sub, nn.Linear):
            out = ff.dense(x(), sub.out_features,
                           use_bias=sub.bias is not None, name=name)
            self._weight_transfers.append(
                (name, "kernel", sub.weight.detach().numpy().T))
            if sub.bias is not None:
                self._weight_transfers.append(
                    (name, "bias", sub.bias.detach().numpy()))
            return out
        if isinstance(sub, nn.Conv2d):
            out = ff.conv2d(
                x(), sub.out_channels, *sub.kernel_size, *sub.stride,
                *(sub.padding if isinstance(sub.padding, tuple)
                  else (sub.padding,) * 2),
                groups=sub.groups, use_bias=sub.bias is not None, name=name)
            self._weight_transfers.append(
                (name, "kernel", sub.weight.detach().numpy()))
            if sub.bias is not None:
                self._weight_transfers.append(
                    (name, "bias", sub.bias.detach().numpy()))
            return out
        if isinstance(sub, nn.MaxPool2d):
            k = _pair(sub.kernel_size)
            s = _pair(sub.stride or sub.kernel_size)
            p = _pair(sub.padding)
            return ff.pool2d(x(), *k, *s, *p, name=name)
        if isinstance(sub, nn.AvgPool2d):
            from ..fftype import PoolType

            k, s, p = _pair(sub.kernel_size), _pair(sub.stride or
                                                    sub.kernel_size), \
                _pair(sub.padding)
            return ff.pool2d(x(), *k, *s, *p, PoolType.POOL_AVG, name=name)
        if isinstance(sub, nn.BatchNorm2d):
            return ff.batch_norm(x(), relu=False, name=name)
        if isinstance(sub, nn.LayerNorm):
            nd = len(env[node.args[0].name].dims)
            axes = list(range(nd - len(sub.normalized_shape), nd))
            out = ff.layer_norm(x(), axes, sub.elementwise_affine,
                                sub.eps, name=name)
            if sub.elementwise_affine:
                self._weight_transfers.append(
                    (name, "gamma", sub.weight.detach().numpy()))
                self._weight_transfers.append(
                    (name, "beta", sub.bias.detach().numpy()))
            return out
        if isinstance(sub, nn.Embedding):
            out = ff.embedding(x(), sub.num_embeddings, sub.embedding_dim,
                               name=name)
            self._weight_transfers.append(
                (name, "kernel", sub.weight.detach().numpy()))
            return out
        if isinstance(sub, nn.Dropout):
            return ff.dropout(x(), sub.p, name=name)
        if isinstance(sub, nn.MultiheadAttention):
            q, k, v = (env[a.name] for a in node.args[:3])
            return ff.multihead_attention(
                q, k, v, sub.embed_dim, sub.num_heads,
                dropout=sub.dropout, bias=sub.in_proj_bias is not None,
                name=name)
        if isinstance(sub, nn.ReLU):
            return ff.relu(x(), name=name)
        if isinstance(sub, nn.GELU):
            return ff.gelu(x(), name=name)
        if isinstance(sub, nn.Sigmoid):
            return ff.sigmoid(x(), name=name)
        if isinstance(sub, nn.Tanh):
            return ff.tanh(x(), name=name)
        if isinstance(sub, nn.Softmax):
            return ff.softmax(x(), sub.dim if sub.dim is not None else -1,
                              name=name)
        if isinstance(sub, nn.Flatten):
            return ff.flat(x(), name=name)
        if isinstance(sub, nn.Identity):
            return x()
        raise NotImplementedError(f"torch module {type(sub).__name__}")

    def _handle_function(self, ff, node, env):
        import torch
        import torch.nn.functional as F

        fn = node.target

        def val(a):
            return env[a.name] if hasattr(a, "name") and a.name in env else a

        args = [val(a) for a in node.args]
        if fn in (operator.add, torch.add):
            return _binary(ff, ff.add, ff.scalar_add, args)
        if fn in (operator.sub, torch.sub):
            return _binary(ff, ff.subtract, ff.scalar_sub, args)
        if fn in (operator.mul, torch.mul):
            return _binary(ff, ff.multiply, ff.scalar_multiply, args)
        if fn in (operator.truediv, torch.div):
            return _binary(ff, ff.divide, ff.scalar_true_divide, args)
        if fn in (torch.relu, F.relu):
            return ff.relu(args[0])
        if fn is F.gelu:
            return ff.gelu(args[0])
        if fn in (torch.sigmoid, F.sigmoid):
            return ff.sigmoid(args[0])
        if fn in (torch.tanh, F.tanh):
            return ff.tanh(args[0])
        if fn is F.softmax or fn is torch.softmax:
            dim = node.kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], dim)
        if fn is torch.flatten:
            return ff.flat(args[0])
        if fn is torch.cat:
            tensors = [val(t) for t in node.args[0]]
            dim = node.kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(tensors, dim)
        if fn in (torch.matmul, torch.bmm):
            return ff.batch_matmul(args[0], args[1])
        if fn is torch.mean:
            dims = node.kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = node.kwargs.get("keepdim", False)
            if dims is None:  # global mean over every dim
                dims = list(range(len(args[0].dims)))
            dims = [dims] if isinstance(dims, int) else list(dims)
            return ff.mean(args[0], dims, keep)
        if fn is operator.getitem:
            seq, idx = args
            return seq[idx]
        if fn is torch.transpose:
            return _swap(ff, args[0], args[1], args[2])
        raise NotImplementedError(f"torch function {fn}")

    def _handle_method(self, ff, node, env):
        def val(a):
            return env[a.name] if hasattr(a, "name") and a.name in env else a

        args = [val(a) for a in node.args]
        x = args[0]
        m = node.target
        if m in ("view", "reshape"):
            shape = [a for a in args[1:]]
            if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
                shape = list(shape[0])
            # resolve -1 against the fx-propagated meta shape
            meta = node.meta.get("tensor_meta")
            if meta is not None:
                shape = list(meta.shape)
            return ff.reshape(x, shape)
        if m == "flatten":
            return ff.flat(x)
        if m == "permute":
            perm = args[1:]
            if len(perm) == 1 and isinstance(perm[0], (tuple, list)):
                perm = list(perm[0])
            return ff.transpose(x, perm)
        if m == "transpose":
            return _swap(ff, x, args[1], args[2])
        if m == "mean":
            dims = [args[1]] if isinstance(args[1], int) else list(args[1])
            return ff.mean(x, dims)
        if m in ("contiguous", "detach", "clone", "to", "float"):
            return x
        if m == "split":
            size, dim = args[1], node.kwargs.get(
                "dim", args[2] if len(args) > 2 else 0)
            total = x.dims[dim % len(x.dims)]
            return ff.split(x, total // size, dim)
        raise NotImplementedError(f"torch method {m}")

    # ------------------------------------------------------------ file path

    def _replay_file(self, ffmodel, input_tensors):
        outputs = {}
        env = {}
        it = iter(input_tensors)
        lines = [l.strip() for l in open(self.source) if l.strip()]
        final = []
        for line in lines:
            fields = line.split(IR_DELIMITER)
            name, in_names, op = fields[0], fields[1], fields[2]
            ins = [env[n] for n in in_names.split(",") if n]
            if op == "input":
                env[name] = next(it)
            elif op == "output":
                final = ins
            else:
                env[name] = _REPLAY[op](ffmodel, ins, fields[3:], name)
        return final


def _binary(ff, tensor_op, scalar_op, args):
    a, b = args[0], args[1]
    if isinstance(b, (int, float)):
        return scalar_op(a, float(b))
    if isinstance(a, (int, float)):
        return scalar_op(b, float(a))
    return tensor_op(a, b)


def _swap(ff, x, d0, d1):
    nd = len(x.dims)
    perm = list(range(nd))
    perm[d0 % nd], perm[d1 % nd] = perm[d1 % nd], perm[d0 % nd]
    return ff.transpose(x, perm)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _fetch_attr(module, target):
    obj = module
    for part in target.split("."):
        obj = getattr(obj, part)
    return obj


# ---------------------------------------------------------------- export

def torch_to_flexflow(module, filename: str, input_shapes=None):
    """Serialize an fx-traced module to a .ff IR file (reference
    torch_to_flexflow, model.py). Records: name; inputs; op; params..."""
    import torch
    import torch.fx

    traced = torch.fx.symbolic_trace(module.eval())
    if input_shapes:
        from torch.fx.passes.shape_prop import ShapeProp

        ShapeProp(traced).propagate(
            *[torch.zeros(s) for s in input_shapes])
    lines = []
    for node in traced.graph.nodes:
        if node.op == "placeholder":
            lines.append(IR_DELIMITER.join([node.name, "", "input"]))
        elif node.op == "output":
            args = node.args[0]
            outs = args if isinstance(args, (tuple, list)) else [args]
            names = ",".join(a.name for a in outs)
            lines.append(IR_DELIMITER.join(["_out", names, "output"]))
        elif node.op == "call_module":
            sub = traced.get_submodule(node.target)
            rec = _serialize_module(node, sub)
            lines.append(rec)
        else:
            raise NotImplementedError(
                f".ff export supports module calls only; got {node.op} "
                f"{node.target} (use PyTorchModel(module) for the direct "
                "path)")
    with open(filename, "w") as f:
        f.write("\n".join(lines) + "\n")


def _serialize_module(node, sub):
    import torch.nn as nn

    ins = ",".join(a.name for a in node.args if hasattr(a, "name"))
    name = node.name
    if isinstance(sub, nn.Linear):
        return IR_DELIMITER.join(
            [name, ins, "linear", str(sub.out_features),
             str(sub.bias is not None)])
    if isinstance(sub, nn.ReLU):
        return IR_DELIMITER.join([name, ins, "relu"])
    if isinstance(sub, nn.Sigmoid):
        return IR_DELIMITER.join([name, ins, "sigmoid"])
    if isinstance(sub, nn.Tanh):
        return IR_DELIMITER.join([name, ins, "tanh"])
    if isinstance(sub, nn.GELU):
        return IR_DELIMITER.join([name, ins, "gelu"])
    if isinstance(sub, nn.Softmax):
        dim = -1 if sub.dim is None else sub.dim
        return IR_DELIMITER.join([name, ins, "softmax", str(dim)])
    if isinstance(sub, nn.Flatten):
        return IR_DELIMITER.join([name, ins, "flat"])
    if isinstance(sub, nn.Dropout):
        return IR_DELIMITER.join([name, ins, "dropout", str(sub.p)])
    if isinstance(sub, nn.Conv2d):
        p = sub.padding if isinstance(sub.padding, tuple) \
            else (sub.padding,) * 2
        return IR_DELIMITER.join(
            [name, ins, "conv2d", str(sub.out_channels),
             str(sub.kernel_size[0]), str(sub.kernel_size[1]),
             str(sub.stride[0]), str(sub.stride[1]), str(p[0]), str(p[1]),
             str(sub.groups), str(sub.bias is not None)])
    if isinstance(sub, nn.MaxPool2d):
        k, s, p = _pair(sub.kernel_size), _pair(sub.stride or
                                                sub.kernel_size), \
            _pair(sub.padding)
        return IR_DELIMITER.join([name, ins, "pool2d", *map(str, k + s + p)])
    if isinstance(sub, nn.Embedding):
        return IR_DELIMITER.join(
            [name, ins, "embedding", str(sub.num_embeddings),
             str(sub.embedding_dim)])
    raise NotImplementedError(f".ff export for {type(sub).__name__}")


_REPLAY = {
    "linear": lambda ff, ins, p, n: ff.dense(
        ins[0], int(p[0]), use_bias=p[1] == "True", name=n),
    "relu": lambda ff, ins, p, n: ff.relu(ins[0], name=n),
    "sigmoid": lambda ff, ins, p, n: ff.sigmoid(ins[0], name=n),
    "tanh": lambda ff, ins, p, n: ff.tanh(ins[0], name=n),
    "gelu": lambda ff, ins, p, n: ff.gelu(ins[0], name=n),
    "softmax": lambda ff, ins, p, n: ff.softmax(ins[0], int(p[0]), name=n),
    "flat": lambda ff, ins, p, n: ff.flat(ins[0], name=n),
    "dropout": lambda ff, ins, p, n: ff.dropout(ins[0], float(p[0]), name=n),
    "conv2d": lambda ff, ins, p, n: ff.conv2d(
        ins[0], int(p[0]), int(p[1]), int(p[2]), int(p[3]), int(p[4]),
        int(p[5]), int(p[6]), groups=int(p[7]), use_bias=p[8] == "True",
        name=n),
    "pool2d": lambda ff, ins, p, n: ff.pool2d(
        ins[0], *(int(v) for v in p[:6]), name=n),
    "embedding": lambda ff, ins, p, n: ff.embedding(
        ins[0], int(p[0]), int(p[1]), name=n),
}
