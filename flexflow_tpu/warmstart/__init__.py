"""Warm start: persistent plan / calibration / executable caching.

Makes the second compile of the same job near-free (`--warmstart-dir`,
docs/performance.md "Warm start & compile caching"):

1. plan cache       — the searched Strategy + mesh shape, content-addressed
                      by a fingerprint of everything the search consumed
2. calibration DB   — persisted on-chip op measurements; calibration only
                      measures misses
3. executable cache — JAX's persistent compilation cache under the same
                      directory (eager step AND the engine's chunked scans)

Plus the `--auto-resume` fast path: the resilience checkpoint manifest
records the plan + structural fingerprint, so a preempted run restores its
plan here without searching — recovery time, not just checkpoint time,
bounds effective goodput (Gemini, SOSP'23).
"""

from .calibration_db import CalibrationDB
from .fingerprint import (
    calibration_fingerprint,
    full_fingerprint,
    graph_signature,
    structural_fingerprint,
)
from .manager import (
    WarmStartManager,
    enable_executable_cache,
    restore_plan,
    store_plan,
)
from .plan_cache import PlanCache

__all__ = [
    "CalibrationDB", "PlanCache", "WarmStartManager",
    "enable_executable_cache", "restore_plan", "store_plan",
    "graph_signature", "structural_fingerprint",
    "calibration_fingerprint", "full_fingerprint",
]
