"""Persistent calibration DB: on-chip op measurements that survive restarts.

`CostModel.calibrate` is the expensive half of a searched compile — each
measured op runs a warmed two-point fori_loop timing on the real chip. The
measurements are keyed by `_params_key(node)` = (op type, params repr,
unsharded input shapes) and are device-specific but run-independent, so
they persist under

    <warmstart-dir>/calibration.json
    {"version": 1,
     "devices": {"<platform>/<device_kind>": {"<key json>": [fwd, bwd]}}}

Loaded into the CostModel BEFORE `calibrate_graph` runs, so calibration
only measures misses (the reference's simulator cache, made durable).
Entries never overwrite an in-memory measurement (fresher wins), and a
corrupt/unreadable DB degrades to an empty one with a warning — a cache
must never be able to fail a compile.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..telemetry import log as fflog

_DB_NAME = "calibration.json"


def serialize_key(key) -> str:
    """(OperatorType, params repr, shapes tuple) → stable JSON string."""
    op_type, params_repr, shapes = key
    return json.dumps(
        [op_type.name, params_repr, [list(s) for s in shapes]])


def deserialize_key(s: str):
    from ..fftype import OperatorType

    op_name, params_repr, shapes = json.loads(s)
    return (OperatorType[op_name], params_repr,
            tuple(tuple(int(d) for d in shape) for shape in shapes))


def device_key() -> str:
    from .fingerprint import device_signature

    d = device_signature()
    return f"{d['platform']}/{d['device_kind']}"


class CalibrationDB:
    def __init__(self, directory: str):
        self.path = os.path.join(os.path.abspath(directory), _DB_NAME)

    def _read(self) -> dict:
        """The whole on-disk DB ({} when absent/corrupt — with a warning,
        never an exception)."""
        try:
            with open(self.path) as f:
                data = json.load(f)
            if not isinstance(data, dict) or "devices" not in data:
                raise ValueError("not a calibration DB")
            return data
        except FileNotFoundError:
            return {"version": 1, "devices": {}}
        except (OSError, ValueError) as e:
            fflog.warning(
                "warmstart: calibration DB %s unreadable (%s) — starting "
                "empty", self.path, e)
            return {"version": 1, "devices": {}}

    def load_into(self, cost_model) -> int:
        """Merge this device's persisted measurements into the cost model
        (in-memory entries win). Returns the number of entries loaded."""
        entries = self._read().get("devices", {}).get(device_key(), {})
        loaded = 0
        for key_s, val in entries.items():
            try:
                key = deserialize_key(key_s)
                fwd, bwd = float(val[0]), float(val[1])
            except (ValueError, KeyError, TypeError, IndexError):
                fflog.warning(
                    "warmstart: skipping malformed calibration entry %r",
                    key_s[:80])
                continue
            if key not in cost_model._calibration:
                cost_model._calibration[key] = (fwd, bwd)
                loaded += 1
        if loaded:
            # cached roofline costs predating the load are stale now
            cost_model._cache.clear()
        return loaded

    def save_from(self, cost_model) -> Optional[int]:
        """Persist the cost model's measurements (merged over the on-disk
        DB, atomic tmp+rename). Coordinator-only: callers gate on
        `distributed.is_coordinator()`. Returns entries written, or None
        when the write failed (warned, not raised)."""
        try:
            data = self._read()
            dev = data.setdefault("devices", {}).setdefault(device_key(), {})
            for key, (fwd, bwd) in cost_model._calibration.items():
                dev[serialize_key(key)] = [float(fwd), float(bwd)]
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = f"{self.path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
            return len(dev)
        except OSError as e:
            fflog.warning(
                "warmstart: could not persist calibration DB %s: %s",
                self.path, e)
            return None

    def save_entries(self, cost_model, keys) -> Optional[int]:
        """Persist ONLY the given `_params_key`s (merged over the
        on-disk DB, atomic tmp+rename) — ffscope's targeted refresh:
        an op-grain drift advisory re-measured one op, so exactly that
        op's DB entry is rewritten and every other persisted entry is
        left untouched. Coordinator-only, like save_from. Returns
        entries written, or None on failure (warned, not raised)."""
        try:
            data = self._read()
            dev = data.setdefault("devices", {}).setdefault(
                device_key(), {})
            written = 0
            for key in keys:
                val = cost_model._calibration.get(key)
                if val is None:
                    continue
                dev[serialize_key(key)] = [float(val[0]), float(val[1])]
                written += 1
            if not written:
                return 0
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = f"{self.path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
            return written
        except OSError as e:
            fflog.warning(
                "warmstart: could not persist calibration entries %s: %s",
                self.path, e)
            return None
