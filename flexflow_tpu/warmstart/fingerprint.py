"""Plan fingerprints: content addresses for cached parallelization plans.

The reference caches every measured operator cost inside the simulator
keyed by (OperatorParameters, MachineView) precisely because re-measuring
dominates search time (simulator.h:691-783); the warm-start subsystem
extends the same idea to the whole compile: a searched plan is valid for
exactly the inputs the search consumed, so those inputs — hashed — become
the plan's content address. Alpa (OSDI'22) treats auto-parallelization
output as an offline artifact for the same reason.

Two fingerprints, two uses:

- **structural** — graph signature (topology + op params + dtypes + weight
  specs + tied-weight links), configured mesh shape, the search-relevant
  FFConfig fields (with referenced files hashed by content), device kind,
  and the cost-model constants (opt_slots, mfu). Deterministic across
  process restarts, independent of any on-chip measurement — this is the
  key under which the resilience checkpoint manifest records the plan, so
  `--auto-resume` can re-adopt the interrupted run's exact plan without a
  search even when calibration would re-measure different numbers.
- **full** — structural + a hash of the calibration entries the cost model
  holds for this graph's ops. The plan-cache key: calibration data feeding
  the search is part of the plan's identity, so a recalibrated world (new
  chip, new toolchain, refreshed measurements) conservatively misses.

Invalidation is by construction: ANY component change → different address
→ miss → fresh search. There is no partial matching.
"""

from __future__ import annotations

import hashlib
import json
import os

# FFConfig fields that steer the search (and therefore the plan). A field
# added to the search MUST be added here, or two configs that search
# differently would share a fingerprint — when in doubt, include it.
_SEARCH_CONFIG_FIELDS = (
    "search_budget", "search_alpha", "search_overlap_backward_update",
    "only_data_parallel", "enable_sample_parallel",
    "enable_parameter_parallel", "enable_attribute_parallel",
    "enable_substitutions", "search_mesh_shapes", "search_calibrate",
    "base_optimize_threshold", "perform_memory_search",
    "search_num_nodes", "search_num_workers",
    "num_nodes", "workers_per_node",
    # overlap-capable collectives price as max(compute, comm) instead of
    # compute + comm (search/cost_model.py) — toggling it can flip the
    # winning strategy, so plans must not share an address across it
    "overlap_collectives",
    # weight-update sharding (ZeRO-style sharded optimizer / ZeRO-3
    # FSDP): forcing it changes how the search prices grad sync +
    # per-chip memory, and the raw None/True/False plus the forced stage
    # (None/0/2/3) are the deterministic inputs to the update-mode
    # decision (unity.choose_update_sharding) — plans must not share an
    # address across either, so the CHOSEN stage is part of the plan
    # fingerprint by construction (the decision is a pure function of
    # these fields + graph + mesh + calibration)
    "weight_update_sharding",
    "weight_update_stage",
    "computation_dtype", "allow_tensor_op_math_conversion",
    "force_tensor_op_math",
    # serving (serving/): a decode graph compiles under
    # COMP_MODE_INFERENCE — its plans must never share an address with a
    # training compile's (the graphs differ structurally too, but the
    # mode is the cheap, explicit discriminator)
    "computation_mode",
    # KV-cache layout (--serve-kv-layout): contiguous and paged decode
    # graphs must never share a plan address — the pool/page-table
    # tensors differ structurally too, but as with computation_mode the
    # field is the explicit discriminator the round-trip test pins
    "serve_kv_layout",
    # disaggregated serving (serving/disagg.py): the prefill and decode
    # sides are two independently searched plans over different
    # sub-meshes — the role (and the device offset carving the sub-mesh
    # out of the global device list) must keep their cache addresses
    # apart even when graph + mesh shape coincide
    "serve_role",
    "mesh_device_offset",
)


def _sha(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()).hexdigest()


def _file_digest(path: str) -> str:
    """Content hash of a config-referenced file; referenced-but-missing is
    its own distinct state (the compile would fail differently)."""
    if not path:
        return ""
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return f"missing:{os.path.basename(path)}"


def device_signature() -> dict:
    """The hardware the plan was searched (and calibrated) for."""
    import jax

    try:
        dev = jax.devices()[0]
        return {
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", ""),
            "device_count": jax.device_count(),
        }
    except Exception:
        return {"platform": "unknown", "device_kind": "", "device_count": 0}


def graph_signature(graph) -> list:
    """JSON-able signature of a PCG: per-node (name, op type, params repr,
    output shapes/dtypes, weight specs, tied-weight source) plus the edge
    list in node-name space. Node names are part of the signature on
    purpose: the cached Strategy is keyed by name, so differently-named
    builds must not share a plan."""
    sig = []
    for node in graph.topo_order():
        sig.append({
            "name": node.name,
            "op": node.op_type.name,
            "params": repr(node.params),
            "outputs": [
                [list(pt.shape.logical_shape), pt.dtype.name]
                for pt in node.outputs
            ],
            "weights": [
                [ws.name, list(ws.shape), ws.dtype.name, bool(ws.trainable)]
                for ws in node.weight_specs
            ],
            "tied": getattr(node, "weight_source", "") or "",
            "in": sorted(
                [graph.nodes[e.src].name, e.src_idx, e.dst_idx]
                for e in graph.in_edges[node.guid]
            ),
        })
    return sig


def config_signature(config) -> dict:
    sig = {}
    for name in _SEARCH_CONFIG_FIELDS:
        v = getattr(config, name, None)
        if not isinstance(v, (bool, int, float, str, type(None))):
            v = str(v)
        sig[name] = v
    sig["substitution_json"] = _file_digest(
        config.substitution_json_path or "")
    sig["machine_model_file"] = _file_digest(config.machine_model_file)
    return sig


def rules_signature(graph, mesh_axes: dict, config) -> str:
    """Content fingerprint of the substitution rule set THIS compile's
    search would rewrite with (ffrules pass 5, analysis/rules.py): the
    generated registry for this (mesh, config, graph), or the loaded
    --substitution-json rules. A changed/added/removed rule changes the
    plan address, so a stale cached plan can never replay against a
    different rule set — the `substitution_json` file digest alone only
    covers EXTERNAL rule changes, not built-in generator changes. The
    generator module's own source digest is folded in as the coarse
    backstop: a closure-body edit (a constraint predicate, a
    match-dependent make_params) changes rule SEMANTICS without
    changing the serialized structure, and conservative-miss beats
    replaying a plan searched under different semantics."""
    from ..analysis.rules import rules_fingerprint
    from ..search import substitution as _subs

    class _MeshShim:
        shape = {k: int(v) for k, v in mesh_axes.items()}

    src_digest = _file_digest(getattr(_subs, "__file__", ""))
    try:
        if config.substitution_json_path:
            # fingerprint only — verification happens at the search's own
            # verifying load site (config= there is the gate)
            xfers = _subs.load_rule_collection(  # fflint: ok unverified_rule_load
                config.substitution_json_path, _MeshShim)
        else:
            xfers = _subs.generate_all_pcg_xfers(  # fflint: ok unverified_rule_load
                _MeshShim, config, graph)
        return f"{rules_fingerprint(xfers)}:{src_digest}"
    except Exception as e:
        # an unloadable rule file is its own distinct state (the compile
        # would fail differently) — never crash the fingerprint
        return f"unloadable:{type(e).__name__}:{src_digest}"


def structural_fingerprint(graph, mesh_axes: dict, config,
                           opt_slots: int = 1, mfu: float = 0.4) -> str:
    """Measurement-free plan identity (see module docstring)."""
    return _sha({
        "v": 2,
        "graph": graph_signature(graph),
        "mesh": {k: int(v) for k, v in mesh_axes.items()},
        "config": config_signature(config),
        "device": device_signature(),
        "opt_slots": int(opt_slots),
        "mfu": repr(float(mfu)),
        # the rule set the search would rewrite with is part of the
        # plan's identity (ffrules pass 5): a changed registry must
        # invalidate every cached plan searched under the old one
        "rules": rules_signature(graph, mesh_axes, config),
    })


def calibration_fingerprint(cost_model, graph) -> str:
    """Hash of the calibration entries the search would consume for this
    graph (restricted to the graph's ops — unrelated DB entries must not
    churn the address). repr() keeps full float precision."""
    from ..search.cost_model import _params_key
    from .calibration_db import serialize_key

    entries = []
    seen = set()
    for node in graph.topo_order():
        if not node.inputs or not node.outputs:
            continue
        key = _params_key(node)
        if key in seen:
            continue
        seen.add(key)
        cal = cost_model._calibration.get(key)
        if cal is not None:
            entries.append([serialize_key(key), repr(cal[0]), repr(cal[1])])
    # collective-hop entries (reserved OP_NOOP keys written by
    # CostModel.calibrate_collectives): they price the sp ring traffic
    # via collective_rotate, so a refreshed hop measurement must change
    # the plan address like any other calibration the search consumed.
    # Iteration is explicitly sorted (fflint unsorted_dict_hash): dict
    # order is insertion order, which differs between a process that
    # MEASURED the entries and one that LOADED them from the DB
    for key, cal in sorted(cost_model._calibration.items(),
                           key=lambda kv: serialize_key(kv[0])):
        name = key[1] if len(key) > 1 else ""
        if isinstance(name, str) and name.startswith("__collective_"):
            entries.append([serialize_key(key), repr(cal[0]), repr(cal[1])])
    entries.sort()
    return _sha({"v": 1, "calibration": entries})


def full_fingerprint(structural: str, calibration: str) -> str:
    """The plan-cache address: structure AND the measurements that priced
    the candidates."""
    return hashlib.sha256(
        f"{structural}:{calibration}".encode()).hexdigest()
