"""WarmStartManager: the three-layer persistent compile cache, orchestrated.

Layer 1 — **plan cache** (plan_cache.py): the winning Strategy + mesh
shape, content-addressed by the full fingerprint. A hit skips
`joint_graph_optimize` entirely (0 search evaluations) and replays the
plan through the same machinery `--import-strategy` uses.

Layer 2 — **calibration DB** (calibration_db.py): persisted on-chip op
measurements, loaded before the search so `calibrate_graph` only measures
misses.

Layer 3 — **executable cache**: JAX's persistent compilation cache wired
under `<warmstart-dir>/xla_cache`, covering every jitted executable the
run compiles — the eager fused train step, eval/forward, and the
pipelined engine's chunked `lax.scan` executables alike.

`restore_plan` / `store_plan` are the two hooks `FFModel._compile_impl`
calls; everything here is fail-soft (a broken cache warns and compiles
fresh) and multi-host-safe (only the coordinator writes; the plan reaches
the other hosts through the existing host-0 broadcast).
"""

from __future__ import annotations

import os
from typing import Optional

from .. import telemetry
from ..telemetry import log as fflog
from .calibration_db import CalibrationDB
from .fingerprint import (
    calibration_fingerprint,
    full_fingerprint,
    structural_fingerprint,
)
from .plan_cache import PlanCache

# process-wide: jax's compilation-cache dir is global config, set once
_exec_cache_dir: Optional[str] = None


def enable_executable_cache(directory: str) -> bool:
    """Point JAX's persistent compilation cache under `directory`
    (idempotent; re-pointing to a different dir follows the newest
    request). Returns whether the cache is on. Never raises — an
    unsupported backend/jax version just leaves the layer off."""
    global _exec_cache_dir
    cache_dir = os.path.join(os.path.abspath(directory), "xla_cache")
    if _exec_cache_dir == cache_dir:
        return True
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        if _exec_cache_dir is not None:
            # jax materializes the cache object lazily from the config and
            # then pins it — re-pointing an already-initialized cache to a
            # new directory needs an explicit reset
            try:
                from jax._src import compilation_cache

                compilation_cache.reset_cache()
            except Exception:
                pass
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # CI-scale executables are small and fast to compile — cache them
        # all; the default thresholds exist to protect long-lived prod
        # caches, and ours lives inside the run's own warm-start dir
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _exec_cache_dir = cache_dir
        return True
    except Exception as e:  # unsupported backend / jax version
        fflog.warning(
            "warmstart: persistent executable cache unavailable (%s) — "
            "plan/calibration layers still active", e)
        return False


class WarmStartManager:
    """One model's handle on a warm-start directory."""

    def __init__(self, model, directory: str):
        self.model = model
        self.directory = os.path.abspath(directory)
        self.plan_cache = PlanCache(self.directory)
        self.calibration_db = CalibrationDB(self.directory)
        self.executable_cache_on = enable_executable_cache(self.directory)
        self.structural_fp: Optional[str] = None
        self.full_fp: Optional[str] = None
        self.calibration_loaded = 0

    # ------------------------------------------------------------ fingerprint

    def prepare(self, graph, cost_model, calibrate_fn) -> str:
        """Load the calibration DB, run (miss-only) calibration, and
        compute this compile's full fingerprint. Returns the full
        fingerprint and stashes both on the manager."""
        with telemetry.span("warmstart.calibration_load"):
            self.calibration_loaded = self.calibration_db.load_into(
                cost_model)
        calibrate_fn()
        sfp = self.model._plan_fingerprint
        cfp = calibration_fingerprint(cost_model, graph)
        self.structural_fp = sfp
        self.full_fp = full_fingerprint(sfp, cfp)
        return self.full_fp

    # ------------------------------------------------------------ plan layer

    def lookup_plan(self, graph):
        """(overrides, mesh_axes) for the prepared fingerprint, validated
        against `graph` and the plan's own mesh — or None (miss). A plan
        that fails validation is stale (the fingerprint SHOULD have caught
        the change, so also say which components to suspect) and reads as
        a miss."""
        entry = self.plan_cache.lookup(self.full_fp)
        if entry is None:
            return None
        try:
            return _decode_validated_plan(
                self.model, graph, entry["strategy"],
                entry.get("mesh_axes"))
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            fflog.warning(
                "warmstart: cached plan %s does not apply to this compile "
                "(%s) — re-searching", self.full_fp[:16], e)
            return None

    def store_plan(self, overrides: dict, mesh_axes: dict,
                   meta: Optional[dict] = None) -> None:
        """Persist the searched plan + calibration DB (coordinator only)."""
        from ..distributed import is_coordinator
        from ..parallel.strategies import Strategy

        if self.full_fp is None or not is_coordinator():
            return
        with telemetry.span("warmstart.store"):
            self.plan_cache.store(
                self.full_fp, Strategy(overrides or {}).to_json(),
                mesh_axes, structural_fingerprint=self.structural_fp or "",
                meta=meta)
            if self._cost_model is not None:
                self.calibration_db.save_from(self._cost_model)

    # stashed by restore_plan so store_plan can persist its measurements
    _cost_model = None


def _decode_validated_plan(model, graph, strategy_json, mesh_axes_raw):
    """Stored plan (strategy JSON + mesh axes) → (overrides, mesh_axes),
    validated against the mesh the plan will actually run on (a
    mesh-shape-searched plan carries its winning factorization; an empty
    mesh_axes means the current mesh). The ONE decode+validate gate both
    restore paths — plan cache and checkpoint manifest — go through.
    `Strategy.validate` delegates to the full ffcheck sharding verifier
    (analysis.verify_strategy), so cache/checkpoint/import adoption all
    inherit every verifier check — axis reuse, oversharding,
    indivisibility, unknown nodes/weights/axes. Raises ValueError/
    KeyError/TypeError/AttributeError on anything stale or malformed;
    callers convert that to a miss + re-search, never a crash."""
    from ..parallel.strategies import Strategy
    from ..search.mesh_search import MeshSpec

    strat = Strategy.from_json(strategy_json)
    mesh_axes = {k: int(v) for k, v in (mesh_axes_raw or {}).items()}
    names = model.config.mesh_shape().axis_names
    unknown = sorted(set(mesh_axes) - set(names))
    if unknown:
        raise ValueError(
            f"plan mesh axes {unknown} not in this config's mesh axis "
            f"names {sorted(names)}")
    sizes = {a: 1 for a in names}
    if mesh_axes:
        sizes.update(mesh_axes)
    else:
        sizes.update({k: int(v) for k, v in model.mesh.shape.items()})
    strat.validate(graph, MeshSpec(sizes))
    return strat.overrides, mesh_axes


def _checkpoint_plan(model, structural_fp: str, graph):
    """The plan recorded in the newest committed checkpoint's manifest,
    when its structural fingerprint matches this compile — the
    `--auto-resume` fast path: weights restore in fit, the PLAN restores
    here, and no search runs in between. None on any mismatch."""
    cfg = model.config
    if not (cfg.auto_resume and cfg.checkpoint_dir):
        return None
    import json

    from ..resilience.checkpointer import latest_checkpoint

    path = latest_checkpoint(cfg.checkpoint_dir)
    if path is None:
        return None
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            plan = (json.load(f).get("extras") or {}).get("plan")
    except (OSError, ValueError):
        return None
    if not isinstance(plan, dict):
        return None
    if plan.get("structural_fingerprint") != structural_fp:
        fflog.info(
            "warmstart: checkpoint %s plan fingerprint differs from this "
            "compile (graph/mesh/config/device changed) — searching fresh",
            path)
        return None
    try:
        return _decode_validated_plan(model, graph, plan["strategy"],
                                      plan.get("mesh_axes"))
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        fflog.warning(
            "warmstart: checkpoint plan in %s does not apply (%s) — "
            "searching fresh", path, e)
        return None


def restore_plan(model, graph, cost_model, calibrate_fn):
    """The compile-time warm-start decision. Returns
    (strategy overrides, plan mesh_axes, source) with source in
    {"checkpoint", "cache"}, or None (→ run the search).

    Side effects: stashes the structural fingerprint on the model (the
    checkpoint-manifest plan key), and — when `--warmstart-dir` is set —
    attaches a WarmStartManager, loads the calibration DB, and runs the
    (miss-only) calibration so the full fingerprint exists for both the
    lookup here and the store after a search."""
    mesh_axes_now = {k: int(v) for k, v in model.mesh.shape.items()}
    sfp = structural_fingerprint(
        graph, mesh_axes_now, model.config,
        opt_slots=cost_model.opt_slots, mfu=cost_model.mfu)
    model._plan_fingerprint = sfp

    # 1) the interrupted run's own plan, recorded in its checkpoint
    with telemetry.span("warmstart.plan_lookup", layer="checkpoint"):
        ck = _checkpoint_plan(model, sfp, graph)
    if ck is not None:
        overrides, mesh_axes = ck
        telemetry.instant("warmstart.plan_hit", source="checkpoint")
        telemetry.inc("warmstart_plan_lookups_total", result="hit",
                      source="checkpoint")
        telemetry.event("warmstart", plan="hit", source="checkpoint",
                        fingerprint=sfp)
        fflog.info("warmstart: plan restored from checkpoint manifest "
                   "(no search)")
        return overrides, mesh_axes, "checkpoint"

    # 2) the persistent plan cache
    if not model.config.warmstart_dir:
        return None
    warm = model._warmstart
    if warm is None:
        warm = model._warmstart = WarmStartManager(
            model, model.config.warmstart_dir)
    warm._cost_model = cost_model
    warm.prepare(graph, cost_model, calibrate_fn)
    stats = getattr(cost_model, "calib_stats", None) or {}
    with telemetry.span("warmstart.plan_lookup", layer="cache"):
        hit = warm.lookup_plan(graph)
    telemetry.counter("warmstart.calibration", {
        "loaded": warm.calibration_loaded,
        "measured": stats.get("measured", 0),
        "cache_hits": stats.get("cache_hits", 0)})
    if hit is None:
        telemetry.instant("warmstart.plan_miss")
        telemetry.inc("warmstart_plan_lookups_total", result="miss",
                      source="cache")
        telemetry.event(
            "warmstart", plan="miss", fingerprint=warm.full_fp,
            calibration_loaded=warm.calibration_loaded,
            calibration_measured=stats.get("measured", 0),
            calibration_cache_hits=stats.get("cache_hits", 0),
            executable_cache=warm.executable_cache_on)
        return None
    overrides, mesh_axes = hit
    telemetry.instant("warmstart.plan_hit", source="cache")
    telemetry.inc("warmstart_plan_lookups_total", result="hit",
                  source="cache")
    telemetry.event(
        "warmstart", plan="hit", source="cache",
        fingerprint=warm.full_fp,
        calibration_loaded=warm.calibration_loaded,
        calibration_measured=stats.get("measured", 0),
        calibration_cache_hits=stats.get("cache_hits", 0),
        executable_cache=warm.executable_cache_on)
    fflog.info("warmstart: plan cache hit %s — search skipped",
               warm.full_fp[:16])
    return overrides, mesh_axes, "cache"


def store_plan(model, meta: Optional[dict] = None,
               replay_names=None) -> None:
    """Persist the just-searched plan under the fingerprint computed by
    restore_plan. No-op when warm start is off or the fingerprint was
    never prepared (multi-host non-coordinators, import paths).

    `replay_names` is the PRE-rewrite graph's node-name set: a
    substitution-rewritten winner's strategy is keyed by rewritten-graph
    names that a fresh compile's graph will never contain, so caching it
    would just produce a validation-failed miss (plus a misleading
    warning) on every restart — skip the plan entry, keep the
    calibration DB (its measurements replay fine)."""
    warm = model._warmstart
    if warm is None or warm.full_fp is None:
        return
    overrides = model._strategy or {}
    if replay_names is not None and not set(overrides) <= set(replay_names):
        from ..distributed import is_coordinator

        rewritten = sorted(set(overrides) - set(replay_names))
        fflog.info(
            "warmstart: winning plan is keyed by rewritten-graph nodes "
            "%s — plan not cached (a fresh compile could not replay it); "
            "calibration DB still persisted", rewritten[:4])
        if warm._cost_model is not None and is_coordinator():
            warm.calibration_db.save_from(warm._cost_model)
        return
    mesh_axes = {k: int(v) for k, v in model.mesh.shape.items()}
    warm.store_plan(overrides, mesh_axes, meta=meta)
