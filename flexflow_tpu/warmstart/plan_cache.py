"""Content-addressed persistent plan cache.

Layout under the warm-start directory:

    <warmstart-dir>/plans/<full-fingerprint>.json
    {"version": 1, "fingerprint": ..., "structural_fingerprint": ...,
     "strategy": <Strategy.to_json()>, "mesh_axes": {...}, "meta": {...}}

One file per fingerprint, written atomically (tmp + rename) by the
coordinator only. Lookup is a single read keyed by the address; anything
wrong with the entry — unparseable JSON, wrong version, fingerprint not
matching its own filename, strategy that fails schema decode — logs a
warning and reads as a miss (the compile then searches fresh and rewrites
the entry). A cache must never be able to fail a compile.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..telemetry import log as fflog

_PLAN_VERSION = 1


class PlanCache:
    def __init__(self, directory: str):
        self.directory = os.path.join(os.path.abspath(directory), "plans")

    def _path(self, fingerprint: str) -> str:
        return os.path.join(self.directory, f"{fingerprint}.json")

    def lookup(self, fingerprint: str) -> Optional[dict]:
        """The committed entry for `fingerprint`, or None. Corrupt/stale
        entries warn and read as a miss — never raise."""
        path = self._path(fingerprint)
        try:
            with open(path) as f:
                entry = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            fflog.warning(
                "warmstart: plan cache entry %s unreadable (%s) — "
                "treating as a miss", path, e)
            return None
        if (not isinstance(entry, dict)
                or entry.get("version") != _PLAN_VERSION
                or entry.get("fingerprint") != fingerprint
                or not isinstance(entry.get("strategy"), dict)):
            fflog.warning(
                "warmstart: plan cache entry %s malformed/stale — "
                "treating as a miss", path)
            return None
        # graph-free ffcheck precheck: per-assignment mesh-axis reuse is
        # an invalid NamedSharding detectable from the JSON alone — a
        # poisoned/hand-edited entry reads as a miss here, before the
        # full verifier (Strategy.validate → analysis.verify_strategy)
        # sees it against the graph downstream
        from ..analysis.sharding import strategy_json_problems

        problems = strategy_json_problems(entry["strategy"])
        if problems:
            fflog.warning(
                "warmstart: plan cache entry %s fails static "
                "verification (%s) — treating as a miss",
                path, "; ".join(str(p) for p in problems[:3]))
            return None
        return entry

    def store(self, fingerprint: str, strategy_json: dict,
              mesh_axes: dict, structural_fingerprint: str = "",
              meta: Optional[dict] = None) -> Optional[str]:
        """Write one plan entry atomically. Returns the path, or None when
        the write failed (warned, not raised). Callers gate on
        `distributed.is_coordinator()` — multi-host, only host 0 writes."""
        entry = {
            "version": _PLAN_VERSION,
            "fingerprint": fingerprint,
            "structural_fingerprint": structural_fingerprint,
            "strategy": strategy_json,
            "mesh_axes": {k: int(v) for k, v in (mesh_axes or {}).items()},
            "created_unix": time.time(),
            "meta": dict(meta or {}),
        }
        path = self._path(fingerprint)
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(entry, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError as e:
            fflog.warning(
                "warmstart: could not persist plan entry %s: %s", path, e)
            return None
