// PCG graph-algorithm core (C++17, no deps).
//
// Native equivalent of the reference's C++ graph utilities
// (include/flexflow/dominators.h, basic_graph.h, graph_structures.h and the
// bottleneck/sequence-split machinery in src/runtime/graph.cc) — the parts
// of the runtime the reference keeps native and unit-tests natively
// (tests/unit/test_dominators.cc). Exposed as a C ABI consumed from Python
// via ctypes (no pybind11 in this image).
//
// All functions take the graph as CSR-ish arrays: n nodes (0..n-1 in
// topological candidate order not required), m edges (src[i] -> dst[i]).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

extern "C" {

// Topological order. Returns 0 on success, -1 if the graph has a cycle.
// Deterministic: among ready nodes, smallest id first.
int ff_topo_order(int32_t n, int32_t m, const int32_t* src,
                  const int32_t* dst, int32_t* out_order) {
  std::vector<std::vector<int32_t>> adj(n);
  std::vector<int32_t> indeg(n, 0);
  for (int32_t i = 0; i < m; i++) {
    adj[src[i]].push_back(dst[i]);
    indeg[dst[i]]++;
  }
  std::priority_queue<int32_t, std::vector<int32_t>, std::greater<int32_t>>
      ready;
  for (int32_t v = 0; v < n; v++)
    if (indeg[v] == 0) ready.push(v);
  int32_t k = 0;
  while (!ready.empty()) {
    int32_t v = ready.top();
    ready.pop();
    out_order[k++] = v;
    for (int32_t w : adj[v])
      if (--indeg[w] == 0) ready.push(w);
  }
  return k == n ? 0 : -1;
}

// Bottleneck nodes: nodes every source->sink path crosses (the reference's
// sequence-split points, graph.cc find_bottleneck_node). out_mask[v] = 1 if
// v is a bottleneck. The last node in topo order is excluded (a cut there
// splits nothing). Returns count, or -1 on cycle.
int ff_bottlenecks(int32_t n, int32_t m, const int32_t* src,
                   const int32_t* dst, int32_t* out_mask) {
  std::vector<int32_t> order(n);
  if (ff_topo_order(n, m, src, dst, order.data()) != 0) return -1;
  std::vector<int32_t> pos(n);
  for (int32_t i = 0; i < n; i++) pos[order[i]] = i;
  std::vector<int32_t> in_cnt(n, 0), out_cnt(n, 0);
  for (int32_t i = 0; i < m; i++) {
    out_cnt[src[i]]++;
    in_cnt[dst[i]]++;
  }
  std::memset(out_mask, 0, sizeof(int32_t) * n);
  int64_t open_edges = 0;
  int32_t count = 0;
  for (int32_t i = 0; i < n; i++) {
    int32_t v = order[i];
    open_edges -= in_cnt[v];
    if (open_edges == 0 && i < n - 1) {
      out_mask[v] = 1;
      count++;
    }
    open_edges += out_cnt[v];
  }
  return count;
}

// Transitive reduction: out_keep[i] = 0 for edge i if a longer path
// src[i] ->* dst[i] exists (the reference's contract_out_edge /
// transitive reduction pass). O(m * (n + m)) bitset BFS.
int ff_transitive_reduction(int32_t n, int32_t m, const int32_t* src,
                            const int32_t* dst, int32_t* out_keep) {
  std::vector<std::vector<int32_t>> adj(n);
  for (int32_t i = 0; i < m; i++) adj[src[i]].push_back(dst[i]);
  // reach[v] = bitset of nodes reachable from v via paths of length >= 1
  int32_t words = (n + 63) / 64;
  std::vector<uint64_t> reach((size_t)n * words, 0);
  std::vector<int32_t> order(n);
  if (ff_topo_order(n, m, src, dst, order.data()) != 0) return -1;
  for (int32_t i = n - 1; i >= 0; i--) {
    int32_t v = order[i];
    uint64_t* rv = &reach[(size_t)v * words];
    for (int32_t w : adj[v]) {
      rv[w / 64] |= (1ull << (w % 64));
      const uint64_t* rw = &reach[(size_t)w * words];
      for (int32_t k = 0; k < words; k++) rv[k] |= rw[k];
    }
  }
  for (int32_t i = 0; i < m; i++) {
    out_keep[i] = 1;
    int32_t s = src[i], d = dst[i];
    // drop if any other out-neighbor of s reaches d
    for (int32_t w : adj[s]) {
      if (w == d) continue;
      if (reach[(size_t)w * words + d / 64] & (1ull << (d % 64))) {
        out_keep[i] = 0;
        break;
      }
    }
  }
  return 0;
}

// Immediate dominators over the DAG (reference dominators.h). Entry nodes
// (in-degree 0) get idom = -1. Multi-source graphs use a virtual root, also
// reported as -1. Returns 0, or -1 on cycle.
int ff_idominators(int32_t n, int32_t m, const int32_t* src,
                   const int32_t* dst, int32_t* out_idom) {
  std::vector<int32_t> order(n);
  if (ff_topo_order(n, m, src, dst, order.data()) != 0) return -1;
  std::vector<int32_t> pos(n);
  for (int32_t i = 0; i < n; i++) pos[order[i]] = i;
  std::vector<std::vector<int32_t>> preds(n);
  for (int32_t i = 0; i < m; i++) preds[dst[i]].push_back(src[i]);
  std::vector<int32_t> idom(n, -2);  // -2 = unset, -1 = root
  auto intersect = [&](int32_t a, int32_t b) {
    while (a != b) {
      if (a == -1 || b == -1) return (int32_t)-1;
      while (a != b && pos[a] > pos[b]) a = idom[a] >= 0 ? idom[a] : -1;
      if (a == -1) return (int32_t)-1;
      while (b != a && pos[b] > pos[a]) b = idom[b] >= 0 ? idom[b] : -1;
      if (b == -1) return (int32_t)-1;
    }
    return a;
  };
  for (int32_t i = 0; i < n; i++) {
    int32_t v = order[i];
    if (preds[v].empty()) {
      idom[v] = -1;
      continue;
    }
    int32_t d = preds[v][0];
    for (size_t j = 1; j < preds[v].size(); j++)
      d = intersect(d, preds[v][j]);
    idom[v] = d;
  }
  std::memcpy(out_idom, idom.data(), sizeof(int32_t) * n);
  return 0;
}

// Strategy-evaluation hot loop for the Unity search (the simulate_runtime
// analog, reference simulator.cc). Model: every op runs on all chips, so
// compute serializes across the whole set (sum of compute); communication
// (reshards, psums, gradient sync) can overlap compute of *other* ops but
// not its own dependency chain, so the critical path of (compute + comm)
// is a second lower bound — concurrent branches (DLRM towers, Inception)
// take the max of their paths instead of the sum:
//   makespan = max( sum_i compute[i],
//                   max over paths P of sum_{i in P} (compute[i]+comm[i]) )
// Returns -1.0 on cycle.
double ff_eval_makespan(int32_t n, const double* compute, const double* comm,
                        int32_t m, const int32_t* src, const int32_t* dst) {
  std::vector<int32_t> order(n);
  if (ff_topo_order(n, m, src, dst, order.data()) != 0) return -1.0;
  std::vector<std::vector<int32_t>> preds(n);
  for (int32_t i = 0; i < m; i++) preds[dst[i]].push_back(src[i]);
  std::vector<double> finish(n, 0.0);
  double total_compute = 0.0, critical = 0.0;
  for (int32_t i = 0; i < n; i++) {
    int32_t v = order[i];
    double start = 0.0;
    for (int32_t p : preds[v]) start = std::max(start, finish[p]);
    finish[v] = start + compute[v] + comm[v];
    critical = std::max(critical, finish[v]);
    total_compute += compute[v];
  }
  return std::max(total_compute, critical);
}

// Resource-aware variant: the TPU recast of the reference's machine-resource
// (horizontal) splits (graph.cc:267-321). On TPU the contended resource of
// concurrent branches is not a chip subset (SPMD runs every op on all chips)
// but the ICI axis a collective rides: two branches all-reducing over the
// SAME mesh axis serialize on its links, while collectives on disjoint axes
// genuinely overlap. axis[i] in [0, n_axes) names the ICI axis of node i's
// communication (-1 = none / axis-free), adding per-axis link-occupancy
// lower bounds:
//   makespan = max( sum_i compute[i],
//                   max_a sum_{axis[i]==a} comm[i],
//                   critical path of compute+comm )
// Returns -1.0 on cycle.
double ff_eval_makespan_axes(int32_t n, const double* compute,
                             const double* comm, const int32_t* axis,
                             int32_t m, const int32_t* src,
                             const int32_t* dst) {
  double base = ff_eval_makespan(n, compute, comm, m, src, dst);
  if (base < 0) return base;
  std::vector<double> axis_comm;
  for (int32_t i = 0; i < n; i++) {
    if (axis[i] < 0) continue;
    if ((size_t)axis[i] >= axis_comm.size()) axis_comm.resize(axis[i] + 1, 0.0);
    axis_comm[axis[i]] += comm[i];
  }
  for (double c : axis_comm) base = std::max(base, c);
  return base;
}

}  // extern "C"
