"""Micro-benchmark attention fwd+bwd at the bench shape on the real chip.

Compares flash-kernel variants (and the XLA path) so layout changes can be
measured in ~seconds instead of re-running the full bench. Iterations are
chained through a lax.scan inside one jit so per-dispatch overhead (large
through the axon relay) amortizes away and nothing is dead-code-eliminated.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

INNER = 50


def timed_scan(step, init, n=INNER, reps=5):
    @jax.jit
    def run(x):
        return jax.lax.scan(lambda c, _: (step(c), None), x, None, length=n)[0]

    out = run(init)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(init)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best / n * 1e3  # ms per iteration


def main():
    b, h, s, d = 8, 16, 512, 64
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
    g = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)

    from flexflow_tpu.kernels.flash_attention import flash_attention
    from flexflow_tpu.ops.attention import sdpa_xla

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True)

    def f_xla(q, k, v):
        return sdpa_xla(q, k, v, causal=True, scale=1.0 / d ** 0.5)

    def fwd_step(f):
        def step(carry):
            q, k, v = carry
            out = f(q, k, v)
            return (out, k, v)  # chain: next q is this out
        return step

    def fb_step(f):
        def step(carry):
            q, k, v = carry
            out, vjp = jax.vjp(f, q, k, v)
            dq, dk, dv = vjp((out * 0 + g).astype(out.dtype))
            return (out + 0.01 * dq.astype(out.dtype), k, v)
        return step

    for name, f in [("flash", f_flash), ("xla", f_xla)]:
        t_f = timed_scan(fwd_step(f), (q, k, v))
        t_fb = timed_scan(fb_step(f), (q, k, v))
        print(f"{name:6s} fwd {t_f:7.3f} ms   f+b {t_fb:7.3f} ms")


if __name__ == "__main__":
    main()
