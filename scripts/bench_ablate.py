"""Ablation harness around bench.py's model to locate step-time costs.

Knobs via env:
  ABL_ATTN=flash|xla    attention impl (default flash)
  ABL_BATCH=N           batch size (default 8)
  ABL_NO_METRICS=1      skip metrics.compute in the step
  ABL_NO_OPT=1          skip optimizer update (grads still computed)
  ABL_FWD_ONLY=1        forward+loss only (no grad)
"""
import json
import os
import sys
import time

import numpy as np


def main():
    sys.argv = [sys.argv[0]]
    import jax

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import DataType
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    attn = os.environ.get("ABL_ATTN", "flash")
    batch = int(os.environ.get("ABL_BATCH", "8"))
    cfg = TransformerLMConfig(
        vocab_size=32000, hidden_size=1024, num_heads=16, num_layers=12,
        sequence_length=512, attention_impl=attn,
    )
    steps, warmup = 20, 3

    config = FFConfig()
    config.batch_size = batch
    config.computation_dtype = DataType.DT_BFLOAT16
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    ex = ff.executor

    if os.environ.get("ABL_NO_METRICS"):
        ex.metrics.compute = lambda counters, logits, labels, **kw: counters
    if os.environ.get("ABL_NO_OPT"):
        ex.optimizer.update = lambda grads, params, slots, step: (params, slots)

    if os.environ.get("ABL_FWD_ONLY"):
        import jax.numpy as jnp

        def fwd_step(params, state, opt_slots, step, counters, rng, batch):
            x_inputs, labels = batch
            loss_fn = ex.make_loss_fn(state, x_inputs, labels, rng)
            lval, (logits, new_state, ce_sum) = loss_fn(params)
            return params, state, opt_slots, step + 1, counters, lval

        # single-process ablation harness: the env var SELECTS the bench
        # variant by design; no fleet to diverge
        step_fn = jax.jit(fwd_step)  # fflint: ok host_divergent_branch
    else:
        step_fn = ex.build_train_step()

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (batch, cfg.sequence_length)).astype(np.int32)
    pos = np.tile(np.arange(cfg.sequence_length, dtype=np.int32), (batch, 1))
    labels = rs.randint(0, cfg.vocab_size,
                        (batch, cfg.sequence_length, 1)).astype(np.int32)
    batch_data = ff._make_batch({"tokens": toks, "positions": pos}, labels)

    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    rng = jax.random.key(0)

    def run(n):
        nonlocal state, rng
        for _ in range(n):
            rng, sub = jax.random.split(rng)
            p, s, o, st, c, _ = step_fn(*state, sub, batch_data)
            state = (p, s, o, st, c)
        jax.block_until_ready(state[0])

    run(warmup)
    t0 = time.perf_counter()
    run(steps)
    dt = time.perf_counter() - t0
    tok_s = steps * batch * cfg.sequence_length / dt
    print(json.dumps({
        "attn": attn, "batch": batch,
        "no_metrics": bool(os.environ.get("ABL_NO_METRICS")),
        "no_opt": bool(os.environ.get("ABL_NO_OPT")),
        "fwd_only": bool(os.environ.get("ABL_FWD_ONLY")),
        "ms_per_step": round(dt / steps * 1e3, 3),
        "tokens_per_sec": round(tok_s, 1),
    }))


if __name__ == "__main__":
    main()
