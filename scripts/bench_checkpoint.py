"""Measure async checkpointing overhead (acceptance: steps/sec with
--checkpoint-every enabled within 10% of the no-checkpoint baseline).

Two numbers, because the CPU test rig conflates them:

- **blocking cost**: what the step loop actually pays per save — the
  copy-on-snapshot (batched `jax.device_get`) + writer-thread handoff.
  This is the cost TPU training would see, where the background writer
  runs on otherwise-idle host cores while devices compute.
- **wall-clock overhead**: total fit-time delta on the 8-virtual-device
  CPU mesh, where the writer thread *competes with XLA for the same
  cores* — an artifact absent on real TPU hosts. Measured interleaved
  (min-of-N, load drift hits both configurations equally) at a dense
  cadence (every 8 steps ≈ every 90ms here; real runs checkpoint every
  minutes) and a moderate one (every 32).

The headline JSON line prints LAST.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault(
    "XLA_FLAGS",
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _build(argv):
    sys.argv = ["bench_checkpoint", *argv]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = (8, 1, 1, 1)
    config.batch_size = 8
    ff = FFModel(config)
    x = ff.create_tensor((8, 64), name="x")
    t = ff.dense(x, 256, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 256, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 8, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _timed_fit(ff, x, y, epochs):
    t0 = time.perf_counter()
    ff.fit(x, y, epochs=epochs, batch_size=8, shuffle=False)
    return time.perf_counter() - t0


def main():
    rs = np.random.RandomState(0)
    x = rs.randn(1024, 64).astype(np.float32)
    y = rs.randint(0, 8, (1024, 1)).astype(np.int32)

    # ---- blocking cost per save: snapshot + async handoff, measured on
    # the exact state tree fit checkpoints
    from flexflow_tpu.resilience.checkpointer import snapshot_to_host
    from flexflow_tpu.resilience.reshard import model_state_tree

    probe = _build([])
    probe.fit(x[:64], y[:64], epochs=1, batch_size=8, shuffle=False)
    tree = model_state_tree(probe)
    snapshot_to_host(tree)  # warm
    t_snap = min(
        (lambda t0: (snapshot_to_host(tree), time.perf_counter() - t0)[1])(
            time.perf_counter())
        for _ in range(10))

    results = []
    with tempfile.TemporaryDirectory() as root:
        bare = _build([])
        _timed_fit(bare, x, y, 1)  # warm
        # the LAST-printed (headline) line must be the documented
        # acceptance cadence (32), not the dense contention-artifact one
        for every in (8, 32):
            ck = _build(["--checkpoint-dir", os.path.join(root, str(every)),
                         "--checkpoint-every", str(every)])
            _timed_fit(ck, x, y, 1)  # warm
            # interleave so machine-load drift hits both configs equally
            t_bare = t_ck = float("inf")
            for _ in range(5):
                t_bare = min(t_bare, _timed_fit(bare, x, y, 2))
                t_ck = min(t_ck, _timed_fit(ck, x, y, 2))
            results.append({
                "checkpoint_every": every,
                "overhead_frac": round(t_ck / t_bare - 1.0, 4),
                "baseline_s": round(t_bare, 4),
                "with_checkpoint_s": round(t_ck, 4),
            })

    for r in results[:-1]:
        print(json.dumps({"metric": "async_checkpoint_overhead_frac", **r,
                          "note": "CPU-rig wall-clock (writer competes "
                                  "with XLA for cores; absent on TPU)"}))
    head = results[-1]
    print(json.dumps({
        "metric": "async_checkpoint_overhead_frac",
        **head,
        "blocking_cost_ms_per_save": round(t_snap * 1e3, 2),
        "within_10pct": bool(head["overhead_frac"] < 0.10),
        "note": "CPU-rig wall-clock; step-loop blocking cost is "
                "blocking_cost_ms_per_save (the TPU-relevant number)",
    }))


if __name__ == "__main__":
    main()
