"""Warm-start CI gate: compare a cold and a warm run's metrics.jsonl.

Usage: python scripts/check_warmstart.py COLD_METRICS WARM_METRICS

The two files come from running scripts/telemetry_smoke.py twice against
one shared `--warmstart-dir` (with search flags, so there is a search to
skip). Asserts:

  - the cold compile searched (plan_source: search) and recorded a
    warmstart MISS;
  - the warm compile's record shows plan_source: cache (the plan cache
    hit — zero search evaluations by construction) and a warmstart HIT;
  - the warm compile duration is smaller than the cold one;
  - both runs' fit summaries carry time_to_first_step_s, warm < cold.

Exits nonzero with a diagnostic on any violation.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str):
    print(f"check_warmstart: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def load(path: str):
    from flexflow_tpu.telemetry import read_jsonl

    recs = read_jsonl(path)
    compiles = [r for r in recs if r.get("kind") == "compile"]
    if not compiles:
        fail(f"{path}: no compile record")
    warm_events = [r for r in recs if r.get("kind") == "warmstart"]
    summaries = [r for r in recs if r.get("kind") == "summary"]
    return compiles[0], warm_events, summaries[-1] if summaries else None


def main():
    if len(sys.argv) != 3:
        fail("usage: check_warmstart.py COLD_METRICS WARM_METRICS")
    cold_c, cold_ws, cold_s = load(sys.argv[1])
    warm_c, warm_ws, warm_s = load(sys.argv[2])

    if cold_c.get("plan_source") != "search":
        fail(f"cold compile plan_source={cold_c.get('plan_source')!r}, "
             f"expected 'search' (pass search flags to the smoke)")
    if not any(w.get("plan") == "miss" for w in cold_ws):
        fail("cold run recorded no warmstart miss event")
    if warm_c.get("plan_source") != "cache":
        fail(f"warm compile plan_source={warm_c.get('plan_source')!r}, "
             f"expected 'cache' — the plan cache did not hit")
    if not any(w.get("plan") == "hit" and w.get("source") == "cache"
               for w in warm_ws):
        fail("warm run recorded no warmstart cache-hit event")

    cold_t, warm_t = cold_c["duration_s"], warm_c["duration_s"]
    if not warm_t < cold_t:
        fail(f"warm compile not faster: cold={cold_t:.3f}s "
             f"warm={warm_t:.3f}s")

    ttfs = {}
    for tag, s in (("cold", cold_s), ("warm", warm_s)):
        if s is None or "time_to_first_step_s" not in s:
            fail(f"{tag} summary missing time_to_first_step_s")
        ttfs[tag] = s["time_to_first_step_s"]
    if not ttfs["warm"] < ttfs["cold"]:
        fail(f"warm time-to-first-step not smaller: {ttfs}")

    print(f"check_warmstart: OK — compile {cold_t:.3f}s → {warm_t:.3f}s "
          f"({cold_t / max(warm_t, 1e-9):.1f}x), time-to-first-step "
          f"{ttfs['cold']:.3f}s → {ttfs['warm']:.3f}s, plan_source "
          f"search → cache")


if __name__ == "__main__":
    main()
