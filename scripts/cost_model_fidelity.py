"""Cost-model fidelity harness: composed prediction vs measured step time.

The reference earns trust in its simulator by MEASURING every op on the
real device inside the search (Simulator::measure_operator_cost,
src/runtime/model.cu:38-75, consumed by graph.cc:1586-1735). This repo
calibrates the dominant ops the same way — but a calibrated op model still
has to COMPOSE into an accurate whole-step prediction (makespan over the
task graph + collective pricing). This harness validates exactly that:

for a battery of single-chip configs (hidden/seq/batch/attention-impl/MoE/
MLP), it
  1. measures the real training-step time with the dispatch-immune jitted
     lax.scan loop (bench.py's measurement methodology),
  2. predicts the step time with the analytic cost model (fixed-mfu
     roofline) and again with on-device calibration
     (CostModel.calibrate_graph),
and emits a JSON artifact with per-config errors and the Spearman rank
correlation between predicted and measured — the search only needs
*ranking* fidelity to pick the right plan, so rank correlation is the
headline number, and calibration must demonstrably shrink the error.

Run on the real chip:  python scripts/cost_model_fidelity.py [out.json]
CI (CPU mesh) asserts rank correlation via tests/test_fidelity.py.
"""

from __future__ import annotations

import json
import os
import sys
import time

# runnable as `python scripts/cost_model_fidelity.py` (script dir, not the
# repo root, lands on sys.path)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _lm(name, hidden, heads, layers, seq, batch, impl, vocab=8192):
    def make():
        import numpy as np

        from flexflow_tpu import FFConfig, FFModel
        from flexflow_tpu.models import (
            TransformerLMConfig,
            build_transformer_lm,
        )

        sys.argv = [sys.argv[0]]
        config = FFConfig()
        config.batch_size = batch
        ff = FFModel(config)
        c = TransformerLMConfig(vocab_size=vocab, hidden_size=hidden,
                                num_heads=heads, num_layers=layers,
                                sequence_length=seq, attention_impl=impl)
        build_transformer_lm(ff, c, batch_size=batch)
        rs = np.random.RandomState(0)
        feeds = {
            "tokens": rs.randint(0, vocab, (batch, seq)).astype(np.int32),
            "positions": np.tile(np.arange(seq, dtype=np.int32),
                                 (batch, 1)),
        }
        labels = rs.randint(0, vocab, (batch, seq, 1)).astype(np.int32)
        return ff, feeds, labels

    return {"name": name, "make": make}


def _mlp(name, batch, in_dim, hidden):
    def make():
        import numpy as np

        from flexflow_tpu import FFConfig, FFModel
        from flexflow_tpu.models import build_mlp_unify

        sys.argv = [sys.argv[0]]
        config = FFConfig()
        config.batch_size = batch
        ff = FFModel(config)
        build_mlp_unify(ff, batch_size=batch, in_dim=in_dim,
                        hidden_dims=(hidden,) * 4)
        rs = np.random.RandomState(0)
        feeds = {
            "input1": rs.randn(batch, in_dim).astype(np.float32),
            "input2": rs.randn(batch, in_dim).astype(np.float32),
        }
        labels = rs.randint(0, hidden, (batch, 1)).astype(np.int32)
        return ff, feeds, labels

    return {"name": name, "make": make}


def _moe(name, batch, fused=True):
    def make():
        import numpy as np

        from flexflow_tpu import FFConfig, FFModel
        from flexflow_tpu.models import MoeConfig, build_moe

        sys.argv = [sys.argv[0]]
        config = FFConfig()
        config.batch_size = batch
        ff = FFModel(config)
        c = MoeConfig()
        build_moe(ff, c, batch_size=batch, fused=fused)
        rs = np.random.RandomState(0)
        feeds = {"input": rs.randn(batch, c.in_dim).astype(np.float32)}
        labels = rs.randint(0, c.num_classes, (batch, 1)).astype(np.int32)
        return ff, feeds, labels

    return {"name": name, "make": make}


def tpu_configs():
    """10 single-chip configs varying hidden / seq / batch / attention
    impl / model family (the VERDICT battery). Bounded by calibration
    compile time: each distinct op key costs two jitted-loop compiles
    through the tunneled backend (~30-60 s each); the calibration cache is
    shared across configs (same-shape ops measure once)."""
    return [
        _lm("lm_h512_s512_b8_xla", 512, 8, 6, 512, 8, "xla"),
        _lm("lm_h1024_s128_b8_xla", 1024, 16, 6, 128, 8, "xla"),
        _lm("lm_h1024_s512_b8_flash", 1024, 16, 6, 512, 8, "flash"),
        _lm("lm_h1024_s512_b4_flash", 1024, 16, 6, 512, 4, "flash"),
        _lm("lm_h1024_s512_b16_flash", 1024, 16, 6, 512, 16, "flash"),
        _lm("lm_flagship12_flash", 1024, 16, 12, 512, 8, "flash",
            vocab=32000),
        _lm("lm_h2048_s256_b8_flash", 2048, 16, 4, 256, 8, "flash"),
        _mlp("mlp_unify_b256_h8192", 256, 1024, 8192),
        _mlp("mlp_unify_b64_h4096", 64, 1024, 4096),
        _moe("moe_flat_b256_fused", 256, fused=True),
    ]


def cpu_configs():
    """Small, strongly size-separated battery for the CPU-mesh CI test."""
    return [
        _lm("lm_h64_s32_b4", 64, 4, 2, 32, 4, "xla", vocab=256),
        _lm("lm_h128_s64_b4", 128, 4, 2, 64, 4, "xla", vocab=256),
        _lm("lm_h256_s64_b8", 256, 4, 4, 64, 8, "xla", vocab=256),
        _mlp("mlp_b16_h256", 16, 128, 256),
        _mlp("mlp_b64_h1024", 64, 256, 1024),
    ]


def measure_step_time(ff, feeds, labels, steps=10,
                      floor_s: float = 0.0) -> float:
    """Measured seconds/step by the relay-immune two-point methodology
    (see CostModel.calibrate's docstring and scripts/debug_calibrate.py:
    through the tunneled backend, block_until_ready does not reliably
    synchronize and a device_get fetch costs a large constant): one jitted
    fori_loop of train steps with a DYNAMIC trip count, synchronized by
    fetching the step counter, timed at n and 3n — the slope is the true
    per-step time with all constant overheads cancelled. Readings below
    `floor_s` (a roofline-derived physical bound) are retried as flukes."""
    import statistics

    import jax
    import jax.numpy as jnp

    from flexflow_tpu import LossType, SGDOptimizer

    if not getattr(ff, "_compiled", False):
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    step_fn = ff.executor.build_train_step()
    batch_data = ff._make_batch(feeds, labels)
    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    rng = jax.random.key(0)

    @jax.jit
    def loop(st, r, batch, n):
        def body(_, carry):
            st, r = carry
            r, sub = jax.random.split(r)
            out = step_fn(*st, sub, batch)
            return (out[:5], r)

        return jax.lax.fori_loop(0, n, body, (st, r))

    def sync(st):
        return int(jax.device_get(st[3]))  # fetching forces completion

    st, rng = loop(state, rng, batch_data, jnp.int32(steps))
    sync(st)  # compile + warm

    def t_of(n):
        nonlocal st, rng
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            st, rng = loop(st, rng, batch_data, jnp.int32(n))
            sync(st)
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    for _ in range(4):
        t1 = t_of(steps)
        t2 = t_of(3 * steps)
        per_step = (t2 - t1) / (2 * steps)
        if per_step >= floor_s:
            return per_step
    raise RuntimeError(
        f"step-time slope repeatedly below the physical floor "
        f"{floor_s * 1e3:.3f} ms — backend measurement flukes")


def predict_step_time(ff, calibrate_top_k: int = 0,
                      calibration_cache: dict | None = None) -> float:
    """Predicted seconds/step: the composed makespan of the compiled PCG
    under the machine model (evaluate_graph — compute roofline + collective
    classification + task-graph critical path). calibrate_top_k > 0 first
    measures the K dominant distinct ops on the local device
    (measure_operator_cost analog) and predicts from those;
    `calibration_cache` shares measurements across configs (the cache is
    keyed by op params + unsharded input shapes, so it is config-safe)."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import evaluate_graph

    cm = CostModel(machine_model_for_mesh(ff.mesh))
    if calibration_cache is not None:
        cm._calibration = calibration_cache
    if calibrate_top_k:
        cm.calibrate_graph(ff.graph, top_k=calibrate_top_k)
    t, _ = evaluate_graph(ff.graph, ff.mesh, cm)
    return t


def _spearman(xs, ys) -> float:
    import numpy as np

    def ranks(v):
        v = np.asarray(v, dtype=float)
        order = np.argsort(v)
        r = np.empty(len(v))
        r[order] = np.arange(len(v), dtype=float)
        for val in np.unique(v):  # ties share the average rank
            mask = v == val
            r[mask] = r[mask].mean()
        return r

    rx, ry = ranks(np.asarray(xs)), ranks(np.asarray(ys))
    if rx.std() == 0 or ry.std() == 0:
        return 0.0
    return float(np.corrcoef(rx, ry)[0, 1])


def run_fidelity(configs, steps=10, calibrate_top_k=6,
                 partial_path: str | None = None) -> dict:
    import jax

    from flexflow_tpu import LossType, SGDOptimizer

    on_tpu = jax.devices()[0].platform == "tpu"
    cal_cache: dict = {}  # shared across configs (keyed by op + shapes)
    rows = []
    for spec in configs:
        ff, feeds, labels = spec["make"]()
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        pred_raw = predict_step_time(ff)
        # the roofline composed prediction is a (loose) physical lower
        # bound: a tenth of it floors the fluke filter on the real chip
        floor = 0.1 * pred_raw if on_tpu else 0.0
        measured = measure_step_time(ff, feeds, labels, steps=steps,
                                     floor_s=floor)
        pred_cal = predict_step_time(ff, calibrate_top_k=calibrate_top_k,
                                     calibration_cache=cal_cache)
        rows.append({
            "name": spec["name"],
            "measured_ms": round(measured * 1e3, 4),
            "predicted_ms": round(pred_raw * 1e3, 4),
            "predicted_calibrated_ms": round(pred_cal * 1e3, 4),
            "rel_err": round(pred_raw / measured - 1.0, 4),
            "rel_err_calibrated": round(pred_cal / measured - 1.0, 4),
        })
        print(f"fidelity: {rows[-1]}", flush=True)
        if partial_path:  # survive a timeout with partial evidence
            with open(partial_path, "w") as f:
                json.dump({"partial": True, "configs": rows}, f, indent=1)
    measured = [r["measured_ms"] for r in rows]
    raw = [r["predicted_ms"] for r in rows]
    cal = [r["predicted_calibrated_ms"] for r in rows]

    def mare(pred):
        return round(sum(abs(p / m - 1.0) for p, m in zip(pred, measured))
                     / len(measured), 4)

    return {
        "device": str(jax.devices()[0]),
        "n_configs": len(rows),
        "configs": rows,
        "spearman": _spearman(raw, measured),
        "spearman_calibrated": _spearman(cal, measured),
        "mean_abs_rel_err": mare(raw),
        "mean_abs_rel_err_calibrated": mare(cal),
    }


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "FIDELITY_r05.json"
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    report = run_fidelity(tpu_configs() if on_tpu else cpu_configs(),
                          steps=10 if on_tpu else 3,
                          calibrate_top_k=4 if on_tpu else 4,
                          partial_path=out_path + ".partial")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items() if k != "configs"},
                     indent=1))
    for r in report["configs"]:
        print(f"  {r['name']:28s} measured {r['measured_ms']:9.3f} ms  "
              f"raw {r['predicted_ms']:9.3f} ({r['rel_err']:+.0%})  "
              f"cal {r['predicted_calibrated_ms']:9.3f} "
              f"({r['rel_err_calibrated']:+.0%})")


if __name__ == "__main__":
    main()
