"""Micro-debug for CostModel.calibrate on the real chip: measure ONE known
op (a 512→2048 Linear at batch 8·512 rows) three ways —
  1. calibrate()'s scan-looped timing (what the fidelity harness uses),
  2. a hand-rolled jitted lax.scan over the same op (ground truth
     methodology, mirrors bench.py),
  3. the analytic roofline —
to localize where the composed calibrated prediction inflates."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    sys.argv = [sys.argv[0]]
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.search.cost_model import CostModel, _op_harness
    from flexflow_tpu.search.machine_model import machine_model_for_mesh

    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    x = ff.create_tensor((4096, 512), name="x")
    ff.dense(x, 2048, ActiMode.AC_MODE_NONE, name="fc")
    sys.path.insert(0, "/root/repo/tests")
    from test_joint_search import _pcg_of

    g = _pcg_of(ff)
    node = next(n for n in g.topo_order() if n.name == "fc")

    mm = machine_model_for_mesh({"data": 1})
    cm = CostModel(mm)
    fn, args = _op_harness(node)

    t0 = time.perf_counter()
    fwd, bwd = cm.calibrate(node, fn, args)
    print(f"calibrate: fwd={fwd*1e3:.4f} ms bwd={bwd*1e3:.4f} ms "
          f"(wall incl. compiles {time.perf_counter()-t0:.1f}s)")

    # ground truth: same op, explicit scan, input threaded through carry
    w = jnp.asarray(np.random.RandomState(0).randn(512, 2048), jnp.float32)
    b = jnp.zeros((2048,), jnp.float32)
    xin = jnp.asarray(np.random.RandomState(1).randn(4096, 512), jnp.float32)

    def body(carry, _):
        y = (xin + carry * 1e-30) @ w + b
        return carry + y.ravel()[0], None

    @jax.jit
    def loop():
        s, _ = jax.lax.scan(body, jnp.float32(0), None, length=16)
        return s

    jax.block_until_ready(loop())
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(loop())
        ts.append((time.perf_counter() - t0) / 16)
    print(f"hand-rolled scan fwd: {sorted(ts)[1]*1e3:.4f} ms/rep")

    flops = 2 * 4096 * 512 * 2048
    print(f"roofline (mfu 0.4): {flops/0.4/mm.chip.peak_flops*1e3:.4f} ms; "
          f"bytes bound: {(4096*512+512*2048+4096*2048)*4/mm.chip.hbm_bandwidth*1e3:.4f} ms")


if __name__ == "__main__":
    main()
