"""Disaggregated-serving smoke: unified vs split pools on the CPU mesh
— the CI gate for serving/disagg.py + the radix prefix cache
(docs/serving.md, "Disaggregated serving").

Runs a small Transformer LM on the virtual 8-device mesh and asserts

  - `serve(disaggregate=True)` compiles TWO decode plans on DISJOINT
    device windows (prefill + decode sub-meshes partition the 8 chips)
    and completes a bursty shared-prefix trace with token streams
    BIT-IDENTICAL to the unified engine at equal total chips;
  - every KV handoff in the strategy report references a VERIFIED
    fftrans transfer program (zero analysis errors, host_hop
    collectives) whose predicted seconds reproduce from the program's
    own per-transfer entries (verify_transition_total), and carries a
    measured wall-clock next to the prediction;
  - the decode-side radix cache works ACROSS TIME: after a full drain
    (no live residents anywhere), re-admitting a served prompt is a
    cross_time hit whose handoff injects ZERO blocks;
  - the merged telemetry carries one serve.request per request, the
    serve.handoff event stream, and a drained snapshot with the radix
    gauges/counters;
  - `run_doctor --check` passes on the telemetry dir — the handoff
    makespan identity, the TTFT identity, and the histogram
    self-consistency all re-verify from the artifacts alone.

Usage:
  python scripts/disagg_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on the first broken invariant.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

SYSTEM_PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # the shared prefix
NUM_REQUESTS = 6


def fail(msg: str):
    print(f"disagg_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.analysis.transition import verify_transition_total
    from flexflow_tpu.models import (TransformerLMConfig,
                                     build_transformer_lm)
    from flexflow_tpu.telemetry import read_jsonl

    config = FFConfig()
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    tdir = config.telemetry_dir
    lm = TransformerLMConfig(vocab_size=128, hidden_size=32, num_heads=4,
                             num_layers=2, sequence_length=32,
                             attention_impl="xla")
    config.only_data_parallel = True
    config.batch_size = 8
    config.diagnostics = True
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # bursty shared-prefix trace: every prompt opens with the system
    # prompt (the workload radix caching exists for)
    rs = np.random.RandomState(7)
    prompts = [SYSTEM_PROMPT
               + rs.randint(1, lm.vocab_size, rs.randint(1, 6)).tolist()
               for _ in range(NUM_REQUESTS)]
    # 4-token blocks: the 10-token system prompt spans 2 FULL blocks +
    # a shared partial, so handoffs exercise multi-block programs and
    # partial-prefix landings rather than one-block degenerate extents
    serve_kw = dict(slots=4, max_new_tokens=8, prefill_chunk=4,
                    kv_block_size=4)

    unified = ff.serve(**serve_kw)
    want = unified.generate(prompts)

    dis = ff.serve(disaggregate=True, **serve_kw)
    if dis.prefill_chips + dis.decode_chips != 8:
        fail(f"sub-meshes do not partition the 8 chips "
             f"({dis.prefill_chips}+{dis.decode_chips})")
    pre_devs = {d.id for d in dis.prefill.decode_model.mesh.devices.flat}
    dec_devs = {d.id for d in dis.decode.decode_model.mesh.devices.flat}
    if pre_devs & dec_devs:
        fail(f"prefill/decode device windows overlap: {pre_devs & dec_devs}")
    got = dis.generate(prompts)
    if got != want:
        fail(f"disaggregated token streams diverge from unified:\n"
             f"  unified {want}\n  disagg  {got}")
    print(f"disagg_smoke: {NUM_REQUESTS} requests bit-identical across "
          f"{dis.prefill_chips}p+{dis.decode_chips}d chips")

    # ---- every handoff references a verified, reproducible program
    sec = dis.disagg_section()
    if sec["summary"]["count"] < 1:
        fail("no handoffs recorded")
    injected = [h for h in sec["handoffs"] if h["injected_blocks"] > 0]
    if not injected:
        fail("every handoff claims a full cache hit — injection path "
             "never exercised")
    for i, h in enumerate(sec["handoffs"]):
        if h["injected_blocks"] == 0:
            continue
        prog = (sec["programs"] or {}).get(str(h["injected_blocks"]))
        if prog is None:
            fail(f"handoff {i} has no transfer program for its "
                 f"{h['injected_blocks']}-block extent")
        if (prog.get("analysis") or {}).get("errors", 0):
            fail(f"handoff program {h['injected_blocks']}: verification "
                 f"errors {prog['analysis']['errors']}")
        total = verify_transition_total(prog)
        if abs(total - prog["predicted_s"]) > 1e-9:
            fail(f"handoff program {h['injected_blocks']}: predicted_s "
                 f"{prog['predicted_s']} does not reproduce ({total})")
        if h["measured_s"] <= 0:
            fail(f"handoff {i} carries no measured seconds")
    if any(h["matched_prefix_len"] for h in sec["handoffs"][1:]) is False:
        fail("shared-prefix trace produced zero decode-side prefix hits")

    # ---- cross-time: full drain, then re-admit a served prompt
    if not (dis.drained and dis.prefill.scheduler.drained
            and dis.decode.scheduler.drained):
        fail("engine not drained after generate()")
    before = dis.decode.block_manager.stats.cross_time_hits
    rerun = dis.generate([prompts[0]])
    if rerun != [want[0]]:
        fail("re-admitted prompt decoded differently after the drain")
    if dis.decode.block_manager.stats.cross_time_hits <= before:
        fail("re-admission after a full drain missed the cross-time "
             "radix cache")
    last = dis.handoffs[-1]
    # the hot shared prefix survived the drain; the prompt's private
    # tail MAY have been LRU-evicted under the run's pool pressure, so
    # the bound here is strict-subset, not zero
    if not (last["matched_prefix_len"] > 0
            and last["injected_blocks"] < last["prompt_blocks"]):
        fail(f"cross-time re-admission injected the full extent "
             f"({last['injected_blocks']}/{last['prompt_blocks']} "
             f"blocks, matched {last['matched_prefix_len']})")
    # a SECOND re-admission finds the freshly re-published prompt with
    # no competing residents: the handoff must move ZERO blocks
    if dis.generate([prompts[0]]) != [want[0]]:
        fail("second re-admission decoded differently")
    if dis.handoffs[-1]["injected_blocks"] != 0:
        fail(f"second re-admission still injected "
             f"{dis.handoffs[-1]['injected_blocks']} block(s)")
    print("disagg_smoke: cross-time prefix hit after full drain "
          "(0-block handoff on the re-published prompt)")

    # ---- telemetry surface
    ff._telemetry.close()
    records = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    kinds = {}
    for r in records:
        kinds[r.get("kind")] = kinds.get(r.get("kind"), 0) + 1
    if kinds.get("serve.handoff", 0) != len(dis.handoffs):
        fail(f"serve.handoff events ({kinds.get('serve.handoff', 0)}) != "
             f"recorded handoffs ({len(dis.handoffs)})")
    rep = json.load(open(os.path.join(tdir, "strategy_report.json")))
    sd = rep.get("serving_disagg")
    if sd is None:
        fail("strategy_report.json has no serving_disagg section")
    if sd["summary"]["count"] != len(dis.handoffs):
        fail("report handoff count does not match the live engine")
    snaps = [r for r in records if r.get("kind") == "metrics_snapshot"
             and r.get("drained")]
    if not snaps:
        fail("no drained metrics snapshot")
    merged = snaps[-1].get("metrics", {})
    counters = merged.get("counters") or {}
    if not any(k.startswith("serve_prefix_cache_hits_total")
               for k in counters):
        fail("drained snapshot missing the radix hit counter")

    # ---- the doctor re-verifies everything from the artifacts alone
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "run_doctor.py"),
         tdir, "--check", "--out", os.path.join(tdir, "doctor.md")],
        capture_output=True, text=True)
    if r.returncode != 0:
        fail(f"run_doctor --check failed:\n{r.stderr}")
    doc = open(os.path.join(tdir, "doctor.md")).read()
    if "Disaggregated serving (KV handoff plane)" not in doc:
        fail("doctor report missing the disaggregated-serving section")
    if "Radix prefix cache" not in doc:
        fail("doctor report missing the radix prefix-cache section")
    print("disagg_smoke: run_doctor --check re-verified the handoff "
          "makespan identity from the report alone")
    print("disagg_smoke: OK")


if __name__ == "__main__":
    main()
