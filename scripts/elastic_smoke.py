"""ffelastic smoke: drift/capacity-triggered live re-planning on the CPU mesh.

The CI gate for the elastic controller (docs/elastic.md): one training run
on the virtual 8-device CPU mesh goes through BOTH trigger streams
in-process and every decision must land in the artifacts run_doctor
--check re-verifies:

  capacity leg — after one epoch on dp=4, the visible device set shrinks
  to 2 (injected visible_devices_fn). The controller force-replans onto
  the (2,1,1,1) mesh at the fit-entry capacity check (the whole next
  epoch runs on the new plan), the move goes through the verified
  fftrans/migrate_state path, and the continued trajectory is BIT-EXACT
  vs a checkpoint-restart control compiled from scratch at the target
  mesh — params, optimizer slots, step counter.

  drift leg — the monitor's prediction is perturbed to 1/50th of the
  plan's makespan (the injected-perturbation idiom: measured step times
  now read as a 50x excursion). The advisory stream must produce a
  payoff-gated re-plan decision labeled trigger=drift carrying BOTH
  sides of the inequality (lhs = predicted_migration_s x fidelity_ratio,
  rhs = benefit_s_per_step x horizon), recalibrate the cost model, and
  keep training.

Gates asserted here: plan_source "replan" with the origin preserved, the
elastic section in strategy_report.json reproducing each decision's
lhs/rhs from its recorded factors, `replan` telemetry events, exactly
one forced shrink decision, at least one drift decision, and the
bit-exact control comparison. CI then runs run_doctor --check on the
telemetry dir, which re-verifies the payoff identity + makespan identity
from the artifacts alone.

Usage: python scripts/elastic_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on any violated assertion.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"elastic_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _flat(tree):
    import jax.tree_util as jtu

    return {jtu.keystr(p): np.asarray(v)
            for p, v in jtu.tree_flatten_with_path(tree)[0]}


def _build(mesh, extra_argv, base_argv):
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    sys.argv = [sys.argv[0]] + list(base_argv) + list(extra_argv)
    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = 8
    ff = FFModel(config)
    x = ff.create_tensor((8, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05, momentum=0.9),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def main():
    from flexflow_tpu.telemetry import read_jsonl

    argv = sys.argv[1:]
    tdir = ""
    if "--telemetry-dir" in argv:
        tdir = argv[argv.index("--telemetry-dir") + 1]
    if not tdir:
        fail("pass --telemetry-dir")
    base = [a for i, a in enumerate(argv)
            if a not in ("--telemetry-dir", "--diagnostics")
            and (i == 0 or argv[i - 1] != "--telemetry-dir")]

    rs = np.random.RandomState(0)
    n = 48  # 6 steps per epoch at batch 8
    X = {"x": rs.randn(n, 16).astype(np.float32)}
    Y = rs.randint(0, 4, (n, 1)).astype(np.int32)

    def fit(ff, seed):
        sx = {"x": np.roll(X["x"], seed, axis=0)}
        sy = np.roll(Y, seed, axis=0)
        ff.fit(sx, sy, epochs=1, batch_size=8, shuffle=False,
               verbose=False)

    # ---------------------------------------------------- epoch 1 (dp=4)
    ff = _build((4, 1, 1, 1),
                ["--telemetry-dir", tdir, "--diagnostics"], base)
    fit(ff, 0)
    ckroot = tempfile.mkdtemp(prefix="elastic_smoke_ck_")
    ff.save_checkpoint(ckroot)

    # ------------------------------------------- capacity leg (4 -> 2)
    # devices "vanish": the controller must force-replan onto the
    # 2-device mesh at the fit-entry capacity check so the WHOLE next
    # epoch runs on the new plan (bit-exact comparable to the control).
    # The huge cooldown mutes the drift stream for this leg — a shrink
    # bypasses cooldown by design, nothing else triggers.
    ctrl = ff.enable_elastic(
        cooldown_steps=10_000, horizon_steps=1000,
        visible_devices_fn=lambda: jax.devices()[:2],
        capacity_check_every=1)
    fit(ff, 1)

    shrinks = [d for d in ctrl.decisions
               if d.get("trigger") == "capacity" and d.get("forced")]
    if len(shrinks) != 1:
        fail(f"expected exactly one forced shrink decision, got "
             f"{len(shrinks)}: {ctrl.decisions}")
    dec = shrinks[0]
    if dec.get("decision") != "migrated":
        fail(f"shrink did not migrate: {dec}")
    if "lhs_s" not in dec or "rhs_s" not in dec:
        fail(f"forced decision dropped the payoff audit trail: {dec}")
    if dict(ff.mesh.shape).get("data") != 2:
        fail(f"post-shrink mesh is not data=2: {dict(ff.mesh.shape)}")
    if ff._plan_source != "replan":
        fail(f"plan_source is {ff._plan_source!r}, want 'replan'")
    if getattr(ff, "_plan_origin", None) is None:
        fail("replan did not preserve the underlying plan origin")

    # bit-exact vs checkpoint-restart control at the same target mesh
    control = _build((2, 1, 1, 1), [], base)
    control.load_checkpoint(ckroot)
    fit(control, 1)
    for name, a, b in (("params", control._params, ff._params),
                       ("opt_slots", control._opt_slots, ff._opt_slots)):
        fa, fb = _flat(a), _flat(b)
        if fa.keys() != fb.keys():
            fail(f"{name} key sets differ after elastic shrink")
        for k in fa:
            if not np.array_equal(fa[k], fb[k]):
                fail(f"elastic {name}{k} != checkpoint-restart control")
    if int(ff._step) != int(control._step):
        fail(f"step counter {int(ff._step)} != control "
             f"{int(control._step)}")

    # --------------------------------------------------- drift leg
    # inject the perturbation: the monitor now believes the plan should
    # run 50x faster than it measures — a sustained excursion
    diag = ff.get_diagnostics()
    ctrl.cooldown_steps = 6
    ctrl.watcher._visible_fn = lambda: jax.devices()[:2]  # capacity quiet
    n_before = len(ctrl.decisions)
    diag.drift.set_prediction((ff._predicted_step_s or 1e-3) / 50)
    fit(ff, 2)
    fit(ff, 3)

    drifts = [d for d in ctrl.decisions[n_before:]
              if d.get("trigger") == "drift"]
    if not drifts:
        fail(f"no drift-triggered decision after the injected "
             f"perturbation: {ctrl.decisions[n_before:]}")
    d0 = drifts[0]
    for k in ("lhs_s", "rhs_s", "predicted_migration_s",
              "fidelity_ratio", "benefit_s_per_step", "horizon_steps",
              "research_s", "advisory"):
        if k not in d0:
            fail(f"drift decision missing {k}: {d0}")
    lat = int(d0["step"]) - int(d0["advisory"]["step"])
    if lat < 0:
        fail(f"decision step precedes its advisory: {d0}")

    # ----------------------------------------- artifacts + identities
    report_path = os.path.join(tdir, "strategy_report.json")
    if not os.path.exists(report_path):
        fail(f"missing strategy report {report_path}")
    with open(report_path) as f:
        report = json.load(f)
    if report.get("plan_source") != "replan":
        fail(f"report plan_source {report.get('plan_source')!r}")
    elastic = report.get("elastic") or {}
    decs = elastic.get("decisions", [])
    if len(decs) != len(ctrl.decisions):
        fail(f"report carries {len(decs)} decisions, controller made "
             f"{len(ctrl.decisions)}")
    # every priced decision reproduces from the record alone — the same
    # identity run_doctor --check re-runs on the uploaded artifact
    for i, d in enumerate(decs):
        if d.get("lhs_s") is None:
            continue
        lhs = d["predicted_migration_s"] * d["fidelity_ratio"]
        rhs = d["benefit_s_per_step"] * d["horizon_steps"]
        for name, got, want in (("lhs_s", d["lhs_s"], lhs),
                                ("rhs_s", d["rhs_s"], rhs)):
            if abs(got - want) > 1e-9 + 1e-6 * abs(want):
                fail(f"decision {i}: {name}={got} does not reproduce "
                     f"from its factors ({want})")

    ff._telemetry.flush()
    recs = list(read_jsonl(os.path.join(tdir, "metrics.jsonl")))
    replans = [r for r in recs if r.get("kind") == "replan"]
    if len(replans) < len(ctrl.decisions):
        fail(f"{len(replans)} replan telemetry events for "
             f"{len(ctrl.decisions)} decisions")
    migrates = [r for r in recs if r.get("kind") == "migrate"]
    if not migrates:
        fail("no migrate event — the elastic moves left no trace")

    mig_pred = dec.get("predicted_migration_s")
    mig_meas = dec.get("migration_measured_s")
    print(f"elastic_smoke: OK — {len(ctrl.decisions)} decision(s): "
          f"1 forced capacity shrink 4->2 (migration predicted "
          f"{(mig_pred or 0) * 1e3:.3f} ms / measured "
          f"{(mig_meas or 0) * 1e3:.1f} ms), {len(drifts)} drift "
          f"re-plan(s) (trigger latency {lat} step(s), re-search "
          f"{d0['research_s']:.2f} s), bit-exact vs checkpoint-restart "
          f"control incl. the continued epoch, payoff identity "
          f"reproduces from the report alone")


if __name__ == "__main__":
    main()
