"""ffcheck: standalone static plan verification — the CI gate.

Three jobs (docs/analysis.md):

1. **Six-source sweep** (`--sources all`, the default): compile one
   small transformer LM through every plan-adoption path — search,
   cache, checkpoint, import, manual, default — and assert the ffcheck
   compile gate ran on each with ZERO errors. This pins the acceptance
   property "ffcheck runs on all six plan sources at compile time".

2. **Corruption self-test** (`--self-test`, also on by default): the
   plan-mutation fuzzer's corruption matrix, run end-to-end through the
   pass pipeline — inject each class into a real searched plan
   (axis reuse, dropped parallel op → implicit reshard, oversharded
   dim, non-bijective ring permutation, donated-then-reused buffer,
   coordinator-only collective) and assert the verifier reports exactly
   that finding class. The ffsan classes ride the same matrix:
   dtype mismatch across a parallel edge, fp32-master bypass,
   low-precision accumulation (graph- and source-level), and a
   host-divergent branch feeding traced code.

3. **Smoke suites** (`--suite longcontext`, `--suite wus`): compile the
   long-context ring plan and the memory-constrained weight-update-
   sharding plan (the same configs the dedicated CI smokes run) and
   assert they verify clean — the ring bijection check really sees the
   sp plan's rings, and the two-keyed OOM rule does NOT fire on a plan
   the update-sharding decision made fit.

Writes a machine-readable report with `--report OUT.json` (uploaded as a
CI artifact). Exits nonzero on any violated assertion.

Usage: python scripts/ffcheck.py [--report OUT.json]
       [--sources all|s1,s2,...] [--self-test] [--no-self-test]
       [--suite longcontext] [--suite wus]
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

ALL_SOURCES = ("search", "cache", "checkpoint", "import", "manual",
               "default")

# progressive report state: fail() flushes whatever has been collected
# so far, so the CI artifact exists (with the failure recorded) for RED
# runs too — that is when a machine-readable report matters most
_REPORT: dict = {"kind": "ffcheck_report", "ok": False}
_REPORT_PATH = ""


def _write_report():
    if not _REPORT_PATH:
        return
    d = os.path.dirname(os.path.abspath(_REPORT_PATH))
    os.makedirs(d, exist_ok=True)
    with open(_REPORT_PATH, "w") as f:
        json.dump(_REPORT, f, indent=1)
    print(f"ffcheck: report written to {_REPORT_PATH}")


def fail(msg: str):
    print(f"ffcheck: FAIL: {msg}", file=sys.stderr)
    _REPORT["failure"] = msg
    _write_report()
    sys.exit(1)


def _lm(config, seq=16, ring=False):
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=2, num_layers=1,
        sequence_length=seq,
        attention_impl="ring" if ring else "xla")
    build_transformer_lm(ff, cfg, batch_size=4)
    return ff, cfg


def _config(**kw):
    from flexflow_tpu import FFConfig

    cfg = FFConfig()
    cfg.mesh_axis_sizes = (2, 4, 1, 1)
    cfg.batch_size = 4
    cfg.search_budget = 6
    cfg.enable_parameter_parallel = True
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _compile(ff):
    from flexflow_tpu import LossType, SGDOptimizer

    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _check_clean(ff, source: str) -> dict:
    res = getattr(ff, "_analysis", None)
    if res is None:
        fail(f"source {source}: compile gate did not run "
             f"(model._analysis is None)")
    if ff._plan_source != source:
        fail(f"expected plan_source {source!r}, got "
             f"{ff._plan_source!r}")
    errs = res.errors()
    if errs:
        fail(f"source {source}: plan verification errors: "
             f"{[str(f) for f in errs[:5]]}")
    missing = {"dtype_flow", "spmd_uniformity"} - set(res.passes_run)
    if missing:
        fail(f"source {source}: ffsan passes did not run: "
             f"{sorted(missing)}")
    print(f"ffcheck: source {source:10s} — clean "
          f"({len(res.findings)} finding(s), "
          f"{res.elapsed_s * 1e3:.0f} ms)")
    entry = {"plan_source": source, **res.summary(),
             "elapsed_s": res.elapsed_s}
    _REPORT.setdefault("sources", []).append(entry)
    return entry


def run_sources(workdir: str, sources) -> list[dict]:
    from flexflow_tpu.parallel.strategies import (
        Strategy,
        megatron_transformer,
    )

    out = []
    plan_path = os.path.join(workdir, "plan.json")

    if "search" in sources or "import" in sources:
        ff = _compile(_lm(_config())[0])
        if "search" in sources:
            out.append(_check_clean(ff, "search"))
        Strategy(ff._strategy or {}).save(plan_path)

    if "cache" in sources:
        ws = os.path.join(workdir, "warmstart")
        _compile(_lm(_config(warmstart_dir=ws))[0])  # cold: populates
        ff = _compile(_lm(_config(warmstart_dir=ws))[0])  # warm: hit
        out.append(_check_clean(ff, "cache"))

    if "checkpoint" in sources:
        ck = os.path.join(workdir, "ckpt")
        ff, cfg = _lm(_config(checkpoint_dir=ck, checkpoint_every=1,
                              auto_resume=True))
        _compile(ff)
        rs = np.random.RandomState(0)
        n = 8
        X = {"tokens": rs.randint(
                0, cfg.vocab_size,
                (n, cfg.sequence_length)).astype(np.int32),
             "positions": np.tile(np.arange(cfg.sequence_length,
                                            dtype=np.int32), (n, 1))}
        Y = rs.randint(0, cfg.vocab_size,
                       (n, cfg.sequence_length, 1)).astype(np.int32)
        ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False,
               verbose=False)
        ff2 = _compile(_lm(_config(checkpoint_dir=ck, checkpoint_every=1,
                                   auto_resume=True))[0])
        out.append(_check_clean(ff2, "checkpoint"))

    if "import" in sources:
        ff = _compile(_lm(_config(import_strategy_file=plan_path))[0])
        out.append(_check_clean(ff, "import"))

    if "manual" in sources:
        ff, _ = _lm(_config(search_budget=0,
                            enable_parameter_parallel=False))
        ff.set_strategy(megatron_transformer(ff))
        _compile(ff)
        out.append(_check_clean(ff, "manual"))

    if "default" in sources:
        ff = _compile(_lm(_config(search_budget=0,
                                  enable_parameter_parallel=False))[0])
        out.append(_check_clean(ff, "default"))
    return out


# ---------------------------------------------------------------- fuzzer

_DONATED_SNIPPET = """
def fit_loop(self, batch):
    new = step_fn(self._params, self._state, self._opt_slots,
                  self._step, self._counters, rng, batch)
    loss = float(self._params["head"]["kernel"].sum())
    return new, loss
"""

_COORD_SNIPPET = """
def save_plan(payload):
    from flexflow_tpu.distributed import barrier, is_coordinator
    if is_coordinator():
        write(payload)
        barrier("plan-committed")
"""

_LP_ACCUM_SNIPPET = """
def bad_loss(logits):
    import jax.numpy as jnp
    return jnp.sum(logits.astype(jnp.bfloat16)) / logits.shape[0]
"""

_DIVERGENT_SNIPPET = """
def maybe_recompile(model, fn):
    import time
    if time.perf_counter() - model.t0 > 60.0:
        return jit(fn)
    return fn
"""


def run_self_test(workdir: str) -> list[dict]:
    """Inject each corruption class into a real plan / source snippet and
    assert the verifier reports exactly that class."""
    from flexflow_tpu.analysis import (
        context_for_model,
        lint,
        run_analysis,
    )
    from flexflow_tpu.analysis.sharding import _LAYOUT_PRESERVING
    from flexflow_tpu.parallel import ops as par_ops
    from flexflow_tpu.parallel.strategies import (
        sequence_parallel_attention,
    )

    results = []

    def check(klass: str, codes, expect: str):
        if expect not in codes:
            fail(f"self-test {klass}: expected finding {expect!r}, "
                 f"got {sorted(set(codes))}")
        print(f"ffcheck: self-test {klass:22s} — caught ({expect})")
        results.append({"class": klass, "finding": expect})
        _REPORT.setdefault("self_test", []).append(
            {"class": klass, "finding": expect})

    ff = _compile(_lm(_config())[0])
    ctx = context_for_model(ff)
    clean = run_analysis(ff.graph, ff.mesh, ctx)
    if clean.errors():
        fail(f"self-test baseline not clean: "
             f"{[str(f) for f in clean.errors()]}")

    def mutate(node_pred, new_assign_fn, expect, klass):
        node = next(n for n in ff.graph.topo_order() if node_pred(n))
        pt = node.outputs[0]
        saved = pt.axis_assignment
        pt.axis_assignment = new_assign_fn(pt)
        try:
            res = run_analysis(ff.graph, ff.mesh, ctx)
        finally:
            pt.axis_assignment = saved
        check(klass, [f.code for f in res.findings], expect)

    # 1) axis reuse: same mesh axis on two dims of one assignment
    mutate(lambda n: len(n.outputs) > 0 and len(n.outputs[0].shape.dims) >= 2,
           lambda pt: (("data",), ("data",))
           + tuple(() for _ in pt.shape.dims[2:]),
           "axis_reuse", "axis_reuse")

    # 2) dropped parallel op: a layout-preserving consumer loses its
    # producer's sharding — the reshard GSPMD inserts is implicit now
    def _ew_with_sharded_producer(n):
        if n.op_type not in _LAYOUT_PRESERVING or not n.inputs:
            return False
        return any(a for a in n.inputs[0].axis_assignment)

    mutate(_ew_with_sharded_producer,
           lambda pt: tuple(() for _ in pt.shape.dims),
           "implicit_reshard", "dropped_parallel_op")

    # 3) oversharded dim: more shards than elements
    mutate(lambda n: (len(n.outputs) > 0
                      and not n.outputs[0].shape.dims[0].is_replica_dim
                      and n.outputs[0].shape.dims[0].size < 8),
           lambda pt: (("data", "model"),)
           + tuple(() for _ in pt.shape.dims[1:]),
           "overshard", "oversharded_dim")

    # 4) non-bijective ring permutation: corrupt the ONE shared schedule
    # builder every ring body uses, on a plan that actually runs a ring
    ring_cfg = _config(search_budget=0, enable_parameter_parallel=False)
    ring_cfg.mesh_axis_sizes = (2, 1, 1, 2)
    ring_ff, _ = _lm(ring_cfg, seq=16, ring=True)
    ring_ff.set_strategy(sequence_parallel_attention(ring_ff))
    _compile(ring_ff)
    rctx = context_for_model(ring_ff)
    good = par_ops.ring_permutation
    par_ops.ring_permutation = lambda n: good(n)[:-1]  # drop a pair
    try:
        res = run_analysis(ring_ff.graph, ring_ff.mesh, rctx)
    finally:
        par_ops.ring_permutation = good
    check("non_bijective_permutation",
          [f.code for f in res.findings], "bad_permutation")

    # 5) donated-then-reused buffer (source-level)
    codes = [f.code for f in lint.lint_source(
        _DONATED_SNIPPET, "snippet.py", select=("donated_reuse",))]
    check("donated_then_reused", codes, "donated_reuse")

    # 6) coordinator-only collective (source-level)
    codes = [f.code for f in lint.lint_source(
        _COORD_SNIPPET, "snippet.py",
        select=("coordinator_collective",))]
    check("coordinator_collective", codes, "coordinator_collective")

    # --- ffsan classes (dtype-flow + SPMD uniformity) ---
    import dataclasses

    from flexflow_tpu.analysis import numerics

    # 7) dtype mismatch across a parallel edge: flip a Combine/
    # Repartition output dtype (synthesized mini-graph — a searched
    # plan need not contain explicit parallel ops)
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel.ops import CombineParams
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    def _pt(shape, dtype):
        return ParallelTensor(
            ParallelTensorShape.from_shape(shape, dtype))

    g2 = Graph()
    src = g2.add_node(OpNode(OT.OP_INPUT, None, name="x"))
    src.outputs = [_pt((8, 8), DataType.DT_BFLOAT16)]
    comb = g2.add_node(OpNode(OT.OP_COMBINE, CombineParams(0, 2),
                              name="combine"))
    comb.inputs = [src.outputs[0]]
    comb.outputs = [_pt((8, 8), DataType.DT_FLOAT)]
    g2.add_edge(src, comb)
    codes = [f.code for f in numerics.run(g2, ff.mesh, ctx)]
    check("parallel_dtype_mismatch", codes, "parallel_dtype_mismatch")

    # 8) fp32-master bypass: flip one trainable weight to bf16 under
    # the bf16 policy
    node = next(n for n in ff.graph.topo_order()
                if any(ws.trainable for ws in n.weight_specs))
    idx = next(i for i, ws in enumerate(node.weight_specs)
               if ws.trainable)
    saved_ws = node.weight_specs[idx]
    saved_cd = ff.config.computation_dtype
    node.weight_specs[idx] = dataclasses.replace(
        saved_ws, dtype=DataType.DT_BFLOAT16)
    ff.config.computation_dtype = DataType.DT_BFLOAT16
    try:
        res = run_analysis(ff.graph, ff.mesh, ctx)
    finally:
        node.weight_specs[idx] = saved_ws
        ff.config.computation_dtype = saved_cd
    check("master_bypass", [f.code for f in res.findings],
          "master_bypass")

    # 9) low-precision accumulation: a bf16 Reduce over 64k terms
    # (graph-level) and a bf16-pinned jnp.sum (source-level)
    g3 = Graph()
    src3 = g3.add_node(OpNode(OT.OP_INPUT, None, name="acts"))
    src3.outputs = [_pt((64, 1024), DataType.DT_BFLOAT16)]
    from flexflow_tpu.ops import ReduceParams

    red = g3.add_node(OpNode(
        OT.OP_REDUCE_SUM, ReduceParams(OT.OP_REDUCE_SUM, (0, 1)),
        name="big_sum"))
    red.inputs = [src3.outputs[0]]
    red.outputs = [_pt((1,), DataType.DT_BFLOAT16)]
    g3.add_edge(src3, red)
    codes = [f.code for f in numerics.run(g3, ff.mesh, ctx)]
    check("low_precision_accum_graph", codes, "low_precision_accum")
    codes = [f.code for f in lint.lint_source(
        _LP_ACCUM_SNIPPET, "snippet.py",
        select=("low_precision_accum",))]
    check("low_precision_accum_src", codes, "low_precision_accum")

    # 10) host-divergent branch feeding traced code (source-level)
    codes = [f.code for f in lint.lint_source(
        _DIVERGENT_SNIPPET, "snippet.py",
        select=("host_divergent_branch",))]
    check("host_divergent_branch", codes, "host_divergent_branch")

    # 11) SPMD fingerprint barrier catches a diverged fleet (simulated
    # second process via an injected broadcast channel)
    from flexflow_tpu.analysis import spmd

    verdict = spmd.fingerprint_barrier(
        ff, broadcast=lambda p: p)  # lockstep fleet: OK
    if verdict["status"] != "ok":
        fail(f"self-test fingerprint_barrier: lockstep verdict "
             f"{verdict!r}")
    try:
        spmd.fingerprint_barrier(
            ff, broadcast=lambda p: {"fingerprint": "divergent"})
    except spmd.SPMDDivergenceError:
        check("spmd_fingerprint_mismatch", ["spmd_divergence"],
              "spmd_divergence")
    else:
        fail("self-test fingerprint_barrier: divergent fleet "
             "not detected")
    return results


# ---------------------------------------------------------------- suites

def run_suite(name: str) -> dict:
    from flexflow_tpu import FFConfig

    if name == "longcontext":
        # the longcontext_smoke config: ring LM on a seq=4 mesh, search
        # on — the bijection check must see the sp plan's rings
        cfg = _config(search_budget=4, enable_parameter_parallel=False)
        cfg.mesh_axis_sizes = (1, 1, 1, 4)
        cfg.enable_sample_parallel = True
        cfg.batch_size = 2
        ff, _ = _lm(cfg, seq=256, ring=True)
        _compile(ff)
        res = ff._analysis
        if res is None or res.errors():
            fail(f"suite longcontext: verification errors "
                 f"{[str(f) for f in (res.errors() if res else [])]}")
        msgs = " ".join(f.message for f in res.findings)
        if "ring schedule" not in msgs and "ring attention" not in msgs:
            fail("suite longcontext: collective pass saw no ring "
                 "schedule in the sp plan")
    elif name == "wus":
        # the wus_smoke config: dp=4, HBM capped below the replicated
        # update — the auto-sharded plan must verify clean (the
        # two-keyed OOM rule must NOT fire on a plan the update-sharding
        # decision made fit)
        from flexflow_tpu import FFModel, LossType, SGDOptimizer
        from flexflow_tpu.models import (
            TransformerLMConfig,
            build_transformer_lm,
        )

        cfg = FFConfig()
        cfg.mesh_axis_sizes = (4, 1, 1, 1)
        cfg.batch_size = 4
        cfg.device_mem = 1.5 * 1024 * 1024
        ff = FFModel(cfg)
        c = TransformerLMConfig(vocab_size=128, hidden_size=64,
                                num_heads=2, num_layers=2,
                                sequence_length=32)
        build_transformer_lm(ff, c, batch_size=4)
        ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
                   loss_type=LossType
                   .LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        res = ff._analysis
        if res is None or res.errors():
            fail(f"suite wus: verification errors "
                 f"{[str(f) for f in (res.errors() if res else [])]}")
        if not (ff._update_sharding or {}).get("enabled"):
            fail("suite wus: update sharding not selected — the suite "
                 "no longer exercises the sharded-update memory path")
    else:
        fail(f"unknown suite {name!r} (have longcontext, wus)")
    print(f"ffcheck: suite {name} — clean")
    _REPORT.setdefault("suites", []).append({"suite": name, "ok": True})
    return {"suite": name, "ok": True}


def main():
    argv = sys.argv[1:]
    report_path = ""
    sources = list(ALL_SOURCES)
    self_test = True
    suites = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--report":
            i += 1
            report_path = argv[i]
        elif a == "--sources":
            i += 1
            sources = ([] if argv[i] == "none"
                       else list(ALL_SOURCES) if argv[i] == "all"
                       else [s.strip() for s in argv[i].split(",")])
            unknown = set(sources) - set(ALL_SOURCES)
            if unknown:
                fail(f"unknown sources {sorted(unknown)}")
        elif a == "--self-test":
            self_test = True
        elif a == "--no-self-test":
            self_test = False
        elif a == "--suite":
            i += 1
            suites.append(argv[i])
        elif a in ("-h", "--help"):
            print(__doc__)
            return
        else:
            fail(f"unknown flag {a!r}")
        i += 1
    sys.argv = [sys.argv[0]]  # FFConfig must not parse ffcheck's flags

    global _REPORT_PATH
    _REPORT_PATH = report_path
    workdir = tempfile.mkdtemp(prefix="ffcheck-")
    try:
        if sources:
            run_sources(workdir, sources)
        if self_test:
            run_self_test(workdir)
        if suites:
            for s in suites:
                run_suite(s)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    _REPORT["ok"] = True
    _write_report()
    print("ffcheck: OK")


if __name__ == "__main__":
    main()
