"""fflint: the repo-wide JAX-hazard lint (analysis/lint.py rules).

Rules — each encodes a bug class a past PR fixed by hand (docs/
analysis.md has the catalog):

  host_sync_in_loop        jax.device_get inside a for/while loop, not
                           behind a telemetry/diagnostics gate
  unsorted_dict_hash       dict iteration feeding a fingerprint/hash
                           without sorted(...)
  global_rng               process-global np.random.* / random.* calls
  time_in_trace            time.*/RNG calls inside a traced function
  coordinator_collective   a collective inside an is_coordinator() branch
  donated_reuse            donated step buffer read host-side after the
                           call without rebinding
  low_precision_accum      a summing reduction explicitly accumulating
                           in bf16/fp16 (f32-accumulate-then-downcast is
                           the codebase convention)
  host_divergent_branch    per-host-nondeterministic branch (time/RNG/
                           env/hostname) guarding a collective or a
                           trace entry — the r13 divergence class
  unverified_transition    a state re-placement applier
                           (place_update_sharded / place_like /
                           restore_tree) in a function that never
                           consults the fftrans transition checker
  unverified_rule_load     a GraphXfer construct/load call
                           (load_rule_collection sans config=,
                           compile_pattern_rule,
                           generate_all_pcg_xfers) in a function that
                           never consults the ffrules verifier

Suppression: trailing `# fflint: ok [codes]` on the line or its `def`.

Usage: python scripts/fflint.py [paths...] [--select r1,r2]
Default paths: flexflow_tpu/ scripts/ bench.py (tests are exempt — they
synthesize hazards on purpose). Exits 1 on ANY finding: CI runs this
with the repo required clean.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_PATHS = ("flexflow_tpu", "scripts", "bench.py")


def main() -> int:
    from flexflow_tpu.analysis.lint import ALL_RULES, lint_paths

    argv = sys.argv[1:]
    select = None
    paths = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--select":
            i += 1
            select = tuple(r.strip() for r in argv[i].split(",")
                           if r.strip())
            unknown = set(select) - set(ALL_RULES)
            if unknown:
                print(f"fflint: unknown rule(s) {sorted(unknown)} "
                      f"(have {ALL_RULES})", file=sys.stderr)
                return 2
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            paths.append(a)
        i += 1

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not paths:
        paths = [os.path.join(root, p) for p in DEFAULT_PATHS]
    paths = [p for p in paths if os.path.exists(p)]

    findings = lint_paths(paths, select=select)
    for f in findings:
        print(f)
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    if findings:
        print(f"fflint: {n_err} error(s), {n_warn} warning(s) — "
              f"fix or suppress with '# fflint: ok <rule>'",
              file=sys.stderr)
        return 1
    print(f"fflint: clean ({len(paths)} path(s), rules: "
          f"{', '.join(select or ALL_RULES)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
