"""ffrules: standalone substitution-rule verification — the CI gate.

Three jobs (docs/analysis.md "ffrules"):

1. **Registry sweep** (default): generate the FULL built-in rule set for
   the CI mesh config (`generate_all_pcg_xfers` on a data=2 x model=4
   mesh, plus the MoE fusion family instantiated from a Group_by graph)
   and verify EVERY rule through all five ffrules passes — symbolic
   shape/dtype transfer, parallel-state soundness, the semantic
   equivalence oracle (fwd + bwd on a 1-device CPU mesh), boundary-
   precondition fuzz, and registry determinism (stable sorted content-
   hashable emission). Zero errors required.

2. **Corruption self-test** (`--self-test`, on by default): the shared
   corpus of deliberately-unsound rules (`analysis.rules
   .selftest_classes`) — wrong output shape, dtype drift, dropped
   replica dim, degree-product violation, partial-sum-through-nonlinear,
   matcher-accepting-indivisible-dims, numeric divergence — each must be
   caught as EXACTLY its finding class.

3. **Load-gate check**: write an unsound JSON rule file and assert
   `load_rule_collection` refuses it with a structured
   RuleVerificationError naming the rule and finding class; with
   verify_rules off (--no-verify-rules) the same file loads with the
   verdict downgraded to warnings and recorded for the compile report.

Writes a machine-readable report with `--report OUT.json` (uploaded as a
CI artifact, on failure too). Exits nonzero on any violated assertion.

Usage: python scripts/ffrules.py [--report OUT.json] [--no-self-test]
       [--no-oracle] [--mesh data,model,dcn,seq]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# progressive report state: fail() flushes whatever has been collected
# so far, so the CI artifact exists (with the failure recorded) for RED
# runs too
_REPORT: dict = {"kind": "ffrules_report", "ok": False}
_REPORT_PATH = ""


def _write_report():
    if not _REPORT_PATH:
        return
    d = os.path.dirname(os.path.abspath(_REPORT_PATH))
    os.makedirs(d, exist_ok=True)
    with open(_REPORT_PATH, "w") as f:
        json.dump(_REPORT, f, indent=1)
    print(f"ffrules: report written to {_REPORT_PATH}")


def fail(msg: str):
    print(f"ffrules: FAIL: {msg}", file=sys.stderr)
    _REPORT["failure"] = msg
    _write_report()
    sys.exit(1)


def _group_by_graph():
    """A minimal PCG exhibiting a Group_by node, so the sweep also
    covers the data-driven fuse_moe_trio family (it only joins the
    registry when a graph exhibits an expert count)."""
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.ops.moe import GroupByParams
    from flexflow_tpu.pcg.graph import Graph, OpNode

    g = Graph()
    g.add_node(OpNode(OT.OP_GROUP_BY, GroupByParams(4, 1.0),
                      name="sweep_gb"))
    return g


def run_sweep(mesh_sizes: dict, oracle: bool) -> None:
    from flexflow_tpu import FFConfig
    from flexflow_tpu.analysis import rules as R

    sys.argv = [sys.argv[0]]
    cfg = FFConfig()
    cfg.mesh_axis_sizes = tuple(mesh_sizes.values())
    t0 = time.perf_counter()
    res = R.verify_registry(mesh_sizes, cfg, graph=_group_by_graph(),
                            oracle=oracle)
    elapsed = time.perf_counter() - t0
    n_rules = next((f.details.get("rules") for f in res.findings
                    if f.code == "rules_clean"), None)
    fp = next((f.details.get("fingerprint") for f in res.findings
               if f.code == "rules_clean"), "")
    errs = res.errors()
    _REPORT["sweep"] = {
        "mesh": mesh_sizes, "elapsed_s": elapsed,
        "fingerprint": fp, **res.summary(),
    }
    if errs:
        fail(f"registry sweep: {len(errs)} error(s): "
             f"{[str(f) for f in errs[:5]]}")
    warns = res.warnings()
    if warns:
        # a rule the verifier cannot even instantiate is an unverified
        # rule — the sweep's whole point is that NONE exist
        fail(f"registry sweep: {len(warns)} unverified rule(s): "
             f"{[str(f) for f in warns[:5]]}")
    print(f"ffrules: sweep — {n_rules} rule(s) verified clean in "
          f"{elapsed:.1f}s on mesh {mesh_sizes} "
          f"(fingerprint {fp[:16]})")


def run_self_test(mesh_sizes: dict, oracle: bool) -> None:
    from flexflow_tpu.analysis import rules as R

    for klass, xfer, expect in R.selftest_classes():
        if not oracle and klass == "numeric_divergence":
            # this class is only observable by executing the graphs
            print(f"ffrules: self-test {klass:26s} — skipped "
                  f"(--no-oracle)")
            continue
        findings = R.verify_rule(xfer, mesh_sizes, oracle=oracle)
        codes = sorted({f.code for f in findings})
        if codes != [expect]:
            fail(f"self-test {klass}: expected exactly {expect!r}, "
                 f"got {codes}")
        print(f"ffrules: self-test {klass:26s} — caught ({expect})")
        _REPORT.setdefault("self_test", []).append(
            {"class": klass, "finding": expect})


_UNSOUND_JSON = {
    "rules": [{
        "name": "external_bad_activation",
        "src": [{"op": "linear", "inputs": ["$0"], "out": "l1",
                 "constraints": [{"attr": "activation", "eq": "none"}]}],
        "dst": [{"op": "linear", "inputs": ["$0"], "match": "l1",
                 "params_update": {"activation": "sigmoid"},
                 "out": "l2"}],
        "map_outputs": [["l1", "l2"]],
    }],
}


def run_load_gate(workdir: str, mesh_sizes: dict) -> None:
    from types import SimpleNamespace

    from flexflow_tpu import FFConfig
    from flexflow_tpu.analysis.rules import (
        RuleVerificationError,
        _LOAD_RESULTS,
    )
    from flexflow_tpu.search.substitution import load_rule_collection

    path = os.path.join(workdir, "unsound_rules.json")
    with open(path, "w") as f:
        json.dump(_UNSOUND_JSON, f)
    sys.argv = [sys.argv[0]]
    cfg = FFConfig()
    mesh = SimpleNamespace(shape=dict(mesh_sizes))
    try:
        load_rule_collection(path, mesh, config=cfg)
    except RuleVerificationError as e:
        msg = str(e)
        if "external_bad_activation" not in msg \
                or "rule_numeric_divergence" not in msg:
            fail(f"load gate: refusal does not name rule + class: {msg}")
        print("ffrules: load gate — unsound JSON rule refused "
              "(rule + class named)")
    else:
        fail("load gate: unsound JSON rule was NOT refused")
    cfg.verify_rules = False  # --no-verify-rules
    xfers = load_rule_collection(path, mesh, config=cfg)
    if len(xfers) != 1:
        fail("load gate: --no-verify-rules did not load the rule")
    recorded = _LOAD_RESULTS.get(os.path.abspath(path))
    if recorded is None or not recorded.errors():
        fail("load gate: downgraded verdict was not recorded")
    print("ffrules: load gate — --no-verify-rules downgrades, verdict "
          "recorded")
    _REPORT["load_gate"] = {"refused": True, "downgrade_recorded": True}


def main():
    import shutil
    import tempfile

    argv = sys.argv[1:]
    report_path = ""
    self_test = True
    oracle = True
    mesh_sizes = {"data": 2, "model": 4, "dcn": 1, "seq": 1}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--report":
            i += 1
            report_path = argv[i]
        elif a == "--no-self-test":
            self_test = False
        elif a == "--self-test":
            self_test = True
        elif a == "--no-oracle":
            oracle = False
        elif a == "--mesh":
            i += 1
            sizes = [int(v) for v in argv[i].split(",")]
            mesh_sizes = dict(zip(("data", "model", "dcn", "seq"), sizes))
        elif a in ("-h", "--help"):
            print(__doc__)
            return
        else:
            fail(f"unknown flag {a!r}")
        i += 1
    sys.argv = [sys.argv[0]]  # FFConfig must not parse ffrules' flags

    global _REPORT_PATH
    _REPORT_PATH = report_path
    workdir = tempfile.mkdtemp(prefix="ffrules-")
    try:
        run_sweep(mesh_sizes, oracle)
        if self_test:
            run_self_test(mesh_sizes, oracle)
        if oracle:
            # the production load gate always runs the oracle — checking
            # its refusal needs graph execution
            run_load_gate(workdir, mesh_sizes)
        else:
            print("ffrules: load gate — skipped (--no-oracle)")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    _REPORT["ok"] = True
    _write_report()
    print("ffrules: OK")


if __name__ == "__main__":
    main()
