"""ffsan smoke: the numerics-verifier + NaN-provenance CI gate.

Three assertions (docs/analysis.md "ffsan"):

1. **Static half present and clean** — compile a mixed-precision (bf16)
   transformer LM with --diagnostics + --sanitize-numerics +
   --spmd-barrier and assert strategy_report.json carries the
   `analysis` section with the `dtype_flow` and `spmd_uniformity`
   passes run, ZERO errors, `sanitize_numerics: true`, and a
   non-diverged barrier verdict; the warm dtype-flow pass itself must
   stay inside its compile-overhead budget.

2. **NaN provenance** — inject a non-finite value at a named op at step
   K (the executor's numeric-fault hook) and assert the ONE `nan_loss`
   alert in alerts.jsonl names exactly that op and phase — "op X's fwd
   went non-finite at step K", not just "loss is NaN".

3. **run_doctor gate** — the artifacts still pass `run_doctor --check`
   (which now also gates on the ffsan report fields).

Routed through the pipelined engine with --pipeline-steps N (the
localization must survive the fused lax.scan dispatch).

Usage: python scripts/ffsan_smoke.py --telemetry-dir DIR
       [--pipeline-steps N] [--report OUT.json]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

FAULT_STEP = 3


def fail(msg: str):
    print(f"ffsan_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    argv = sys.argv[1:]
    telemetry_dir = "ffsan-artifacts"
    pipeline_steps = 1
    report_path = ""
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--telemetry-dir":
            i += 1
            telemetry_dir = argv[i]
        elif a == "--pipeline-steps":
            i += 1
            pipeline_steps = int(argv[i])
        elif a == "--report":
            i += 1
            report_path = argv[i]
        elif a in ("-h", "--help"):
            print(__doc__)
            return
        else:
            fail(f"unknown flag {a!r}")
        i += 1
    sys.argv = [sys.argv[0]]  # FFConfig must not parse our flags

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    cfg = FFConfig()
    cfg.mesh_axis_sizes = (2, 1, 1, 1)
    cfg.batch_size = 4
    cfg.computation_dtype = DataType.DT_BFLOAT16
    cfg.sanitize_numerics = True
    cfg.spmd_barrier = True
    cfg.diagnostics = True
    cfg.telemetry_dir = telemetry_dir
    cfg.pipeline_steps = pipeline_steps
    ff = FFModel(cfg)
    lm = TransformerLMConfig(vocab_size=64, hidden_size=32, num_heads=2,
                             num_layers=1, sequence_length=16)
    build_transformer_lm(ff, lm, batch_size=4)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # ---- 1) static half: report fields + clean numerics section
    rpath = os.path.join(telemetry_dir, "strategy_report.json")
    if not os.path.exists(rpath):
        fail(f"no {rpath} (diagnostics did not write the report)")
    rep = json.load(open(rpath))
    analysis = rep.get("analysis")
    if analysis is None:
        fail("strategy_report.json has no analysis section")
    for p in ("dtype_flow", "spmd_uniformity"):
        if p not in analysis.get("passes_run", []):
            fail(f"pass {p} did not run (got "
                 f"{analysis.get('passes_run')})")
    if analysis["errors"]:
        errs = [f for f in analysis["findings"]
                if f["severity"] == "error"]
        fail(f"mixed-precision compile has analysis errors: {errs[:3]}")
    num_findings = [f for f in analysis["findings"]
                    if f["pass"] in ("dtype_flow", "spmd_uniformity")
                    and f["severity"] != "info"]
    if num_findings:
        fail(f"ffsan passes not clean on the bf16 LM: {num_findings}")
    if not rep.get("sanitize_numerics"):
        fail("report does not record sanitize_numerics: true")
    if rep.get("spmd_barrier") not in ("ok", "single_process"):
        fail(f"barrier verdict {rep.get('spmd_barrier')!r}")
    # warm-pass budget: source scans are cached per process, so a warm
    # dtype-flow pass is a pure graph walk — time it standalone
    from flexflow_tpu.analysis import context_for_model, numerics

    ctx = context_for_model(ff)
    best = min(_timed(numerics.run, ff.graph, ff.mesh, ctx)
               for _ in range(3))
    if best > 0.005:
        fail(f"warm dtype_flow pass took {best * 1e3:.1f} ms (> 5 ms)")
    print(f"ffsan_smoke: static half clean "
          f"(dtype_flow warm {best * 1e3:.2f} ms, barrier "
          f"{rep['spmd_barrier']})")

    # ---- 2) NaN provenance: poison one op's fwd at step FAULT_STEP
    target = next((n.name for n in ff.graph.topo_order()
                   if n.op_type == OT.OP_MULTIHEAD_ATTENTION),
                  None) or next(
        n.name for n in ff.graph.topo_order()
        if n.op_type == OT.OP_LINEAR)
    ff.executor.set_numeric_fault(target, "fwd", FAULT_STEP)
    rs = np.random.RandomState(0)
    n = 32
    X = {"tokens": rs.randint(0, lm.vocab_size,
                              (n, lm.sequence_length)).astype(np.int32),
         "positions": np.tile(np.arange(lm.sequence_length,
                                        dtype=np.int32), (n, 1))}
    Y = rs.randint(0, lm.vocab_size,
                   (n, lm.sequence_length, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)

    apath = os.path.join(telemetry_dir, "alerts.jsonl")
    alerts = [json.loads(line) for line in open(apath)
              if line.strip()]
    nan_alerts = [a for a in alerts if a.get("rule") == "nan_loss"]
    if len(nan_alerts) != 1:
        fail(f"expected exactly one nan_loss alert (fire-once), got "
             f"{len(nan_alerts)}")
    alert = nan_alerts[0]
    details = alert.get("details") or {}
    if details.get("op") != target or details.get("phase") != "fwd":
        fail(f"alert does not name the poisoned op: wanted "
             f"({target!r}, fwd), got {details!r} "
             f"[{alert.get('message')}]")
    if int(details.get("at_step", -1)) != FAULT_STEP:
        fail(f"alert localizes step {details.get('at_step')} "
             f"!= injected {FAULT_STEP}")
    print(f"ffsan_smoke: nan_loss alert names {target} (fwd) at step "
          f"{FAULT_STEP} — provenance OK "
          f"(pipeline_steps={pipeline_steps})")

    if report_path:
        os.makedirs(os.path.dirname(os.path.abspath(report_path)),
                    exist_ok=True)
        with open(report_path, "w") as f:
            json.dump({"kind": "ffsan_report", "ok": True,
                       "dtype_flow_warm_s": best,
                       "spmd_barrier": rep["spmd_barrier"],
                       "localized": details,
                       "pipeline_steps": pipeline_steps}, f, indent=1)
        print(f"ffsan_smoke: report written to {report_path}")
    print("ffsan_smoke: OK")


def _timed(fn, *args):
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
