"""ZeRO-3 / FSDP smoke: memory-constrained LM on a dp CPU mesh.

The CI gate for stage-3 parameter sharding (docs/performance.md
"Parameter sharding (ZeRO-3/FSDP)"): compiles a small transformer LM on
a pure data-parallel mesh with per-chip HBM capped below what STAGE 2
can fit (stage 2 keeps one resident gathered copy per weight, so its
model bytes are flat in dp), WITHOUT forcing --weight-update-sharding,
runs a short fit, then asserts

  - Unity's update-dimension decision (choose_update_sharding) SELECTED
    stage 3 on its own: auto mode (forced is None), reason memory_bound,
    predicted stage-2 memory over the cap and predicted stage-3 memory
    under it (1/shards-at-rest weights + at most two gathered layers in
    flight is what fits the plan);
  - the params really live 1/shards at rest: the addressable parameter
    bytes on chip 0 are ~1/shards of the logical parameter bytes;
  - the donated param-gather executable round-trips: gathering the
    (donated, rebound) tree reproduces the full logical values;
  - the strategy report prices the per-layer gathers on the overlappable
    channel: update_stage 3, report-level param_gather_s > 0, and every
    op that carries param_gather_s shows overlap_s >= param_gather_s
    with sync_s == 0 (the gather hides behind the previous layer's
    compute; only hop latency is exposed);
  - the makespan identity still reproduces with the gather channel in
    play (run_doctor --check covers the same report in CI);
  - the ffcheck memory-liveness pass verified the 1/shards-at-rest +
    transient-gather accounting without tripping the OOM gate on the
    plan the decision made fit;
  - telemetry carries the param_gather event (layers/bytes/overlap) and
    the weight_update event with stage 3 — the compiled executable
    really runs the just-in-time gathers;
  - the fit completed (steps recorded) with stage 3 live.

Usage: python scripts/fsdp_smoke.py --telemetry-dir OUT
       [--mesh 4,1,1,1] [-ll:fsize MiB] [flexflow flags]
Exits nonzero with a diagnostic on any violated assertion.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"fsdp_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl

    # defaults: a dp=4 mesh and a per-chip HBM cap squeezed below what
    # stage 2's resident gathered copies can fit — auto mode must flip
    # to stage 3 (NO --weight-update-sharding here: the point is that
    # Unity selects it)
    argv = sys.argv[1:]
    if any(a.startswith("--weight-update-sharding") for a in argv):
        fail("do not force --weight-update-sharding — the smoke proves "
             "the search selects stage 3")
    if "--mesh" not in argv:
        argv += ["--mesh", "4,1,1,1"]
    if "-ll:fsize" not in argv:
        argv += ["-ll:fsize", "0.9"]
    if "--diagnostics" not in argv:
        argv += ["--diagnostics"]
    sys.argv = [sys.argv[0]] + argv

    config = FFConfig()
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    config.batch_size = 4

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=128, hidden_size=64, num_heads=2, num_layers=2,
        sequence_length=32)
    build_transformer_lm(ff, cfg, batch_size=4)
    ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # 1) the update-dimension search selected stage 3, for the memory
    # reason, in auto mode
    dec = ff._update_sharding or {}
    if dec.get("forced") is not None:
        fail(f"decision was forced ({dec['forced']}) — auto mode required")
    if not dec.get("enabled") or dec.get("stage") != 3:
        fail(f"search did not select stage 3 (stage {dec.get('stage')}, "
             f"reason {dec.get('reason')}): {dec.get('predicted')}")
    if dec.get("reason") != "memory_bound":
        fail(f"expected a memory_bound selection, got {dec.get('reason')}")
    pred = dec.get("predicted") or {}
    cap = pred.get("hbm_cap_bytes", 0.0)
    if not (pred.get("stage2_mem_bytes", 0.0) > cap
            >= pred.get("stage3_mem_bytes", float("inf"))):
        fail(f"memory pricing inconsistent with a stage-3 memory_bound "
             f"pick: stage2 {pred.get('stage2_mem_bytes')} / stage3 "
             f"{pred.get('stage3_mem_bytes')} vs cap {cap}")
    if not ff.executor.gather_specs or not ff.executor.gather_schedule:
        fail("stage 3 selected but the executor built no gather schedule")

    # 2) the params live 1/shards at rest: addressable bytes on chip 0
    # vs the logical parameter bytes of the sharded weights
    shards = dec["shards"]
    dev0 = jax.devices()[0]
    sharded_logical = 0
    sharded_local = 0
    for (node, wname), (_spec, shape) in ff.executor.update_specs.items():
        leaf = ff._params[node][wname]
        sharded_logical += int(np.prod(shape)) * 4
        for sh in leaf.addressable_shards:
            if sh.device == dev0:
                sharded_local += int(sh.data.size) * sh.data.dtype.itemsize
    if not sharded_logical or \
            sharded_local > sharded_logical / shards * 1.01:
        fail(f"at-rest layout is not 1/shards: {sharded_local} bytes on "
             f"chip 0 vs {sharded_logical} logical / {shards} shards")

    # 3) the donated gather executable round-trips (rebind pattern —
    # the tree is donated, so it is reassigned from the call)
    before = {
        # two one-off reference fetches at setup, not a hot loop
        key: np.asarray(jax.device_get(ff._params[key[0]][key[1]]))  # fflint: ok host_sync_in_loop
        for key in list(ff.executor.gather_specs)[:2]}
    gather_fn = ff.executor.build_param_gather()
    tree = {k: dict(v) for k, v in ff._params.items()}
    tree = gather_fn(tree)
    for (node, wname), want in before.items():
        # two one-off verification fetches at setup, not a hot loop
        got = np.asarray(jax.device_get(tree[node][wname]))  # fflint: ok host_sync_in_loop
        if not np.array_equal(got, want):
            fail(f"gathered {node}.{wname} != logical values")
    ff._params = tree  # gathered values == logical values, placement differs
    # same-model round-trip of the values just gathered above — not a
    # plan transition (no second plan exists to verify against)
    ff._params = ff.executor.place_update_sharded(ff._params)  # fflint: ok unverified_transition

    rs = np.random.RandomState(0)
    n = 8
    X = {"tokens": rs.randint(0, cfg.vocab_size,
                              (n, cfg.sequence_length)).astype(np.int32),
         "positions": np.tile(
             np.arange(cfg.sequence_length, dtype=np.int32), (n, 1))}
    Y = rs.randint(0, cfg.vocab_size,
                   (n, cfg.sequence_length, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)

    tdir = config.telemetry_dir
    report_path = os.path.join(tdir, "strategy_report.json")
    if not os.path.exists(report_path):
        fail(f"missing strategy report {report_path}")
    with open(report_path) as f:
        report = json.load(f)

    # 4) the report prices the per-layer gathers on the overlappable
    # channel
    if report.get("update_stage") != 3:
        fail(f"strategy report update_stage {report.get('update_stage')} "
             f"!= 3")
    if report.get("update_shards") != dec["shards"]:
        fail(f"report update_shards {report.get('update_shards')} != "
             f"decision shards {dec['shards']}")
    if not report.get("param_gather_s", 0.0) > 0.0:
        fail("report param_gather_s is zero — the gathers were not "
             "priced on the stage-3 channel")
    gathered_ops = [o for o in report["ops"]
                    if o.get("param_gather_s", 0.0) > 0.0]
    if not gathered_ops:
        fail("no op carries param_gather_s")
    for o in gathered_ops:
        if (o.get("overlap_s", 0.0)
                < o["param_gather_s"] + o.get("grad_sync_s", 0.0)
                or o.get("sync_s")):
            fail(f"op {o['name']} gather not on the overlappable "
                 f"channel: overlap_s {o.get('overlap_s')} / "
                 f"param_gather_s {o['param_gather_s']} / sync_s "
                 f"{o.get('sync_s')}")

    # 5) the report's makespan identity holds with the gather channel
    from flexflow_tpu.diagnostics.explain import verify_report_total

    total = verify_report_total(report)
    pred_s = report["total_predicted_s"]
    if not (abs(total - pred_s) <= 1e-9 + 1e-6 * abs(pred_s)):
        fail(f"makespan identity broken with the param-gather channel: "
             f"verify={total} vs report={pred_s}")

    # 6) ffcheck's memory-liveness pass verified the stage-3 accounting
    # and did not trip the OOM gate on the plan the decision made fit
    analysis = report.get("analysis") or {}
    if analysis.get("errors", 1) != 0:
        fail(f"ffcheck reported errors on the stage-3 plan: {analysis}")
    mem_findings = [f for f in analysis.get("findings", [])
                    if f.get("code") == "memory_timeline"]
    if not mem_findings:
        fail("no memory_timeline finding — the liveness pass did not run")
    details = mem_findings[0].get("details") or {}
    if details.get("update_stage") != 3:
        fail(f"liveness pass did not see stage 3: {details}")
    if not details.get("gather_peak_bytes", 0.0) > 0.0:
        fail("liveness pass recorded no transient gather bytes")
    if [f for f in analysis.get("findings", [])
            if f.get("code") == "oom_predicted"
            and f.get("severity") == "error"]:
        fail("OOM gate fired on the plan the stage-3 decision made fit")

    # 7) the compiled executable really runs the gathers
    recs = list(read_jsonl(os.path.join(tdir, "metrics.jsonl")))
    pg = [r for r in recs if r.get("kind") == "param_gather"]
    if not pg:
        fail("no param_gather event in telemetry")
    if not pg[0].get("layers") or not pg[0].get("bytes"):
        fail(f"param_gather event inconsistent: {pg[0]}")
    wu = [r for r in recs if r.get("kind") == "weight_update"]
    if not wu or wu[0].get("stage") != 3:
        fail(f"weight_update event missing stage 3: {wu[:1]}")

    # 8) the fit actually stepped under stage 3
    steps = [r for r in recs if r.get("kind") == "step"]
    if not steps:
        fail("no step records — fit did not run")

    print(f"fsdp_smoke: OK — stage 3 selected "
          f"({dec['shards']} shards, reason {dec['reason']}; "
          f"mem stage2 {pred['stage2_mem_bytes'] / 2**20:.2f} -> stage3 "
          f"{pred['stage3_mem_bytes'] / 2**20:.2f} MiB/chip vs cap "
          f"{cap / 2**20:.2f}), params {sharded_local} B/chip at rest "
          f"(~1/{dec['shards']} of {sharded_logical} B), param_gather_s "
          f"{report['param_gather_s'] * 1e6:.1f} us overlapped, "
          f"{len(steps)} steps, makespan identity holds")


if __name__ == "__main__":
    main()
