"""Long-context smoke: ring-attention LM on a seq-sharded CPU mesh.

The CI gate for the round-7 long-context roofline pass (docs/performance.md
"Long-context path"): compiles a small transformer LM with
attention_impl="ring" on a mesh whose `seq` axis is sharded, with the Unity
search + overlap-aware cost model on, runs a short fit through the
seq-parallel plan, then asserts

  - the search SELECTED the sequence-parallel ring strategy: the strategy
    report has the attention node under config "sp" with a nonzero
    `overlap_s` (the ring traffic priced on the overlappable channel —
    max(compute, comm), matching the double-buffered runtime schedule);
  - the makespan identity still reproduces with the overlap channel in
    play (run_doctor --check covers the same report in CI);
  - telemetry carries the `ring.attention` event with overlap=true — the
    compiled executable really contains the double-buffered ppermute
    pipeline, not the serial ablation body;
  - the fit completed (steps recorded) with the seq-sharded plan live.

Usage: python scripts/longcontext_smoke.py --telemetry-dir OUT
       [--mesh 1,1,1,4] [--budget N] [flexflow flags]
Exits nonzero with a diagnostic on any violated assertion.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"longcontext_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl

    # defaults: seq=4 mesh, a small search budget, sample-parallel gate
    # open, diagnostics on (the strategy report is an acceptance artifact)
    argv = sys.argv[1:]
    if "--mesh" not in argv:
        argv += ["--mesh", "1,1,1,4"]
    if "--budget" not in argv:
        argv += ["--budget", "4"]
    if "--enable-sample-parallel" not in argv:
        argv += ["--enable-sample-parallel"]
    if "--diagnostics" not in argv:
        argv += ["--diagnostics"]
    sys.argv = [sys.argv[0]] + argv

    config = FFConfig()
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    config.batch_size = 2

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=128, hidden_size=64, num_heads=2, num_layers=1,
        sequence_length=256, attention_impl="ring")
    build_transformer_lm(ff, cfg, batch_size=2)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rs = np.random.RandomState(0)
    n = 8
    X = {"tokens": rs.randint(0, cfg.vocab_size,
                              (n, cfg.sequence_length)).astype(np.int32),
         "positions": np.tile(
             np.arange(cfg.sequence_length, dtype=np.int32), (n, 1))}
    Y = rs.randint(0, cfg.vocab_size,
                   (n, cfg.sequence_length, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=2, shuffle=False, verbose=False)

    tdir = config.telemetry_dir
    report_path = os.path.join(tdir, "strategy_report.json")
    if not os.path.exists(report_path):
        fail(f"missing strategy report {report_path}")
    with open(report_path) as f:
        report = json.load(f)

    # 1) the search selected the sequence-parallel ring strategy
    attn_ops = [o for o in report["ops"]
                if o["op_type"] == "OP_MULTIHEAD_ATTENTION"]
    if not attn_ops:
        fail("no attention op in the strategy report")
    sp_attn = [o for o in attn_ops if o["config"] == "sp"]
    if not sp_attn:
        fail(f"search did not select the ring sp strategy "
             f"(attention configs: {[o['config'] for o in attn_ops]})")

    # 2) its ring traffic was priced on the overlappable channel
    if not any(o.get("overlap_s", 0.0) > 0.0 for o in sp_attn):
        fail("sp attention has no overlap_s — ring comm was priced "
             "serially, not on the overlappable channel")

    # 3) the report's makespan identity holds with overlap in play
    from flexflow_tpu.diagnostics.explain import verify_report_total

    total = verify_report_total(report)
    pred = report["total_predicted_s"]
    if not (abs(total - pred) <= 1e-9 + 1e-6 * abs(pred)):
        fail(f"makespan identity broken with overlap channel: "
             f"verify={total} vs report={pred}")

    # 4) the compiled executable carries the overlapped ring schedule
    metrics_path = os.path.join(tdir, "metrics.jsonl")
    recs = list(read_jsonl(metrics_path))
    ring_events = [r for r in recs if r.get("kind") == "ring.attention"]
    if not ring_events:
        fail("no ring.attention event in telemetry — the ring body "
             "was never traced")
    if not all(r.get("overlap") for r in ring_events):
        fail(f"ring.attention traced without overlap: {ring_events}")

    # 5) the fit actually stepped under the seq-sharded plan
    steps = [r for r in recs if r.get("kind") == "step"]
    if not steps:
        fail("no step records — fit did not run")

    print(f"longcontext_smoke: OK — sp attention selected "
          f"(overlap_s {sp_attn[0].get('overlap_s', 0.0) * 1e6:.1f} µs), "
          f"{len(ring_events)} overlapped ring compile(s), "
          f"{len(steps)} steps, makespan identity holds")


if __name__ == "__main__":
    main()
