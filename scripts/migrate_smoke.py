"""fftrans migration smoke: verified in-process plan migration on the CPU mesh.

The CI gate for the transition verifier + migrate path (docs/analysis.md
"Transition verification"): trains a small LM on dp=4 under ZeRO stage 3
(params sharded at rest — the transition that NEEDS a gather path), then
migrates the live state in-process to a dp=2×tp=2 replicated compile via
`migrate_state`, with a checkpoint-restart control restoring the same
state the classic way, and asserts

  - the TransitionPlan verified clean (zero errors across all five
    fftrans passes) and the stage-3 transfers record their gather path;
  - strategy_report.json carries the `transition` section with
    predicted_s REPRODUCING from the per-transfer entries alone
    (verify_transition_total — the ffcheck-identity treatment; the
    run_doctor --check step in CI re-verifies the same artifact);
  - measured migration seconds landed next to the prediction (the
    fidelity datapoint the re-planner's pay-off rule needs);
  - the migrated state is BIT-EXACT vs the checkpoint-restart control —
    params, optimizer slots, counters, step — and every migrated leaf
    carries the NEW compile's sharding;
  - one more epoch on each continues bit-exactly (identical losses by
    identical params at every step);
  - telemetry carries the transition_verify + migrate events.

Usage: python scripts/migrate_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on any violated assertion.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"migrate_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _flat(tree):
    import jax.tree_util as jtu

    return {jtu.keystr(p): np.asarray(v)
            for p, v in jtu.tree_flatten_with_path(tree)[0]}


def _build(mesh, extra_argv, base_argv, cfg, TelemetryDir=None):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    sys.argv = [sys.argv[0]] + list(base_argv) + list(extra_argv)
    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = 4
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=4)
    ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def main():
    from flexflow_tpu.analysis.transition import verify_transition_total
    from flexflow_tpu.models import TransformerLMConfig
    from flexflow_tpu.resilience import migrate_state
    from flexflow_tpu.telemetry import read_jsonl

    argv = sys.argv[1:]
    tdir = ""
    if "--telemetry-dir" in argv:
        tdir = argv[argv.index("--telemetry-dir") + 1]
    if not tdir:
        fail("pass --telemetry-dir")
    # the telemetry/diagnostics session belongs to the MIGRATED model —
    # its strategy report is the artifact under test
    base = [a for i, a in enumerate(argv)
            if a not in ("--telemetry-dir", "--diagnostics")
            and (i == 0 or argv[i - 1] != "--telemetry-dir")]

    cfg = TransformerLMConfig(
        vocab_size=128, hidden_size=64, num_heads=2, num_layers=2,
        sequence_length=32)
    rs = np.random.RandomState(0)
    n = 8
    X = {"tokens": rs.randint(0, cfg.vocab_size,
                              (n, cfg.sequence_length)).astype(np.int32),
         "positions": np.tile(
             np.arange(cfg.sequence_length, dtype=np.int32), (n, 1))}
    Y = rs.randint(0, cfg.vocab_size,
                   (n, cfg.sequence_length, 1)).astype(np.int32)

    # 1) old plan: dp=4, ZeRO stage 3 — params sharded at rest
    old = _build((4, 1, 1, 1), ["--weight-update-sharding=stage3"],
                 base, cfg)
    if (old._update_sharding or {}).get("stage") != 3:
        fail(f"old compile did not run stage 3: {old._update_sharding}")
    old.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)

    # 2) checkpoint-restart CONTROL onto the new plan
    ckroot = tempfile.mkdtemp(prefix="migrate_smoke_ck_")
    old.save_checkpoint(ckroot)
    ctrl = _build((2, 2, 1, 1), [], base, cfg)
    ctrl.load_checkpoint(ckroot)

    # 3) verified in-process migration onto an identical new compile
    mig = _build((2, 2, 1, 1),
                 ["--telemetry-dir", tdir, "--diagnostics"], base, cfg)
    section = migrate_state(old, mig)

    analysis = section.get("analysis") or {}
    if analysis.get("errors", 1) != 0:
        fail(f"transition verification reported errors: {analysis}")
    if sorted(analysis.get("passes_run", [])) != sorted(
            ("state_mapping", "transition_memory", "transfer_collectives",
             "migration_donation", "transfer_uniformity")):
        fail(f"fftrans passes incomplete: {analysis.get('passes_run')}")
    sharded = [t for t in section["transfers"] if t.get("update_sharded")]
    if not sharded:
        fail("no stage-3 transfer in the plan — the scenario degenerated")
    for t in sharded:
        if not any(c["kind"] == "all_gather" for c in t["collectives"]):
            fail(f"stage-3 transfer {t['key']} records no gather path")
    if section.get("measured_s") is None or section["measured_s"] < 0:
        fail("no measured migration seconds on the executed plan")

    # 4) bit-exact vs the checkpoint-restart control
    for name, a, b in (("params", ctrl._params, mig._params),
                       ("opt_slots", ctrl._opt_slots, mig._opt_slots),
                       ("counters", ctrl._counters, mig._counters)):
        fa, fb = _flat(a), _flat(b)
        if fa.keys() != fb.keys():
            fail(f"{name} key sets differ after migration")
        for k in fa:
            if not np.array_equal(fa[k], fb[k]):
                fail(f"migrated {name}{k} != checkpoint-restart control")
    if int(ctrl._step) != int(mig._step):
        fail(f"step counter {int(mig._step)} != control {int(ctrl._step)}")
    import jax.tree_util as jtu

    for _p, leaf in jtu.tree_flatten_with_path(mig._params)[0]:
        if leaf.sharding.mesh.shape != mig.mesh.shape:
            fail("a migrated leaf does not carry the new mesh's sharding")

    # 5) losses continue bit-exact: one more epoch each, identical
    # params at the end imply identical losses at every step
    ctrl.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    mig.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    fa, fb = _flat(ctrl._params), _flat(mig._params)
    for k in fa:
        if not np.array_equal(fa[k], fb[k]):
            fail(f"post-migration trajectory diverged at {k}")

    # 6) the report artifact: transition section + the identity
    report_path = os.path.join(tdir, "strategy_report.json")
    if not os.path.exists(report_path):
        fail(f"missing strategy report {report_path}")
    with open(report_path) as f:
        report = json.load(f)
    t = report.get("transition")
    if not t:
        fail("strategy report has no transition section")
    total = verify_transition_total(t)
    want = t.get("predicted_s", 0.0)
    if abs(total - want) > 1e-9 + 1e-6 * abs(want):
        fail(f"transition identity broken: verify={total} vs "
             f"report={want}")
    if not t.get("bytes_on_wire"):
        fail("transition section carries no bytes-on-wire accounting")

    # 7) telemetry events
    recs = list(read_jsonl(os.path.join(tdir, "metrics.jsonl")))
    tv = [r for r in recs if r.get("kind") == "transition_verify"]
    mg = [r for r in recs if r.get("kind") == "migrate"]
    if not tv or tv[0].get("errors", 1) != 0:
        fail(f"transition_verify event missing/unclean: {tv[:1]}")
    if not mg or mg[0].get("measured_s") is None:
        fail(f"migrate event missing measured_s: {mg[:1]}")

    print(f"migrate_smoke: OK — {len(section['transfers'])} transfers "
          f"(stage-3 gather paths on {len(sharded)}), predicted "
          f"{section['predicted_s'] * 1e3:.3f} ms / measured "
          f"{section['measured_s'] * 1e3:.1f} ms, "
          f"{sum(section['bytes_on_wire'].values()) / 2**20:.2f} MiB on "
          f"wire, bit-exact vs checkpoint-restart incl. one continued "
          f"epoch, identity holds")


if __name__ == "__main__":
    main()
