"""ffpulse smoke: fit + serve with continuous export, then verify it all.

The CI gate for the metrics plane (docs/observability.md "Metrics
plane"): one small transformer LM on the virtual CPU mesh goes through

  1. a short fit with --metrics-interval export on and the localhost
     HTTP endpoint up (the script binds a free port itself — port 0
     means OFF in config semantics), so rolling `metrics_snapshot`
     records and an atomic `metrics.prom` land while training runs;
  2. a live scrape of /metrics (must parse back through
     parse_prometheus with the step-time histogram present) and
     /healthz (must report the snapshot count) while the exporter
     thread is still serving;
  3. a shared-prefix serving trace through the SAME session, so the
     drained snapshot carries the request-grain serving histograms
     (queue wait / TTFT / TBT / e2e) next to the training goodput
     gauges (tokens/s, train_mfu from the cost-model FLOPs anchor);
  4. artifact verification from the files alone: every snapshot's
     histogram bucket counts sum to its recorded total, the drained
     snapshot's TTFT count equals the completed-with-token request
     count, train_mfu is positive, and metrics.prom round-trips.

ci.yml then runs scripts/run_doctor.py --check on the same dir — the
doctor re-derives the snapshot identities from the artifacts with no
help from this process.

Usage: python scripts/obs_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on any violated identity.
"""

import json
import os
import socket
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"obs_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl
    from flexflow_tpu.telemetry.metrics import parse_prometheus

    config = FFConfig()  # parses --telemetry-dir / --metrics-* from argv
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    if not config.metrics_interval:
        config.metrics_interval = 0.2
    if not config.metrics_port:
        # port 0 means OFF in config semantics — the smoke must exercise
        # the endpoint, so bind a free port here and hand it over
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        config.metrics_port = s.getsockname()[1]
        s.close()

    lm = TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
        sequence_length=32, attention_impl="xla")
    batch = 8
    config.batch_size = batch
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # ---- leg 1: fit with export on ------------------------------------
    rs = np.random.RandomState(0)
    n = batch * 8  # 8 steps
    toks = rs.randint(1, lm.vocab_size, (n, lm.sequence_length)).astype(
        np.int32)
    pos = np.tile(np.arange(lm.sequence_length, dtype=np.int32), (n, 1))
    labels = rs.randint(0, lm.vocab_size,
                        (n, lm.sequence_length, 1)).astype(np.int32)
    ff.fit({"tokens": toks, "positions": pos}, labels,
           epochs=1, batch_size=batch)

    # ---- leg 2: scrape the live endpoint ------------------------------
    base = f"http://127.0.0.1:{config.metrics_port}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.load(r)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            prom_text = r.read().decode()
    except OSError as e:
        fail(f"metrics endpoint not serving on {base}: {e}")
    if health.get("snapshots", 0) < 1:
        fail(f"/healthz reports no snapshots: {health}")
    scraped = parse_prometheus(prom_text)
    if "train_step_time_s" not in scraped["histograms"]:
        fail("/metrics scrape missing the train_step_time_s histogram")

    # ---- leg 3: shared-prefix serving trace, same session -------------
    engine = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4,
                      kv_layout="paged", kv_block_size=4)
    system = rs.randint(1, lm.vocab_size, 8).tolist()
    prompts = [system] + [
        system + rs.randint(1, lm.vocab_size, 4).tolist() for _ in range(5)]
    outs = engine.generate(prompts)
    if any(len(o) != 4 for o in outs):
        fail(f"serving leg: expected 4 tokens per request, got "
             f"{[len(o) for o in outs]}")

    tel = ff.get_telemetry()
    tel.close()

    # ---- leg 4: verify the artifacts from the files alone -------------
    tdir = config.telemetry_dir
    recs = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    snaps = [r for r in recs if r.get("kind") == "metrics_snapshot"]
    if len(snaps) < 2:
        fail(f"expected interval + final snapshots, got {len(snaps)}")
    for r in snaps:
        for key, h in (r["metrics"].get("histograms") or {}).items():
            if sum(h["counts"]) != h["count"]:
                fail(f"snapshot seq {r.get('seq')}: {key} bucket counts "
                     f"sum to {sum(h['counts'])} but count is "
                     f"{h['count']}")
    final = snaps[-1]["metrics"]
    hists = final.get("histograms") or {}
    gauges = final.get("gauges") or {}
    if hists.get("train_step_time_s", {}).get("count", 0) < 8:
        fail(f"final snapshot missing the 8 fit steps: "
             f"{hists.get('train_step_time_s')}")
    if not gauges.get("train_mfu", 0) > 0:
        fail(f"train_mfu gauge missing/zero (goodput anchor did not "
             f"land): {gauges}")
    drained = [r for r in snaps if r.get("drained")]
    if not drained:
        fail("no drained serving snapshot")
    ttft = drained[-1]["metrics"]["histograms"].get("serve_ttft_s")
    with_token = sum(1 for r in recs if r.get("kind") == "serve.request"
                     and r.get("new_tokens", 0) > 0)
    if ttft is None or ttft["count"] != with_token:
        fail(f"drained snapshot TTFT count "
             f"({ttft and ttft['count']}) != completed-with-token "
             f"requests ({with_token})")

    prom_path = os.path.join(tdir, "metrics.prom")
    if not os.path.exists(prom_path):
        fail("missing metrics.prom")
    with open(prom_path) as f:
        on_disk = parse_prometheus(f.read())
    for name in ("train_step_time_s", "serve_ttft_s"):
        if name not in on_disk["histograms"]:
            fail(f"metrics.prom missing histogram {name}")

    print(f"obs_smoke: OK — {len(snaps)} snapshots, "
          f"scraped {len(prom_text.splitlines())} prom lines live, "
          f"train_mfu={gauges['train_mfu']:.2e}, "
          f"ttft_count={ttft['count']}, "
          f"serve histograms exported: "
          f"{sorted(k for k in on_disk['histograms'] if k.startswith('serve_'))}")


if __name__ == "__main__":
    main()
