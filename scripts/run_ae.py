"""AE-style example runner — reference scripts/osdi22ae/*.sh +
tests/python_interface_test.sh in ONE command.

For each model of the OSDI'22 artifact-evaluation set (MLP, AlexNet, DLRM,
MoE, Inception-v3, XDL, candle-uno, ResNeXt-50) this trains the zoo build
twice — once with the Unity-style search enabled (joint rewrite×placement
search plus mesh factorization, the dlrm.sh "strategy discovered by Unity"
leg) and once with pure data parallelism (the --only-data-parallel leg) —
and prints both throughputs plus one machine-readable `AE_RESULT {json}`
line per run. The MNIST MLP additionally enforces the reference's ≥90%
train-accuracy gate (python_interface_test.sh's check).

Usage:
  python scripts/run_ae.py                  # full set
  python scripts/run_ae.py --models mlp,dlrm,moe --batches 4 --epochs 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# harmless on TPU; gives the dp-vs-Unity comparison 8 virtual devices when
# this lands on the CPU backend (must precede the first jax import)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=8")

import numpy as np


def _spec_mlp(batch, rs):
    from flexflow_tpu.models import build_mnist_mlp

    def build(ff):
        build_mnist_mlp(ff, batch_size=batch)
        centers = rs.randn(10, 784) * 2.0
        n = max(2048, batch * 8)
        y = rs.randint(0, 10, n)
        x = (centers[y] + rs.randn(n, 784)).astype(np.float32)
        return {"input": x}, y.reshape(-1, 1).astype(np.int32), "scce"

    return build


def _spec_alexnet(batch, rs):
    from flexflow_tpu.models import build_alexnet

    def build(ff):
        build_alexnet(ff, batch_size=batch)
        n = batch * 2
        x = rs.randn(n, 3, 229, 229).astype(np.float32)
        y = rs.randint(0, 10, (n, 1)).astype(np.int32)
        return {"input": x}, y, "scce"

    return build


def _spec_inception(batch, rs):
    from flexflow_tpu.models import build_inception_v3

    def build(ff):
        build_inception_v3(ff, batch_size=batch)
        n = batch * 2
        x = rs.randn(n, 3, 299, 299).astype(np.float32)
        y = rs.randint(0, 10, (n, 1)).astype(np.int32)
        return {"input": x}, y, "scce"

    return build


def _spec_resnext(batch, rs):
    from flexflow_tpu.models import build_resnext50

    def build(ff):
        build_resnext50(ff, batch_size=batch)
        n = batch * 2
        x = rs.randn(n, 3, 224, 224).astype(np.float32)
        y = rs.randint(0, 10, (n, 1)).astype(np.int32)
        return {"input": x}, y, "scce"

    return build


def _spec_dlrm(batch, rs):
    from flexflow_tpu.models import DLRMConfig, build_dlrm

    def build(ff):
        c = DLRMConfig(sparse_feature_size=16,
                       embedding_size=(1000, 1000, 1000, 1000),
                       mlp_bot=(16, 64, 16), mlp_top=(80, 64, 2))
        build_dlrm(ff, c, batch_size=batch)
        n = batch * 4
        feeds = {f"sparse{i}": rs.randint(0, 1000, (n, 1)).astype(np.int64)
                 for i in range(4)}
        feeds["dense_input"] = rs.randn(n, 16).astype(np.float32)
        y = rs.rand(n, 2).astype(np.float32)
        return feeds, y, "mse"

    return build


def _spec_xdl(batch, rs):
    from flexflow_tpu.models import build_xdl
    from flexflow_tpu.models.xdl import XDLConfig

    def build(ff):
        c = XDLConfig(sparse_feature_size=16,
                      embedding_size=(1000,) * 4, mlp_top=(256, 64, 2))
        build_xdl(ff, c, batch_size=batch)
        n = batch * 4
        feeds = {f"sparse{i}": rs.randint(0, 1000, (n, 1)).astype(np.int64)
                 for i in range(4)}
        y = rs.rand(n, 2).astype(np.float32)
        return feeds, y, "mse"

    return build


def _spec_moe(batch, rs):
    from flexflow_tpu.models import MoeConfig, build_moe

    def build(ff):
        c = MoeConfig()
        build_moe(ff, c, batch_size=batch, fused=True)
        n = max(1024, batch * 8)
        centers = rs.randn(10, c.in_dim) * 2.0
        y = rs.randint(0, 10, n)
        x = (centers[y] + rs.randn(n, c.in_dim)).astype(np.float32)
        return {"input": x}, y.reshape(-1, 1).astype(np.int32), "scce"

    return build


def _spec_candle(batch, rs):
    from flexflow_tpu.models import build_candle_uno
    from flexflow_tpu.models.candle_uno import CandleUnoConfig

    def build(ff):
        c = CandleUnoConfig()
        inputs, _ = build_candle_uno(ff, c, batch_size=batch)
        n = batch * 4
        feeds = {t.name: rs.randn(n, t.dims[1]).astype(np.float32)
                 for t in inputs}
        y = rs.rand(n, 1).astype(np.float32)
        return feeds, y, "mse"

    return build


SPECS = {
    "mlp": (_spec_mlp, 0.90),       # (spec factory, accuracy gate or None)
    "alexnet": (_spec_alexnet, None),
    "dlrm": (_spec_dlrm, None),
    "moe": (_spec_moe, None),
    "inception": (_spec_inception, None),
    "xdl": (_spec_xdl, None),
    "candle_uno": (_spec_candle, None),
    "resnext50": (_spec_resnext, None),
}

_MODES = {
    "unity": ["--budget", "8", "--enable-parameter-parallel",
              "--search-mesh-shapes"],
    "dp": ["--only-data-parallel"],
}


def run_one(name: str, mode: str, batch: int, epochs: int) -> dict:
    import jax

    from flexflow_tpu import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )

    spec_factory, gate = SPECS[name]
    ndev = jax.device_count()
    sys.argv = ["run_ae"] + _MODES[mode]
    config = FFConfig()
    config.batch_size = batch
    if mode == "dp":
        config.mesh_axis_sizes = (ndev, 1, 1, 1)
    else:
        config.mesh_axis_sizes = (ndev, 1, 1, 1)  # re-factorized by search
    ff = FFModel(config)
    rs = np.random.RandomState(0)
    feeds, labels, loss = spec_factory(batch, rs)(ff)
    loss_type = (LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
                 if loss == "scce"
                 else LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    metrics = [MetricsType.METRICS_ACCURACY] if loss == "scce" else []
    ff.compile(optimizer=SGDOptimizer(lr=0.01 if gate is None else 0.05),
               loss_type=loss_type, metrics=metrics)
    n = labels.shape[0]
    # (no global np.random.seed here: fit's shuffle has been keyed on
    # (config.seed, absolute epoch) since the resilience PR, so the
    # global RNG is dead state — fflint's global_rng rule keeps it out)
    t0 = time.perf_counter()
    ff.fit(feeds, labels, epochs=epochs)
    dt = time.perf_counter() - t0
    result = {
        "model": name,
        "mode": mode,
        "mesh": dict(ff.mesh.shape),
        "samples_per_sec": round(epochs * (n // batch) * batch / dt, 2),
    }
    if gate is not None:
        acc = ff.get_perf_metrics().get_accuracy()
        result["accuracy"] = round(acc, 4)
        result["gate"] = gate
        if acc < gate:
            print(f"AE_RESULT {json.dumps(result)}")
            raise SystemExit(
                f"{name}: accuracy gate failed ({acc:.4f} < {gate})")
    print(f"AE_RESULT {json.dumps(result)}")
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default=",".join(SPECS))
    ap.add_argument("--batches", type=int, default=2,
                    help="(unused sizes are derived per model)")
    ap.add_argument("--batch-size", type=int, default=0,
                    help="0 = per-model default")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--modes", default="unity,dp")
    args = ap.parse_args()

    import jax

    heavy = {"alexnet", "inception", "resnext50"}
    results = []
    for name in args.models.split(","):
        name = name.strip()
        if name not in SPECS:
            raise SystemExit(f"unknown model {name!r}; have {sorted(SPECS)}")
        base = 2 if (name in heavy
                     and jax.devices()[0].platform != "tpu") else 8
        batch = args.batch_size or max(base, jax.device_count())
        for mode in args.modes.split(","):
            print(f"Running {name} with "
                  + ("a parallelization strategy discovered by Unity"
                     if mode == "unity" else "data parallelism"))
            results.append(run_one(name, mode.strip(), batch, args.epochs))
    print(json.dumps({"ae_summary": results}, indent=1))


if __name__ == "__main__":
    main()
