"""Run doctor CLI: render a post-mortem report from any telemetry dir.

Usage:
    python scripts/run_doctor.py TELEMETRY_DIR [--out report.md]
        [--json] [--check] [--strict]

  --out FILE   write the markdown report to FILE (default: stdout)
  --json       emit the structured diagnose() dict instead of markdown
  --check      exit nonzero unless the diagnostics artifacts exist and
               parse (strategy_report.json + alerts.jsonl + metrics.jsonl)
               — the CI acceptance gate
  --strict     additionally exit nonzero when the verdict is "dead"
               (error-level / abort alerts present)

Reads only files — no devices, no live run — so it works on any telemetry
dir copied off the machine that produced it.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("directory", help="telemetry dir of the run")
    ap.add_argument("--out", default="", help="write markdown here")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--strict", action="store_true")
    args = ap.parse_args()

    from flexflow_tpu.diagnostics.doctor import diagnose, render

    if not os.path.isdir(args.directory):
        print(f"run_doctor: no such directory {args.directory!r}",
              file=sys.stderr)
        sys.exit(2)

    if args.check:
        problems = []
        for name in ("metrics.jsonl", "alerts.jsonl",
                     "strategy_report.json"):
            p = os.path.join(args.directory, name)
            if not os.path.exists(p):
                problems.append(f"missing {name}")
        if not problems:
            from flexflow_tpu.diagnostics.explain import verify_report_total

            rep = json.load(open(
                os.path.join(args.directory, "strategy_report.json")))
            total = verify_report_total(rep)
            if abs(total - rep["total_predicted_s"]) > 1e-9 + 1e-6 * abs(
                    rep["total_predicted_s"]):
                problems.append(
                    f"strategy_report per-op costs ({total}) do not "
                    f"reproduce total_predicted_s "
                    f"({rep['total_predicted_s']}) under the makespan rule")
            # ffsan gates: the numerics + SPMD passes must have run in
            # the compile gate, and a recorded fingerprint-barrier
            # mismatch means the artifacts describe a diverged fleet
            analysis = rep.get("analysis")
            if analysis is not None:
                for p in ("dtype_flow", "spmd_uniformity"):
                    if p not in analysis.get("passes_run", []):
                        problems.append(
                            f"analysis section missing the {p} pass "
                            f"(ffsan did not run in the compile gate)")
            if rep.get("spmd_barrier") not in (
                    None, "off", "ok", "single_process"):
                problems.append(
                    f"SPMD fingerprint barrier verdict "
                    f"{rep.get('spmd_barrier')!r} — the fleet diverged "
                    f"before the first step")
            # fftrans gate: when the run went through a verified plan
            # transition (restore/migration), the section's predicted
            # migration seconds must reproduce from the per-transfer
            # entries alone — the same identity treatment as the
            # makespan check above
            trans = rep.get("transition")
            if trans is not None:
                from flexflow_tpu.analysis.transition import (
                    verify_transition_total,
                )

                tt = verify_transition_total(trans)
                want = trans.get("predicted_s", 0.0)
                if abs(tt - want) > 1e-9 + 1e-6 * abs(want):
                    problems.append(
                        f"transition section per-transfer costs ({tt}) "
                        f"do not reproduce predicted_s ({want})")
                ta = trans.get("analysis") or {}
                if ta.get("errors", 0):
                    problems.append(
                        f"transition verification recorded "
                        f"{ta['errors']} error(s) — the migration ran "
                        f"unverified (--no-verify-plan)")
            # disagg gate: every KV handoff the serving_disagg section
            # records must reference a verified transfer program whose
            # predicted seconds reproduce from its own per-transfer
            # entries alone (the same makespan identity as the
            # transition gate), and must agree with that program's
            # price; a fully radix-cached handoff moved zero rows and
            # carries no program by construction
            disagg = rep.get("serving_disagg")
            if disagg is not None:
                from flexflow_tpu.analysis.transition import (
                    verify_transition_total,
                )

                programs = disagg.get("programs") or {}
                for key, prog in programs.items():
                    tt = verify_transition_total(prog)
                    want = prog.get("predicted_s", 0.0)
                    if abs(tt - want) > 1e-9 + 1e-6 * abs(want):
                        problems.append(
                            f"handoff program {key}: per-transfer costs "
                            f"({tt}) do not reproduce predicted_s "
                            f"({want})")
                    pa = prog.get("analysis") or {}
                    if pa.get("errors", 0):
                        problems.append(
                            f"handoff program {key}: transfer "
                            f"verification recorded {pa['errors']} "
                            f"error(s)")
                for i, h in enumerate(disagg.get("handoffs", [])):
                    nblk = int(h.get("injected_blocks", 0))
                    if nblk == 0:
                        if h.get("predicted_s", 0.0):
                            problems.append(
                                f"handoff {i}: fully cached (0 blocks) "
                                f"but predicted_s is nonzero")
                        continue
                    prog = programs.get(str(nblk))
                    if prog is None:
                        problems.append(
                            f"handoff {i}: no verified transfer program "
                            f"for its {nblk}-block extent")
                        continue
                    if abs(h.get("predicted_s", 0.0)
                           - prog.get("predicted_s", 0.0)) > 1e-9:
                        problems.append(
                            f"handoff {i}: predicted_s "
                            f"({h.get('predicted_s')}) does not match "
                            f"its program's price "
                            f"({prog.get('predicted_s')})")
            # ffelastic gate: every priced re-plan decision must be
            # reproducible from the record alone — both sides of the
            # pay-off inequality recompute from their recorded factors,
            # and the migrate/decline call must match the inequality
            # (forced = capacity shrink migrates regardless; dry-run
            # and failed decisions are exempt from the call check)
            elastic = rep.get("elastic") or {}
            for i, dec in enumerate(elastic.get("decisions", [])):
                if dec.get("lhs_s") is None or dec.get("rhs_s") is None:
                    continue
                lhs = (dec.get("predicted_migration_s", 0.0)
                       * dec.get("fidelity_ratio", 1.0))
                rhs = (dec.get("benefit_s_per_step", 0.0)
                       * dec.get("horizon_steps", 0))
                for name, got, want in (("lhs_s", dec["lhs_s"], lhs),
                                        ("rhs_s", dec["rhs_s"], rhs)):
                    if abs(got - want) > 1e-9 + 1e-6 * abs(want):
                        problems.append(
                            f"elastic decision {i}: recorded {name} "
                            f"({got}) does not reproduce from its "
                            f"factors ({want})")
                forced = bool(dec.get("forced"))
                call = dec.get("decision")
                if call == "migrated" and not forced and not lhs < rhs:
                    problems.append(
                        f"elastic decision {i}: migrated but the "
                        f"pay-off inequality does not hold "
                        f"({lhs} >= {rhs})")
                if (call == "declined" and not forced
                        and not dec.get("dry_run") and lhs < rhs):
                    problems.append(
                        f"elastic decision {i}: declined but the "
                        f"pay-off inequality holds ({lhs} < {rhs})")
            # speculative-decoding gate: every payoff decision in the
            # speculation section must be reproducible from its own
            # recorded factors — lhs = K·draft + verify, rhs =
            # (Σ_{i=1..K} a^i)·decode (the SAME accumulation order as
            # the engine, so the floats match) — and the chosen call
            # must agree with the inequality. Calibration rounds
            # (calibrate_decode / bootstrap / no_headroom) carry no
            # priced inequality and are exempt from the call check.
            spec = rep.get("speculation")
            if spec is not None:
                for i, dec in enumerate(spec.get("decisions", [])):
                    if dec.get("reason") != "payoff":
                        continue
                    k = int(dec.get("k", 0))
                    lhs = (k * dec.get("draft_cost_s", 0.0)
                           + dec.get("verify_cost_s", 0.0))
                    a = dec.get("acceptance_ema", 0.0)
                    exp = 0.0
                    x = 1.0
                    for _ in range(k):
                        x *= a
                        exp += x
                    rhs = exp * dec.get("decode_cost_s", 0.0)
                    for name, got, want in (
                            ("lhs_s", dec.get("lhs_s"), lhs),
                            ("expected_accepted",
                             dec.get("expected_accepted"), exp),
                            ("rhs_s", dec.get("rhs_s"), rhs)):
                        if got is None or abs(got - want) > (
                                1e-9 + 1e-6 * abs(want)):
                            problems.append(
                                f"speculation decision {i}: recorded "
                                f"{name} ({got}) does not reproduce "
                                f"from its factors ({want})")
                    chosen = dec.get("chosen")
                    if chosen == "speculate" and not lhs < rhs:
                        problems.append(
                            f"speculation decision {i}: speculated but "
                            f"the payoff inequality does not hold "
                            f"({lhs} >= {rhs})")
                    if chosen == "decode" and lhs < rhs:
                        problems.append(
                            f"speculation decision {i}: fell back to "
                            f"plain decode but the payoff inequality "
                            f"holds ({lhs} < {rhs})")
                drafted = spec.get("draft_tokens", 0)
                accepted = spec.get("accepted_tokens", 0)
                if accepted > drafted:
                    problems.append(
                        f"speculation section accepted {accepted} of "
                        f"{drafted} drafted tokens — acceptance cannot "
                        f"exceed the drafted count")
            # ffpulse gate: every metrics_snapshot must be self-
            # consistent from the artifact alone — for each histogram
            # the bucket counts must sum to the recorded total, and on a
            # DRAINED serving snapshot the TTFT observation count must
            # equal the completed-with-token request count (serve.request
            # events with new_tokens > 0 since the last stats_reset —
            # no_token requests are excluded from TTFT by design)
            # ffscope gates: (a) when a profile section is present its
            # attribution identity must re-verify from the JSON alone —
            # per-op seconds sum back to attributed_s, attributed +
            # unattributed bounded by step device time × parallelism
            # within the stated slop, and every fidelity recomputable
            # from its own measured/predicted pair; (b) a flight.json
            # dump must be a well-formed bounded ring snapshot
            prof = rep.get("profile")
            if prof is not None:
                from flexflow_tpu.scope.attribution import (
                    verify_profile_section,
                )

                problems.extend(verify_profile_section(prof))
            fpath = os.path.join(args.directory, "flight.json")
            if os.path.exists(fpath):
                try:
                    flight = json.load(open(fpath))
                except Exception as e:
                    flight = None
                    problems.append(f"flight.json does not parse: {e}")
                if flight is not None:
                    if flight.get("kind") != "flight_record":
                        problems.append(
                            f"flight.json kind is "
                            f"{flight.get('kind')!r}, expected "
                            f"'flight_record'")
                    for key in ("reason", "capacity", "events"):
                        if key not in flight:
                            problems.append(f"flight.json missing {key!r}")
                    events = flight.get("events")
                    if isinstance(events, list):
                        cap = flight.get("capacity")
                        if isinstance(cap, int) and len(events) > cap:
                            problems.append(
                                f"flight.json holds {len(events)} events "
                                f"but claims capacity {cap} — the ring "
                                f"bound did not hold")
                        for i, ev in enumerate(events):
                            if not (isinstance(ev, dict) and "seq" in ev
                                    and "kind" in ev and "name" in ev):
                                problems.append(
                                    f"flight.json event {i} malformed "
                                    f"(needs seq/kind/name)")
                                break
            from flexflow_tpu.telemetry.recorder import read_jsonl

            records = read_jsonl(
                os.path.join(args.directory, "metrics.jsonl"))
            snapshots = [r for r in records
                         if r.get("kind") == "metrics_snapshot"]
            for r in snapshots:
                for key, h in (r.get("metrics", {})
                               .get("histograms") or {}).items():
                    if sum(h.get("counts", [])) != h.get("count"):
                        problems.append(
                            f"snapshot seq {r.get('seq')}: histogram "
                            f"{key} bucket counts sum to "
                            f"{sum(h.get('counts', []))} but count is "
                            f"{h.get('count')}")
            drained = [r for r in snapshots if r.get("drained")]
            if drained:
                last = drained[-1]
                hists = last.get("metrics", {}).get("histograms") or {}
                ttft = hists.get("serve_ttft_s")
                window = []
                for r in records:
                    if r.get("kind") == "serve.stats_reset":
                        window = []
                    elif r.get("kind") == "serve.request":
                        window.append(r)
                with_token = sum(1 for r in window
                                 if r.get("new_tokens", 0) > 0)
                if ttft is not None and ttft.get("count") != with_token:
                    problems.append(
                        f"drained snapshot: serve_ttft_s count "
                        f"({ttft.get('count')}) != completed-with-token "
                        f"requests ({with_token})")
        if problems:
            print("run_doctor: CHECK FAILED: " + "; ".join(problems),
                  file=sys.stderr)
            sys.exit(1)

    d = diagnose(args.directory)
    out = (json.dumps(d, indent=1, default=str) if args.as_json
           else render(d))
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
        print(f"run_doctor: report written to {args.out}")
    else:
        print(out)
    if args.strict and d["verdict"] == "dead":
        sys.exit(3)


if __name__ == "__main__":
    main()
