"""ffscope smoke: profiled fit + injected stall, then verify artifacts.

The CI gate for the op-grain observability plane (docs/observability.md
"ffscope"): one small model on the virtual CPU mesh goes through a fit
with

  1. sampled op-grain profiling on (--profile-every 2): profiled steps
     run under jax.profiler tracing and their attributed per-op device
     time lands in strategy_report.json's `profile` section;
  2. the hang watchdog armed (--watchdog-timeout) plus a fault hook
     that stalls one step past the deadline, so the watchdog fires
     mid-fit, dumps flight.json, and names the lagging host from the
     file heartbeat channel;

then verifies everything FROM THE ARTIFACTS ALONE:

  - the profile section carries a measured column for every report op,
    at least one op measured > 0, and the attribution identity
    (Σ attributed ≤ step device time × parallelism within the stated
    slop; fidelity recomputable from measured/predicted) re-verifies;
  - flight.json parses, is a bounded ring dump (events ≤ capacity),
    records reason "watchdog", and names the lagging host;
  - alerts.jsonl carries the hang_watchdog alert;
  - the markdown report renders the measured-vs-predicted table.

ci.yml then runs scripts/run_doctor.py --check on the same dir — the
doctor re-derives the attribution identity and flight-dump
well-formedness independently.

Usage: python scripts/scope_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on any violated identity.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

STALL_STEP = 5
STALL_S = 2.0


def fail(msg: str):
    print(f"scope_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )
    from flexflow_tpu.scope.attribution import verify_profile_section
    from flexflow_tpu.telemetry import read_jsonl

    config = FFConfig()  # parses --telemetry-dir / --profile-every etc.
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    if not config.profile_every:
        config.profile_every = 2
    if not config.watchdog_timeout:
        config.watchdog_timeout = 0.8  # STALL_S must exceed this

    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 16)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])

    def stall(step):
        if step == STALL_STEP:
            time.sleep(STALL_S)

    ff.set_fault_hook(stall)
    rs = np.random.RandomState(0)
    n = 32 * 8  # 8 steps: captures at 2/4/6/8, stall at 5
    ff.fit(rs.randn(n, 64).astype(np.float32),
           rs.randint(0, 16, (n, 1)).astype(np.int32),
           epochs=1, batch_size=32, verbose=False)
    ff.get_telemetry().close()

    tdir = config.telemetry_dir

    # ---- profile section: per-op measured next to predicted -----------
    rep = json.load(open(os.path.join(tdir, "strategy_report.json")))
    prof = rep.get("profile")
    if prof is None:
        fail("strategy_report.json has no profile section "
             "(--profile-every capture never landed)")
    if prof.get("source") != "xplane":
        fail(f"profile source {prof.get('source')!r}, expected 'xplane'")
    rows = {r["name"]: r for r in prof["ops"]}
    missing = [o["name"] for o in rep["ops"] if o["name"] not in rows]
    if missing:
        fail(f"report ops without a measured row: {missing}")
    total_measured = sum(r["measured_s"] for r in prof["ops"])
    if not total_measured > 0:
        fail("no device time attributed to any op")
    problems = verify_profile_section(prof)
    if problems:
        fail("attribution identity violated: " + "; ".join(problems))
    with_fid = [r for r in prof["ops"]
                if r.get("predicted_s") and r.get("fidelity")]
    if not with_fid:
        fail("no op carries a recomputable fidelity ratio")
    md = open(os.path.join(tdir, "strategy_report.md")).read()
    if "Measured profile (ffscope)" not in md:
        fail("markdown report missing the measured profile table")

    # ---- watchdog + flight record -------------------------------------
    fpath = os.path.join(tdir, "flight.json")
    if not os.path.exists(fpath):
        fail("flight.json missing (watchdog never fired on the stall)")
    flight = json.load(open(fpath))
    if flight.get("kind") != "flight_record":
        fail(f"flight.json kind {flight.get('kind')!r}")
    if flight.get("reason") != "watchdog":
        fail(f"flight reason {flight.get('reason')!r}, expected "
             f"'watchdog'")
    if len(flight["events"]) > flight["capacity"]:
        fail(f"ring bound violated: {len(flight['events'])} events > "
             f"capacity {flight['capacity']}")
    wd = flight.get("watchdog") or {}
    if wd.get("lagging_host") is None:
        fail(f"watchdog dump does not name the lagging host: {wd}")
    if not wd.get("stalled_s", 0) > config.watchdog_timeout:
        fail(f"recorded stall {wd.get('stalled_s')}s under the "
             f"{config.watchdog_timeout}s deadline")
    alerts = read_jsonl(os.path.join(tdir, "alerts.jsonl"))
    hang = [a for a in alerts if a.get("rule") == "hang_watchdog"]
    if not hang:
        fail("no hang_watchdog alert in alerts.jsonl")

    print(f"scope_smoke: OK — {len(with_fid)} ops with fidelity "
          f"(total measured {total_measured * 1e3:.2f} ms over "
          f"{prof['parallelism']} lines), watchdog fired after "
          f"{wd['stalled_s']:.2f}s stall naming host "
          f"{wd['lagging_host']}, flight ring "
          f"{len(flight['events'])}/{flight['capacity']} events")


if __name__ == "__main__":
    main()
