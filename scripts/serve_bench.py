"""Serving benchmark: requests/s/chip, decode tokens/s/chip, and the
paged-KV headline — slots-at-fixed-HBM under a shared-prefix trace.

The standalone driver for the ROADMAP's serving metrics — bench.py embeds
the same measurements as its serving leg; this script runs them alone
with tunable load, for serving-focused profiling:

  python scripts/serve_bench.py [--requests N] [--slots S]
      [--prompt-len P] [--max-new-tokens T] [--shared-prefix K]
      [--layout paged|contiguous|both] [--telemetry-dir DIR]
      [flexflow flags]

--shared-prefix K (default: prompt-len // 2) prepends one K-token system
prompt to every request — the N-users-one-system-prompt trace the paged
layout's copy-on-write prefix sharing exists for. With --layout both
(default) the same trace runs through both KV layouts and the report
carries, next to each layout's req/s/chip:

  - prefix_hit_rate / cow_copies (paged),
  - kv_hbm_bytes_per_layer resident per layout, and
  - slots_at_fixed_hbm: contiguous KV rows ÷ the paged PEAK working set
    — how many more concurrent max_seq slots the pool recovers at equal
    HBM (vLLM's capacity metric; the ISSUE 11 acceptance bar is >= 2x).

Prints one JSON line per metric, the full per-layout payload last.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pop_int(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = int(argv[i + 1])
        del argv[i:i + 2]
        return val
    return default


def _pop_str(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = argv[i + 1]
        del argv[i:i + 2]
        return val
    return default


def run_trace(ff, layout, prompts, slots, max_new, **serve_kw):
    """Drain `prompts` through a fresh engine of `layout`; returns
    (completions, stats) with the measured window warmed + reset."""
    kw = {"max_new_tokens": max_new, "kv_layout": layout, **serve_kw}
    if slots:
        kw["slots"] = slots
    engine = ff.serve(**kw)
    # warm the bucket/decode/copy executables so the measured drain is
    # steady state
    engine.generate(prompts[:1])
    engine.reset_stats()
    for p in prompts:
        engine.submit(p)
    engine.run_until_drained()
    return [r.generated for r in engine.scheduler.completed], engine.stats()


def main():
    argv = sys.argv[1:]
    n_requests = _pop_int(argv, "--requests", 16)
    slots = _pop_int(argv, "--slots", 0)  # 0 → FFConfig default
    prompt_len = _pop_int(argv, "--prompt-len", 8)
    max_new = _pop_int(argv, "--max-new-tokens", 16)
    shared_prefix = _pop_int(argv, "--shared-prefix", prompt_len // 2)
    kv_block_size = _pop_int(argv, "--kv-block-size", 0)
    layout = _pop_str(argv, "--layout", "both")
    sys.argv = [sys.argv[0]] + argv
    if not kv_block_size:
        # block granularity must divide INTO the shared prefix for the
        # sharing to be visible; half the prefix keeps at least one full
        # shared block plus a partial tail (the COW case)
        kv_block_size = max(2, min(16, shared_prefix // 2)) \
            if shared_prefix >= 4 else 0

    import jax
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        lm = TransformerLMConfig(vocab_size=32000, hidden_size=1024,
                                 num_heads=16, num_layers=12,
                                 sequence_length=512,
                                 attention_impl="flash")
    else:
        lm = TransformerLMConfig(vocab_size=256, hidden_size=64,
                                 num_heads=4, num_layers=2,
                                 sequence_length=64, attention_impl="xla")
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # the shared-prefix trace: one system prompt opens every request
    # (served alone first so the partial tail block registers and later
    # extensions exercise COW), distinct suffixes after it
    rs = np.random.RandomState(0)
    system = rs.randint(1, lm.vocab_size, shared_prefix).tolist()
    tail = max(1, prompt_len - shared_prefix)
    prompts = [
        system + rs.randint(1, lm.vocab_size, tail).tolist()
        if (i or not system) else list(system)
        for i in range(n_requests)]

    serve_kw = {"kv_block_size": kv_block_size} if kv_block_size else {}
    layouts = ("paged", "contiguous") if layout == "both" else (layout,)
    results = {}
    completions = {}
    for lay in layouts:
        completions[lay], results[lay] = run_trace(
            ff, lay, prompts, slots, max_new,
            **(serve_kw if lay == "paged" else {}))
        print(json.dumps({
            "metric": f"serving_requests_per_sec_per_chip_{lay}",
            "value": round(
                results[lay].get("requests_per_sec_per_chip", 0.0), 4),
            "unit": "req/s",
        }))
    if layout == "both" and completions["paged"] != completions["contiguous"]:
        print("serve_bench: FAIL — paged completions diverge from "
              "contiguous", file=sys.stderr)
        sys.exit(1)

    payload = {"shared_prefix": shared_prefix, "requests": n_requests,
               "prompt_len": prompt_len, "max_new_tokens": max_new,
               **{lay: results[lay] for lay in layouts}}
    if "paged" in results:
        st = results["paged"]
        print(json.dumps({
            "metric": "serving_prefix_hit_rate",
            "value": round(st.get("prefix_hit_rate", 0.0), 4),
        }))
        if "contiguous" in results:
            # the engine computes this under `kv_peak_vs_contiguous`
            # (serving/engine.py stats()) — one definition, read here
            payload["slots_at_fixed_hbm"] = round(
                st["kv_peak_vs_contiguous"], 4)
            print(json.dumps({
                "metric": "serving_slots_at_fixed_hbm",
                "value": payload["slots_at_fixed_hbm"],
                "unit": "x contiguous",
            }))
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
