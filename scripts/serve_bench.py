"""Serving benchmark: requests/s/chip, decode tokens/s/chip, and the
paged-KV headline — slots-at-fixed-HBM under a shared-prefix trace.

The standalone driver for the ROADMAP's serving metrics — bench.py embeds
the same measurements as its serving leg; this script runs them alone
with tunable load, for serving-focused profiling:

  python scripts/serve_bench.py [--requests N] [--slots S]
      [--prompt-len P] [--max-new-tokens T] [--shared-prefix K]
      [--arrival-rate R] [--burst B] [--layout paged|contiguous|both]
      [--disaggregate] [--speculate [--draft-chips D]]
      [--telemetry-dir DIR] [flexflow flags]

--shared-prefix K (default: prompt-len // 2) prepends one K-token system
prompt to every request — the N-users-one-system-prompt trace the paged
layout's copy-on-write prefix sharing exists for.

--arrival-rate R > 0 switches from closed-loop (all requests queued up
front, back-to-back stepping) to OPEN-loop load: requests arrive on a
seeded Poisson process at R req/s, so queue wait and tail latency come
from arrival pressure, not from the drain order — the load model tail
percentiles are honest under. --burst B >= 1 modulates it: alternating
windows of 8 arrivals have their inter-arrival gaps divided by B (a
bursty trace at the same average rate). The report then carries
TTFT/TBT/queue-wait p50/p95/p99 from the engine's mergeable histograms
(engine.metrics_summary).

--disaggregate replaces the layout ablation with the DISAGGREGATION
ablation: the identical trace runs through the unified paged engine and
through serve(disaggregate=True) (split prefill/decode pools at the same
total chip count, KV moved per request by verified fftrans handoffs),
completions asserted bit-identical, and the payload carries both sides'
TTFT/TBT/queue-wait percentiles plus the handoff measured-vs-predicted
seconds — the ISSUE 19 acceptance harness for "disagg + radix cache
improves TTFT p95 at equal chips on the bursty shared-prefix trace".

--speculate replaces the layout ablation with the SPECULATION ablation
(docs/serving.md "Speculative decoding"): the identical trace runs
through the plain paged engine and through serve(speculate=True,
draft_model=...) with a seed-clone drafter (--draft-chips D > 0 places
it on a disjoint sub-mesh), completions asserted bit-identical, and the
payload carries both sides' TBT percentiles plus the acceptance rate,
round count, and payoff-gate decision tally — the ISSUE 20 ablation leg
for "speculation reduces TBT when the payoff inequality holds".

With --layout both (default) the same trace runs through both KV layouts
and the report carries, next to each layout's req/s/chip:

  - prefix_hit_rate / cow_copies (paged),
  - kv_hbm_bytes_per_layer resident per layout, and
  - slots_at_fixed_hbm: contiguous KV rows ÷ the paged PEAK working set
    — how many more concurrent max_seq slots the pool recovers at equal
    HBM (vLLM's capacity metric; the ISSUE 11 acceptance bar is >= 2x).

Prints one JSON line per metric, the full per-layout payload last.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pop_int(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = int(argv[i + 1])
        del argv[i:i + 2]
        return val
    return default


def _pop_str(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = argv[i + 1]
        del argv[i:i + 2]
        return val
    return default


def _pop_float(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = float(argv[i + 1])
        del argv[i:i + 2]
        return val
    return default


def _pop_flag(argv, flag):
    if flag in argv:
        argv.remove(flag)
        return True
    return False


def _drained(engine):
    """Both engine shapes: the disaggregated coordinator exposes its own
    drained property (covers both schedulers + pending handoffs)."""
    if hasattr(engine, "prefill_chips"):
        return engine.drained
    return engine.scheduler.drained


def _completed(engine):
    if hasattr(engine, "prefill_chips"):
        return engine.completed
    return engine.scheduler.completed


def open_loop_offsets(n, rate, burst, rs):
    """Seeded bursty-Poisson arrival offsets (seconds from window start):
    exponential inter-arrival gaps at `rate` req/s, with every other
    window of 8 arrivals compressed by `burst` — bursts at the same
    long-run average rate, the trace shape TTFT p95 is judged under."""
    import numpy as np

    gaps = rs.exponential(1.0 / rate, size=n)
    if burst > 1.0:
        for i in range(n):
            if (i // 8) % 2 == 0:
                gaps[i] /= burst
    return np.cumsum(gaps)


def run_trace(ff, layout, prompts, slots, max_new, arrival_rate=0.0,
              burst=1.0, disaggregate=False, speculate=False,
              draft_model=None, draft_chips=0, warm="slots", **serve_kw):
    """Run `prompts` through a fresh engine of `layout`; returns
    (completions, metrics_summary) with the measured window warmed +
    reset. arrival_rate > 0 drives the trace open-loop (submission by
    wall clock on a seeded bursty-Poisson process); otherwise all
    requests queue up front and the engine drains closed-loop.
    disaggregate=True routes through serve(disaggregate=True) — split
    prefill/decode pools at the same total chip count."""
    import time

    import numpy as np

    kw = {"max_new_tokens": max_new, "kv_layout": layout, **serve_kw}
    if slots:
        kw["slots"] = slots
    if disaggregate:
        kw["disaggregate"] = True
    if speculate:
        kw["speculate"] = True
        kw["draft_model"] = draft_model
        if draft_chips:
            kw["draft_chips"] = draft_chips
    engine = ff.serve(**kw)
    # warm the bucket/decode/copy executables so the measured drain is
    # steady state: a full slot-width batch compiles every decode batch
    # bucket (and, disaggregated, both sides' buckets + the KV-inject
    # programs) — a 1-request warmup leaves those compiles inside the
    # measured window, where they read as multi-second TTFT/TBT spikes.
    # warm="trace" pre-runs the whole trace once instead (the
    # disaggregation comparison below measures the steady state, where
    # every radix-hit-shrunk inject extent has already compiled)
    nwarm = (len(prompts) if warm == "trace"
             else max(1, min(len(prompts), slots or 1)))
    engine.generate(prompts[:nwarm])
    engine.reset_stats()
    if arrival_rate > 0:
        offsets = open_loop_offsets(
            len(prompts), arrival_rate, burst, np.random.RandomState(7))
        t0 = time.perf_counter()
        i = 0
        while i < len(prompts) or not _drained(engine):
            now = time.perf_counter() - t0
            while i < len(prompts) and offsets[i] <= now:
                engine.submit(prompts[i])
                i += 1
            if _drained(engine):
                # idle between bursts: sleep to the next arrival instead
                # of spinning (open loop — the clock, not the engine,
                # paces submissions)
                time.sleep(max(0.0, offsets[i]
                               - (time.perf_counter() - t0)))
                continue
            engine.step()
        engine.note_drain(time.perf_counter() - t0)
    else:
        for p in prompts:
            engine.submit(p)
        engine.run_until_drained()
    done = sorted(_completed(engine),
                  key=lambda r: r.request_id)  # submission order: the
    # cross-layout parity check must not depend on completion timing
    stats = engine.metrics_summary()
    if speculate:
        # the speculation accounting for the measured window: acceptance
        # rate, rounds, and how the payoff gate actually decided
        stats["speculation"] = engine.stats()["speculation"]
    if disaggregate:
        # lift the per-side request-grain percentiles to the flat keys
        # the payload loop below reads: TTFT + queue wait observe on the
        # prefill side, TBT on the decode side
        for short, side in (("ttft", "prefill"), ("queue_wait", "prefill"),
                            ("tbt", "decode")):
            for q in ("p50", "p95", "p99"):
                key = f"{short}_{q}_s"
                v = (stats.get(side) or {}).get(key)
                if v is not None and key not in stats:
                    stats[key] = v
    return [r.generated for r in done], stats


def main():
    argv = sys.argv[1:]
    n_requests = _pop_int(argv, "--requests", 16)
    slots = _pop_int(argv, "--slots", 0)  # 0 → FFConfig default
    prompt_len = _pop_int(argv, "--prompt-len", 8)
    max_new = _pop_int(argv, "--max-new-tokens", 16)
    shared_prefix = _pop_int(argv, "--shared-prefix", prompt_len // 2)
    kv_block_size = _pop_int(argv, "--kv-block-size", 0)
    arrival_rate = _pop_float(argv, "--arrival-rate", 0.0)
    burst = _pop_float(argv, "--burst", 1.0)
    layout = _pop_str(argv, "--layout", "both")
    disaggregate = _pop_flag(argv, "--disaggregate")
    speculate = _pop_flag(argv, "--speculate")
    draft_chips = _pop_int(argv, "--draft-chips", 0)
    if disaggregate and speculate:
        print("serve_bench: --disaggregate and --speculate are mutually "
              "exclusive", file=sys.stderr)
        sys.exit(2)
    sys.argv = [sys.argv[0]] + argv
    if not kv_block_size:
        # block granularity must divide INTO the shared prefix for the
        # sharing to be visible; half the prefix keeps at least one full
        # shared block plus a partial tail (the COW case). Disaggregated
        # runs pin radix prefixes across time, so they need FINE blocks
        # and the deep pool they imply — the half-prefix heuristic at
        # e.g. 21 shared tokens yields 10-token blocks and a ~29-block
        # pool that thrashes between pinned prefixes and live decodes
        if disaggregate:
            kv_block_size = 4 if shared_prefix >= 4 else 0
        else:
            kv_block_size = max(2, min(16, shared_prefix // 2)) \
                if shared_prefix >= 4 else 0

    import jax
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        lm = TransformerLMConfig(vocab_size=32000, hidden_size=1024,
                                 num_heads=16, num_layers=12,
                                 sequence_length=512,
                                 attention_impl="flash")
    else:
        # sequence length follows the requested trace: a 48-token prompt
        # with a 32-token budget must not silently truncate to "length"
        # finishes at the model's 64-row KV ceiling
        seq = 64
        while seq < prompt_len + max_new + 8:
            seq *= 2
        lm = TransformerLMConfig(vocab_size=256, hidden_size=64,
                                 num_heads=4, num_layers=2,
                                 sequence_length=seq, attention_impl="xla")
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    draft = None
    if speculate:
        # seed-clone drafter: identical weights put acceptance at its
        # upper extreme, so the leg measures the verify-path ceiling
        # (the TRANSFORMER_LM_ZOO *-draft tiers are the realistic
        # trained drafters; untrained random weights would reject ~all
        # proposals and measure nothing)
        dconfig = FFConfig()
        dconfig.batch_size = 8
        draft = FFModel(dconfig)
        build_transformer_lm(draft, lm, batch_size=8)
        draft.compile(
            optimizer=SGDOptimizer(lr=0.01),
            loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # the shared-prefix trace: one system prompt opens every request
    # (served alone first so the partial tail block registers and later
    # extensions exercise COW), distinct suffixes after it
    rs = np.random.RandomState(0)
    system = rs.randint(1, lm.vocab_size, shared_prefix).tolist()
    tail = max(1, prompt_len - shared_prefix)
    prompts = [
        system + rs.randint(1, lm.vocab_size, tail).tolist()
        if (i or not system) else list(system)
        for i in range(n_requests)]

    serve_kw = {"kv_block_size": kv_block_size} if kv_block_size else {}
    if disaggregate:
        # the acceptance comparison: unified paged vs disaggregated on
        # the IDENTICAL trace at equal total chips — TTFT/TBT/queue-wait
        # percentiles print side by side under the _paged/_disagg keys
        layouts = ("paged", "disagg")
    elif speculate:
        # plain decode vs drafter+verify on the IDENTICAL trace — TBT
        # percentiles print side by side under the _paged/_spec keys
        layouts = ("paged", "spec")
    else:
        layouts = (("paged", "contiguous") if layout == "both"
                   else (layout,))
    results = {}
    completions = {}
    for lay in layouts:
        extra = dict(serve_kw) if lay in ("paged", "disagg", "spec") else {}
        if disaggregate and lay == "paged":
            # the acceptance baseline is the unified r16 engine: prefix
            # sharing spans LIVE residents only (no cross-time radix
            # cache) — what the unified path was before ISSUE 19
            extra["prefix_cache"] = False
        completions[lay], results[lay] = run_trace(
            ff, "paged" if lay in ("disagg", "spec") else lay, prompts,
            slots, max_new, arrival_rate=arrival_rate, burst=burst,
            disaggregate=(lay == "disagg"), speculate=(lay == "spec"),
            draft_model=draft, draft_chips=draft_chips,
            warm="trace" if (disaggregate or speculate) else "slots",
            **extra)
        print(json.dumps({
            "metric": f"serving_requests_per_sec_per_chip_{lay}",
            "value": round(
                results[lay].get("requests_per_sec_per_chip", 0.0), 4),
            "unit": "req/s",
        }))
        # request-grain latency percentiles from the engine's mergeable
        # histograms (present whenever the window saw the observation)
        for short in ("ttft", "tbt", "queue_wait"):
            for q in ("p50", "p95", "p99"):
                key = f"{short}_{q}_s"
                if key in results[lay]:
                    print(json.dumps({
                        "metric": f"serving_{short}_{q}_s_{lay}",
                        "value": round(results[lay][key], 6),
                        "unit": "s",
                    }))
    if ("contiguous" in completions
            and completions["paged"] != completions["contiguous"]):
        print("serve_bench: FAIL — paged completions diverge from "
              "contiguous", file=sys.stderr)
        sys.exit(1)
    if "disagg" in completions:
        if completions["disagg"] != completions["paged"]:
            print("serve_bench: FAIL — disaggregated completions diverge "
                  "from the unified paged engine", file=sys.stderr)
            sys.exit(1)
        print(json.dumps({
            "metric": "serving_disagg_ttft_p95_s",
            "value": results["disagg"].get("ttft_p95_s"),
            "unified_ttft_p95_s": results["paged"].get("ttft_p95_s"),
            "handoffs": results["disagg"].get("handoffs", 0),
            "handoff_predicted_s": round(
                results["disagg"].get("handoff_predicted_s", 0.0), 6),
            "handoff_measured_s": round(
                results["disagg"].get("handoff_measured_s", 0.0), 6),
            "unit": "s",
        }))
    if "spec" in completions:
        if completions["spec"] != completions["paged"]:
            print("serve_bench: FAIL — speculative completions diverge "
                  "from plain decode", file=sys.stderr)
            sys.exit(1)
        sp = results["spec"].get("speculation", {})
        print(json.dumps({
            "metric": "serving_spec_tbt_p95_s",
            "value": results["spec"].get("tbt_p95_s"),
            "plain_tbt_p95_s": results["paged"].get("tbt_p95_s"),
            "acceptance_rate": round(sp.get("acceptance_rate", 0.0), 4),
            "rounds": sp.get("rounds", 0),
            "decision_counts": sp.get("decision_counts", {}),
            "draft_chips": draft_chips,
            "unit": "s",
        }))

    payload = {"shared_prefix": shared_prefix, "requests": n_requests,
               "prompt_len": prompt_len, "max_new_tokens": max_new,
               "arrival_rate": arrival_rate, "burst": burst,
               "load_model": "open" if arrival_rate > 0 else "closed",
               **{lay: results[lay] for lay in layouts}}
    if "paged" in results:
        st = results["paged"]
        print(json.dumps({
            "metric": "serving_prefix_hit_rate",
            "value": round(st.get("prefix_hit_rate", 0.0), 4),
        }))
        if "contiguous" in results:
            # the engine computes this under `kv_peak_vs_contiguous`
            # (serving/engine.py stats()) — one definition, read here
            payload["slots_at_fixed_hbm"] = round(
                st["kv_peak_vs_contiguous"], 4)
            print(json.dumps({
                "metric": "serving_slots_at_fixed_hbm",
                "value": payload["slots_at_fixed_hbm"],
                "unit": "x contiguous",
            }))
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
