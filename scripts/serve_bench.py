"""Serving benchmark: requests/s/chip + decode tokens/s/chip.

The standalone driver for the ROADMAP's serving metric ("target a
requests/sec/chip bench leg next to the training slope metric") —
bench.py embeds the same measurement as its serving leg; this script runs
it alone with tunable load, for serving-focused profiling:

  python scripts/serve_bench.py [--requests N] [--slots S]
      [--prompt-len P] [--max-new-tokens T] [--telemetry-dir DIR]
      [flexflow flags]

Prints one JSON line per metric, the full stats payload last.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pop_int(argv, flag, default):
    if flag in argv:
        i = argv.index(flag)
        val = int(argv[i + 1])
        del argv[i:i + 2]
        return val
    return default


def main():
    argv = sys.argv[1:]
    n_requests = _pop_int(argv, "--requests", 16)
    slots = _pop_int(argv, "--slots", 0)  # 0 → FFConfig default
    prompt_len = _pop_int(argv, "--prompt-len", 8)
    max_new = _pop_int(argv, "--max-new-tokens", 16)
    sys.argv = [sys.argv[0]] + argv

    import jax
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        lm = TransformerLMConfig(vocab_size=32000, hidden_size=1024,
                                 num_heads=16, num_layers=12,
                                 sequence_length=512,
                                 attention_impl="flash")
    else:
        lm = TransformerLMConfig(vocab_size=256, hidden_size=64,
                                 num_heads=4, num_layers=2,
                                 sequence_length=64, attention_impl="xla")
    config = FFConfig()
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    kw = {"max_new_tokens": max_new}
    if slots:
        kw["slots"] = slots
    engine = ff.serve(**kw)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, lm.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    # warm the bucket + decode executables so the measured drain is steady
    # state, then reset accounting by building the measured run fresh
    engine.generate(prompts[:1])
    engine.reset_stats()
    for p in prompts:
        engine.submit(p)
    engine.run_until_drained()
    stats = engine.stats()
    print(json.dumps({
        "metric": "serving_requests_per_sec_per_chip",
        "value": round(stats.get("requests_per_sec_per_chip", 0.0), 4),
        "unit": "req/s",
    }))
    print(json.dumps({
        "metric": "serving_decode_tokens_per_sec_per_chip",
        "value": round(stats.get("decode_tokens_per_sec_per_chip", 0.0), 2),
        "unit": "tokens/s",
    }))
    print(json.dumps(stats))


if __name__ == "__main__":
    main()
