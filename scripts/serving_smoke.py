"""Serving smoke: serve synthetic requests on the CPU mesh, validate
artifacts — the CI gate for the serving subsystem (docs/serving.md).

Runs a small Transformer LM, builds the serving engine for BOTH KV
layouts (paged — the default — twice, plus contiguous), and asserts

  - every request completes, with tokens and a finish reason;
  - greedy decode is token-identical between the two paged engines AND
    bit-for-bit identical between the paged and contiguous layouts;
  - a shared-prefix trace (every prompt opens with one system prompt)
    reports prefix_hit_rate > 0 and at least one COW copy, with a paged
    peak working set smaller than the contiguous cache;
  - telemetry carries the serving surface: serve.compile (plan_source,
    kv_layout), one serve.request per completion (TTFT > 0), a
    serve.summary with requests/s/chip + decode tokens/s/chip +
    prefix_hit_rate, and the serve.prefill / serve.step trace spans;
  - with --warmstart-dir, the SECOND paged engine's compile is a
    plan-cache hit (plan_source == "cache") while the contiguous compile
    still searches — the layouts never share a cache address.

Usage:
  python scripts/serving_smoke.py --telemetry-dir OUT \
      [--warmstart-dir WS --mesh 2,4,1,1 --budget 4 \
       --enable-parameter-parallel] [flexflow flags]
Exits nonzero with a diagnostic on any missing artifact/field.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

NUM_REQUESTS = 6


def fail(msg: str):
    print(f"serving_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl

    config = FFConfig()  # parses --telemetry-dir/--warmstart-dir/... from argv
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    lm = TransformerLMConfig(vocab_size=128, hidden_size=32, num_heads=4,
                             num_layers=2, sequence_length=32,
                             attention_impl="xla")
    # the TRAIN compile stays data-parallel (fast); the search flags on
    # argv apply to the DECODE compiles via config_overrides below
    search_overrides = dict(
        only_data_parallel=config.only_data_parallel,
        search_budget=config.search_budget,
        enable_parameter_parallel=config.enable_parameter_parallel,
        enable_attribute_parallel=config.enable_attribute_parallel,
        search_calibrate=config.search_calibrate,
        warmstart_dir=config.warmstart_dir,
    )
    config.only_data_parallel = True
    config.warmstart_dir = ""
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, lm.vocab_size, rs.randint(2, 9)).tolist()
               for _ in range(NUM_REQUESTS)]
    serve_kw = dict(slots=4, max_new_tokens=8, prefill_chunk=4,
                    config_overrides=search_overrides)

    engine = ff.serve(**serve_kw)
    outputs = engine.generate(prompts)
    stats = engine.stats()
    if stats["requests_completed"] != NUM_REQUESTS:
        fail(f"completed {stats['requests_completed']}/{NUM_REQUESTS}")
    for i, (req, out) in enumerate(zip(engine.scheduler.completed, outputs)):
        if not out:
            fail(f"request {i} produced no tokens")
        if req.finish_reason not in ("max_tokens", "eos", "length"):
            fail(f"request {i} has no finish reason")

    # second engine: token identity always; plan-cache hit with a
    # populated --warmstart-dir
    engine2 = ff.serve(**serve_kw)
    if engine2.generate(prompts) != outputs:
        fail("second engine's greedy output differs (determinism broken)")
    if search_overrides["warmstart_dir"]:
        if engine.decode_model._plan_source != "search":
            fail(f"first serving compile expected plan_source=search, got "
                 f"{engine.decode_model._plan_source!r}")
        if engine2.decode_model._plan_source != "cache":
            fail(f"second serving compile expected plan_source=cache, got "
                 f"{engine2.decode_model._plan_source!r} (warm-start plan "
                 f"cache missed)")

    # ---- layout parity: the contiguous ablation layout must be
    # bit-for-bit token-identical to the paged default (and with a
    # warm-start dir its plan must MISS the paged entry: the layouts
    # never share a cache address)
    contig = ff.serve(kv_layout="contiguous", **serve_kw)
    if contig.generate(prompts) != outputs:
        fail("contiguous layout's completions differ from paged "
             "(layouts must be bit-for-bit identical)")
    if search_overrides["warmstart_dir"] and \
            contig.decode_model._plan_source == "cache":
        fail("contiguous compile hit the paged plan-cache entry — the "
             "kv layout is missing from the fingerprint")

    # ---- shared-prefix trace: a 9-token system prompt (deliberately NOT
    # block-aligned at kv_block_size=4, so extensions diverge INSIDE its
    # partial tail block and must COW), served alone first, then extended
    # by every later request; the paged engine must report prefix reuse
    # and a peak working set under the contiguous cache's footprint
    system = rs.randint(1, lm.vocab_size, 9).tolist()
    trace = [list(system)] + [
        system + rs.randint(1, lm.vocab_size,
                            rs.randint(1, 5)).tolist()
        for _ in range(NUM_REQUESTS - 1)]
    paged_sp = ff.serve(kv_block_size=4, **serve_kw)
    sp_out = paged_sp.generate(trace)
    sp_stats = paged_sp.stats()
    if not sp_stats.get("prefix_hit_rate", 0) > 0:
        fail(f"shared-prefix trace reported no prefix reuse: {sp_stats}")
    if not sp_stats.get("cow_copies", 0) > 0:
        fail("shared-prefix trace triggered no copy-on-write")
    peak_rows = (sp_stats["kv_blocks_in_use_peak"]
                 * sp_stats["kv_block_size"])
    contig_rows = sp_stats["slots"] * (sp_stats["max_seq_len"] + 1)
    if not sp_stats.get("kv_peak_vs_contiguous", 0) > 1:
        fail(f"paged peak KV rows {peak_rows} not under the contiguous "
             f"footprint {contig_rows}")
    contig_sp = ff.serve(kv_layout="contiguous", **serve_kw)
    if contig_sp.generate(trace) != sp_out:
        fail("shared-prefix trace: paged completions diverge from "
             "contiguous (COW reuse must be bit-for-bit invisible)")
    ff.get_telemetry().close()

    # ---- artifact validation
    tdir = config.telemetry_dir
    recs = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    compiles = [r for r in recs if r["kind"] == "serve.compile"]
    if len(compiles) != 5:
        fail(f"expected 5 serve.compile records, got {len(compiles)}")
    for c in compiles:
        for field in ("plan_source", "slots", "max_seq_len", "duration_s",
                      "kv_layout"):
            if field not in c:
                fail(f"serve.compile missing {field}: {c}")
    layouts = [c["kv_layout"] for c in compiles]
    if layouts != ["paged", "paged", "contiguous", "paged", "contiguous"]:
        fail(f"unexpected serve.compile kv_layout sequence: {layouts}")
    reqs = [r for r in recs if r["kind"] == "serve.request"]
    if len(reqs) != 5 * NUM_REQUESTS:
        fail(f"expected {5 * NUM_REQUESTS} serve.request records, "
             f"got {len(reqs)}")
    for r in reqs:
        if not (r.get("ttft_s") or 0) > 0:
            fail(f"serve.request without ttft_s: {r}")
        if "finish_reason" not in r or "new_tokens" not in r:
            fail(f"malformed serve.request: {r}")
    summaries = [r for r in recs if r["kind"] == "serve.summary"]
    if len(summaries) < 5:
        fail(f"expected >=5 serve.summary records, got {len(summaries)}")
    for field in ("requests_per_sec_per_chip",
                  "decode_tokens_per_sec_per_chip", "ttft_p50_s",
                  "decode_iterations"):
        if not (summaries[-1].get(field, 0) > 0):
            fail(f"serve.summary field {field} missing/zero: "
                 f"{summaries[-1]}")
    # the shared-prefix paged drain is the second-to-last summary; its
    # reuse metrics must have landed in the archived artifact too
    paged_summ = [s for s in summaries if s.get("kv_layout") == "paged"]
    if not any(s.get("prefix_hit_rate", 0) > 0 for s in paged_summ):
        fail("no archived serve.summary carries prefix_hit_rate > 0")

    with open(os.path.join(tdir, "trace.json")) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    for span in ("serve.compile", "serve.prefill", "serve.step"):
        if span not in names:
            fail(f"trace missing span {span!r} (have {sorted(names)})")

    summ = summaries[-1]
    print(f"serving_smoke: OK — {NUM_REQUESTS} requests x5 engines "
          f"(paged x3 + contiguous x2, bit-identical), "
          f"plan {compiles[0]['plan_source']}->{compiles[1]['plan_source']}, "
          f"prefix_hit_rate={sp_stats['prefix_hit_rate']:.2f} "
          f"cow={sp_stats['cow_copies']} "
          f"kv_peak_rows={peak_rows}/{contig_rows} "
          f"ttft_p50={summ['ttft_p50_s'] * 1e3:.1f}ms "
          f"req/s/chip={summ['requests_per_sec_per_chip']:.2f} "
          f"decode tok/s/chip={summ['decode_tokens_per_sec_per_chip']:.1f}")


if __name__ == "__main__":
    main()
