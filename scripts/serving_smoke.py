"""Serving smoke: serve synthetic requests on the CPU mesh, validate
artifacts — the CI gate for the serving subsystem (docs/serving.md).

Runs a small Transformer LM, builds the serving engine TWICE, and asserts

  - every request completes, with tokens and a finish reason;
  - greedy decode is token-identical between the two engines;
  - telemetry carries the serving surface: serve.compile (plan_source),
    one serve.request per completion (TTFT > 0), a serve.summary with
    requests/s/chip + decode tokens/s/chip, and the serve.prefill /
    serve.step trace spans;
  - with --warmstart-dir, the SECOND engine's compile is a plan-cache hit
    (plan_source == "cache") — the serving acceptance criterion.

Usage:
  python scripts/serving_smoke.py --telemetry-dir OUT \
      [--warmstart-dir WS --mesh 2,4,1,1 --budget 4 \
       --enable-parameter-parallel] [flexflow flags]
Exits nonzero with a diagnostic on any missing artifact/field.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

NUM_REQUESTS = 6


def fail(msg: str):
    print(f"serving_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl

    config = FFConfig()  # parses --telemetry-dir/--warmstart-dir/... from argv
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    lm = TransformerLMConfig(vocab_size=128, hidden_size=32, num_heads=4,
                             num_layers=2, sequence_length=32,
                             attention_impl="xla")
    # the TRAIN compile stays data-parallel (fast); the search flags on
    # argv apply to the DECODE compiles via config_overrides below
    search_overrides = dict(
        only_data_parallel=config.only_data_parallel,
        search_budget=config.search_budget,
        enable_parameter_parallel=config.enable_parameter_parallel,
        enable_attribute_parallel=config.enable_attribute_parallel,
        search_calibrate=config.search_calibrate,
        warmstart_dir=config.warmstart_dir,
    )
    config.only_data_parallel = True
    config.warmstart_dir = ""
    config.batch_size = 8
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rs = np.random.RandomState(0)
    prompts = [rs.randint(1, lm.vocab_size, rs.randint(2, 9)).tolist()
               for _ in range(NUM_REQUESTS)]
    serve_kw = dict(slots=4, max_new_tokens=8, prefill_chunk=4,
                    config_overrides=search_overrides)

    engine = ff.serve(**serve_kw)
    outputs = engine.generate(prompts)
    stats = engine.stats()
    if stats["requests_completed"] != NUM_REQUESTS:
        fail(f"completed {stats['requests_completed']}/{NUM_REQUESTS}")
    for i, (req, out) in enumerate(zip(engine.scheduler.completed, outputs)):
        if not out:
            fail(f"request {i} produced no tokens")
        if req.finish_reason not in ("max_tokens", "eos", "length"):
            fail(f"request {i} has no finish reason")

    # second engine: token identity always; plan-cache hit with a
    # populated --warmstart-dir
    engine2 = ff.serve(**serve_kw)
    if engine2.generate(prompts) != outputs:
        fail("second engine's greedy output differs (determinism broken)")
    if search_overrides["warmstart_dir"]:
        if engine.decode_model._plan_source != "search":
            fail(f"first serving compile expected plan_source=search, got "
                 f"{engine.decode_model._plan_source!r}")
        if engine2.decode_model._plan_source != "cache":
            fail(f"second serving compile expected plan_source=cache, got "
                 f"{engine2.decode_model._plan_source!r} (warm-start plan "
                 f"cache missed)")
    ff.get_telemetry().close()

    # ---- artifact validation
    tdir = config.telemetry_dir
    recs = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    compiles = [r for r in recs if r["kind"] == "serve.compile"]
    if len(compiles) != 2:
        fail(f"expected 2 serve.compile records, got {len(compiles)}")
    for c in compiles:
        for field in ("plan_source", "slots", "max_seq_len", "duration_s"):
            if field not in c:
                fail(f"serve.compile missing {field}: {c}")
    reqs = [r for r in recs if r["kind"] == "serve.request"]
    if len(reqs) != 2 * NUM_REQUESTS:
        fail(f"expected {2 * NUM_REQUESTS} serve.request records, "
             f"got {len(reqs)}")
    for r in reqs:
        if not (r.get("ttft_s") or 0) > 0:
            fail(f"serve.request without ttft_s: {r}")
        if "finish_reason" not in r or "new_tokens" not in r:
            fail(f"malformed serve.request: {r}")
    summaries = [r for r in recs if r["kind"] == "serve.summary"]
    if len(summaries) < 2:
        fail(f"expected >=2 serve.summary records, got {len(summaries)}")
    for field in ("requests_per_sec_per_chip",
                  "decode_tokens_per_sec_per_chip", "ttft_p50_s",
                  "decode_iterations"):
        if not (summaries[-1].get(field, 0) > 0):
            fail(f"serve.summary field {field} missing/zero: "
                 f"{summaries[-1]}")

    with open(os.path.join(tdir, "trace.json")) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    for span in ("serve.compile", "serve.prefill", "serve.step"):
        if span not in names:
            fail(f"trace missing span {span!r} (have {sorted(names)})")

    summ = summaries[-1]
    print(f"serving_smoke: OK — {NUM_REQUESTS} requests x2 engines, "
          f"plan {compiles[0]['plan_source']}->{compiles[1]['plan_source']}, "
          f"ttft_p50={summ['ttft_p50_s'] * 1e3:.1f}ms "
          f"req/s/chip={summ['requests_per_sec_per_chip']:.2f} "
          f"decode tok/s/chip={summ['decode_tokens_per_sec_per_chip']:.1f}")


if __name__ == "__main__":
    main()
