"""Speculative-decoding smoke: drafter/verify vs plain decode on the CPU
mesh — the CI gate for serving/speculative.py (docs/serving.md,
"Speculative decoding").

Runs a small Transformer LM on the virtual 8-device mesh and asserts

  - `serve(speculate=True, draft_model=...)` with a high-acceptance
    drafter (a seed-clone of the target) completes a trace with token
    streams BIT-IDENTICAL to the unified engine — both colocated and
    with `--serve-draft-chips` carving a disjoint drafter sub-mesh;
  - the engine actually speculated (rounds >= 1) with acceptance rate
    > 0, and the acceptance EMA persisted to the warm-start calibration
    DB under the (target, drafter) pair key;
  - the strategy report carries the `speculation` section whose payoff
    decisions reproduce arithmetically, and `run_doctor --check`
    re-verifies the inequality from the artifacts alone;
  - the merged telemetry carries serve.speculate events and the spec
    metric series in a drained snapshot.

Usage:
  python scripts/spec_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on the first broken invariant.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

NUM_REQUESTS = 6


def fail(msg: str):
    print(f"spec_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def build(config_ctor, with_diag):
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (TransformerLMConfig,
                                     build_transformer_lm)

    lm = TransformerLMConfig(vocab_size=128, hidden_size=32, num_heads=4,
                             num_layers=2, sequence_length=32,
                             attention_impl="xla")
    config = config_ctor()
    config.only_data_parallel = True
    config.batch_size = 8
    if with_diag:
        config.diagnostics = True
    else:
        # one telemetry session per smoke: the drafter and the plain
        # baseline compile silently
        config.telemetry_dir = ""
        config.diagnostics = False
    ff = FFModel(config)
    build_transformer_lm(ff, lm, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff, lm


def main():
    from flexflow_tpu import FFConfig
    from flexflow_tpu.serving.speculative import load_acceptance
    from flexflow_tpu.telemetry import read_jsonl
    from flexflow_tpu.warmstart.calibration_db import device_key

    probe = FFConfig()
    if not probe.telemetry_dir:
        fail("pass --telemetry-dir")
    tdir = probe.telemetry_dir
    ws = os.path.join(tdir, "warmstart")

    def ctor():
        cfg = FFConfig()
        cfg.warmstart_dir = ws
        return cfg

    ff, lm = build(ctor, with_diag=True)
    # the drafter: a seed-clone of the target — identical weights give
    # the all-accept extreme, the honest way to exercise acceptance on
    # random (untrained) models
    dff, _ = build(ctor, with_diag=False)

    rs = np.random.RandomState(7)
    prompts = [rs.randint(1, lm.vocab_size, rs.randint(2, 9)).tolist()
               for _ in range(NUM_REQUESTS)]
    serve_kw = dict(slots=4, max_new_tokens=8, prefill_chunk=4,
                    kv_block_size=4)

    unified = ff.serve(**serve_kw)
    want = unified.generate(prompts)

    # ---- colocated speculation: bit-identity + acceptance accounting
    eng = ff.serve(speculate=True, draft_model=dff, **serve_kw)
    got = eng.generate(prompts)
    if got != want:
        fail(f"speculative token streams diverge from plain decode:\n"
             f"  plain {want}\n  spec  {got}")
    sp = eng.stats()["speculation"]
    if sp["rounds"] < 1:
        fail("the engine never ran a speculative round")
    if sp["draft_tokens"] < 1 or sp["accepted_tokens"] < 1:
        fail(f"no acceptance recorded: {sp}")
    if not sp["acceptance_rate"] > 0:
        fail(f"acceptance rate must be > 0, got {sp['acceptance_rate']}")
    print(f"spec_smoke: {NUM_REQUESTS} requests bit-identical, "
          f"{sp['rounds']} speculative round(s), acceptance "
          f"{sp['acceptance_rate']:.2f} "
          f"({sp['accepted_tokens']}/{sp['draft_tokens']} drafted)")

    # ---- the acceptance EMA persisted under the pair key
    rate, samples = load_acceptance(ff, eng.pair_key)
    if samples < 1:
        fail("acceptance EMA did not persist at drain")
    db_path = os.path.join(ws, "calibration.json")
    if not os.path.exists(db_path):
        fail(f"no calibration DB at {db_path}")
    db = json.load(open(db_path))
    keys = list((db.get("devices", {}).get(device_key()) or {}).keys())
    if not any("__spec_acceptance__" in k for k in keys):
        fail(f"calibration DB holds no __spec_acceptance__ entry: {keys}")
    print(f"spec_smoke: acceptance EMA {rate:.3f} ({samples:.0f} samples) "
          f"round-tripped through the warm-start calibration DB")

    # ---- disjoint drafter sub-mesh: same streams at 4+4 chips
    eng2 = ff.serve(speculate=True, draft_model=dff, draft_chips=4,
                    **serve_kw)
    tdev = {d.id for d in eng2.decode_model.mesh.devices.flat}
    ddev = {d.id for d in eng2.drafter.engine.decode_model.mesh.devices.flat}
    if tdev & ddev:
        fail(f"drafter/target device windows overlap: {tdev & ddev}")
    if len(tdev) != 4 or len(ddev) != 4:
        fail(f"--serve-draft-chips carved {len(tdev)}t+{len(ddev)}d of 8")
    if eng2.generate(prompts) != want:
        fail("sub-mesh speculative streams diverge from plain decode")
    print(f"spec_smoke: bit-identical again on disjoint sub-meshes "
          f"({len(tdev)}t+{len(ddev)}d chips)")

    # ---- report + telemetry surface
    ff._telemetry.close()
    rep = json.load(open(os.path.join(tdir, "strategy_report.json")))
    sec = rep.get("speculation")
    if sec is None:
        fail("strategy_report.json has no speculation section")
    if not sec.get("decisions"):
        fail("speculation section carries no payoff decisions")
    if sec.get("rounds", 0) < 1 or sec.get("accepted_tokens", 0) < 1:
        fail(f"report speculation accounting empty: {sec}")
    records = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    kinds = {}
    for r in records:
        kinds[r.get("kind")] = kinds.get(r.get("kind"), 0) + 1
    if kinds.get("serve.speculate", 0) < 1:
        fail("no serve.speculate events in the telemetry stream")
    snaps = [r for r in records if r.get("kind") == "metrics_snapshot"
             and r.get("drained")]
    if not snaps:
        fail("no drained metrics snapshot")
    counters = snaps[-1].get("metrics", {}).get("counters") or {}
    if not any(k.startswith("serve_spec_rounds_total") for k in counters):
        fail("drained snapshot missing serve_spec_rounds_total")

    # ---- the doctor re-verifies the payoff inequality from the
    # artifacts alone
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "run_doctor.py"),
         tdir, "--check", "--out", os.path.join(tdir, "doctor.md")],
        capture_output=True, text=True)
    if r.returncode != 0:
        fail(f"run_doctor --check failed:\n{r.stderr}")
    doc = open(os.path.join(tdir, "doctor.md")).read()
    if "Speculative decoding" not in doc:
        fail("doctor report missing the speculative-decoding section")
    print("spec_smoke: run_doctor --check re-verified every payoff "
          "decision from the report alone")
    print("spec_smoke: OK")


if __name__ == "__main__":
    main()
