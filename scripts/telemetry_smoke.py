"""Telemetry smoke: tiny fit with --telemetry-dir, then validate artifacts.

The CI gate for the observability subsystem (docs/observability.md): runs a
small MLP fit on the virtual CPU mesh with telemetry + checkpointing on,
then asserts

  - trace.json parses as Chrome trace-event JSON and carries the spans
    the acceptance criteria name (compile, >=1 step, data_wait, and the
    checkpoint snapshot/serialize/commit trio);
  - metrics.jsonl opens with a manifest, every step record carries the
    data-wait / save-latency split, and the final summary has p50/p95
    step time and examples/sec.

Usage: python scripts/telemetry_smoke.py --telemetry-dir OUT [flexflow flags]
Exits nonzero with a diagnostic on any missing artifact/field.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"telemetry_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )
    from flexflow_tpu.telemetry import read_jsonl

    config = FFConfig()  # parses --telemetry-dir / --checkpoint-* from argv
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    if not config.checkpoint_dir:
        # checkpoint spans are part of the acceptance surface
        config.checkpoint_dir = os.path.join(
            config.telemetry_dir, "_smoke_ckpt")
        config.checkpoint_every = 4

    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    X = rs.randn(256, 64).astype(np.float32)
    Y = rs.randint(0, 10, (256, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=32)

    tdir = config.telemetry_dir
    trace_path = os.path.join(tdir, "trace.json")
    metrics_path = os.path.join(tdir, "metrics.jsonl")
    for p in (trace_path, metrics_path):
        if not os.path.exists(p):
            fail(f"missing artifact {p}")

    with open(trace_path) as f:
        trace = json.load(f)
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("trace.json has no traceEvents list")
    for e in events:
        if "name" not in e or "ph" not in e:
            fail(f"malformed trace event {e}")
    names = {e["name"] for e in events}
    for required in ("compile", "step", "data_wait", "ckpt.snapshot",
                     "ckpt.serialize", "ckpt.commit"):
        if required not in names:
            fail(f"trace missing span {required!r} (have {sorted(names)})")

    recs = read_jsonl(metrics_path)
    if not recs or recs[0]["kind"] != "manifest":
        fail("metrics.jsonl must start with the run manifest")
    steps = [r for r in recs if r["kind"] == "step"]
    if not steps:
        fail("no step records")
    for s in steps:
        for field in ("data_wait_s", "save_latency_s", "step_time_s",
                      "device_time_s", "ema_step_time_s"):
            if field not in s:
                fail(f"step record missing {field}: {s}")
    summaries = [r for r in recs if r["kind"] == "summary"]
    if not summaries:
        fail("no summary record")
    summ = summaries[-1]
    for field in ("p50_step_time_s", "p95_step_time_s", "examples_per_sec"):
        if not (summ.get(field, 0) > 0):
            fail(f"summary field {field} missing/zero: {summ}")
    if not [r for r in recs if r["kind"] == "checkpoint"]:
        fail("no checkpoint records (save pipeline unmeasured)")

    print(f"telemetry_smoke: OK — {len(events)} trace events, "
          f"{len(steps)} step records, "
          f"p50={summ['p50_step_time_s'] * 1e3:.2f}ms "
          f"p95={summ['p95_step_time_s'] * 1e3:.2f}ms "
          f"examples/s={summ['examples_per_sec']:.1f}")


if __name__ == "__main__":
    main()
