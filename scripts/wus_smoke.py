"""Weight-update-sharding smoke: memory-constrained LM on a dp CPU mesh.

The CI gate for the round-8 ZeRO-style weight-update sharding
(docs/performance.md "Weight-update sharding"): compiles a small
transformer LM on a pure data-parallel mesh with per-chip HBM capped
below the replicated update's footprint (-ll:fsize), WITHOUT forcing
--weight-update-sharding, runs a short fit, then asserts

  - Unity's update-dimension decision (choose_update_sharding) SELECTED
    the sharded update on its own: auto mode (forced is None), reason
    memory_bound, predicted replicated memory over the cap and predicted
    sharded memory under it (the 1/dp masters+slots saving is what fits
    the plan);
  - the strategy report prices the grad RS+AG on the overlappable
    channel: update_sharding true with the mesh's dp degree as
    update_shards, report-level grad_sync_s > 0, and every op that
    carries grad sync shows overlap_s >= grad_sync_s with sync_s == 0
    (the pair hides behind backward compute, only hop latency is
    exposed);
  - the makespan identity still reproduces with the grad-sync channel in
    play (run_doctor --check covers the same report in CI);
  - telemetry carries the weight_update event (shards/buckets/bytes) and
    the weight_update_decision event — the compiled executable really
    runs the sharded update, and the drift monitor sees the channel;
  - the fit completed (steps recorded) with the sharded update live.

Usage: python scripts/wus_smoke.py --telemetry-dir OUT
       [--mesh 4,1,1,1] [-ll:fsize MiB] [flexflow flags]
Exits nonzero with a diagnostic on any violated assertion.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual 8-device CPU mesh, exactly like tests/conftest.py
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def fail(msg: str):
    print(f"wus_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm
    from flexflow_tpu.telemetry import read_jsonl

    # defaults: a dp=4 mesh and a per-chip HBM cap squeezed below the
    # replicated update's predicted footprint — auto mode must flip to
    # the sharded update to fit (NO --weight-update-sharding here: the
    # point is that Unity selects it)
    argv = sys.argv[1:]
    if "--weight-update-sharding" in argv:
        fail("do not force --weight-update-sharding — the smoke proves "
             "the search selects it")
    if "--mesh" not in argv:
        argv += ["--mesh", "4,1,1,1"]
    if "-ll:fsize" not in argv:
        argv += ["-ll:fsize", "1.5"]
    if "--diagnostics" not in argv:
        argv += ["--diagnostics"]
    sys.argv = [sys.argv[0]] + argv

    config = FFConfig()
    if not config.telemetry_dir:
        fail("pass --telemetry-dir")
    config.batch_size = 4

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=128, hidden_size=64, num_heads=2, num_layers=2,
        sequence_length=32)
    build_transformer_lm(ff, cfg, batch_size=4)
    ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=0.9),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    # 1) the update-dimension search selected the sharded update, for the
    # memory reason, in auto mode
    dec = ff._update_sharding or {}
    if dec.get("forced") is not None:
        fail(f"decision was forced ({dec['forced']}) — auto mode required")
    if not dec.get("enabled"):
        fail(f"search kept the replicated update "
             f"(reason {dec.get('reason')}): {dec.get('predicted')}")
    if dec.get("reason") != "memory_bound":
        fail(f"expected a memory_bound selection, got {dec.get('reason')}")
    pred = dec.get("predicted") or {}
    cap = pred.get("hbm_cap_bytes", 0.0)
    if not (pred.get("replicated_mem_bytes", 0.0) > cap
            >= pred.get("sharded_mem_bytes", float("inf"))):
        fail(f"memory pricing inconsistent with a memory_bound pick: "
             f"replicated {pred.get('replicated_mem_bytes')} / sharded "
             f"{pred.get('sharded_mem_bytes')} vs cap {cap}")
    if not ff.executor.update_specs:
        fail("decision enabled but the executor sharded no weight")

    rs = np.random.RandomState(0)
    n = 8
    X = {"tokens": rs.randint(0, cfg.vocab_size,
                              (n, cfg.sequence_length)).astype(np.int32),
         "positions": np.tile(
             np.arange(cfg.sequence_length, dtype=np.int32), (n, 1))}
    Y = rs.randint(0, cfg.vocab_size,
                   (n, cfg.sequence_length, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)

    tdir = config.telemetry_dir
    report_path = os.path.join(tdir, "strategy_report.json")
    if not os.path.exists(report_path):
        fail(f"missing strategy report {report_path}")
    with open(report_path) as f:
        report = json.load(f)

    # 2) the report prices the sharded update's grad RS+AG on the
    # overlappable channel
    if not report.get("update_sharding"):
        fail("strategy report does not show update_sharding")
    if report.get("update_shards") != dec["shards"]:
        fail(f"report update_shards {report.get('update_shards')} != "
             f"decision shards {dec['shards']}")
    if not report.get("grad_sync_s", 0.0) > 0.0:
        fail("report grad_sync_s is zero — the grad sync was not priced "
             "on the sharded channel")
    synced = [o for o in report["ops"] if o.get("grad_sync_s", 0.0) > 0.0]
    if not synced:
        fail("no op carries grad_sync_s")
    for o in synced:
        if o.get("overlap_s", 0.0) < o["grad_sync_s"] or o.get("sync_s"):
            fail(f"op {o['name']} grad sync not on the overlappable "
                 f"channel: overlap_s {o.get('overlap_s')} / grad_sync_s "
                 f"{o['grad_sync_s']} / sync_s {o.get('sync_s')}")

    # 3) the report's makespan identity holds with grad sync overlapped
    from flexflow_tpu.diagnostics.explain import verify_report_total

    total = verify_report_total(report)
    pred_s = report["total_predicted_s"]
    if not (abs(total - pred_s) <= 1e-9 + 1e-6 * abs(pred_s)):
        fail(f"makespan identity broken with grad-sync channel: "
             f"verify={total} vs report={pred_s}")

    # 4) the compiled executable really runs the sharded update
    recs = list(read_jsonl(os.path.join(tdir, "metrics.jsonl")))
    wu = [r for r in recs if r.get("kind") == "weight_update"]
    if not wu:
        fail("no weight_update event in telemetry")
    if wu[0].get("shards") != dec["shards"] or not wu[0].get("bytes"):
        fail(f"weight_update event inconsistent: {wu[0]}")
    if not [r for r in recs if r.get("kind") == "weight_update_decision"]:
        fail("no weight_update_decision event in telemetry")

    # 5) the fit actually stepped under the sharded update
    steps = [r for r in recs if r.get("kind") == "step"]
    if not steps:
        fail("no step records — fit did not run")

    print(f"wus_smoke: OK — sharded update selected "
          f"({dec['shards']} shards, reason {dec['reason']}; "
          f"mem {pred['replicated_mem_bytes'] / 2**20:.2f} -> "
          f"{pred['sharded_mem_bytes'] / 2**20:.2f} MiB/chip vs cap "
          f"{cap / 2**20:.2f}), grad_sync_s "
          f"{report['grad_sync_s'] * 1e6:.1f} us overlapped, "
          f"{len(steps)} steps, makespan identity holds")


if __name__ == "__main__":
    main()
