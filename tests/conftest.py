"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests multi-GPU only on real hardware (SURVEY §4); we do better
by unit-testing all SPMD logic on XLA's host platform with
--xla_force_host_platform_device_count=8, so sharding/search/collective code
is exercised in CI without TPUs.

The container's sitecustomize registers the axon TPU plugin and forces
jax_platforms="axon,cpu" via jax.config (which overrides env vars), so we
override it back through jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():  # pragma: no cover - defensive
    from jax.extend.backend import clear_backends

    clear_backends()

import numpy as np
import pytest

assert jax.devices()[0].platform == "cpu", jax.devices()
assert jax.device_count() == 8, jax.devices()


@pytest.fixture
def rng():
    return np.random.RandomState(0)
