"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests multi-GPU only on real hardware (SURVEY §4); we do better
by unit-testing all SPMD logic on XLA's host platform with
--xla_force_host_platform_device_count=8, so sharding/search/collective code
is exercised in CI without TPUs.

The container's sitecustomize registers the axon TPU plugin and forces
jax_platforms="axon,cpu" via jax.config (which overrides env vars), so we
override it back through jax.config before any backend initializes.
"""

import os
import re

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

from jax._src import xla_bridge as _xb

if _xb.backends_are_initialized():  # pragma: no cover - defensive
    from jax.extend.backend import clear_backends

    clear_backends()

import numpy as np
import pytest

assert jax.devices()[0].platform == "cpu", jax.devices()
assert jax.device_count() == 8, jax.devices()


@pytest.fixture
def rng():
    return np.random.RandomState(0)


# --------------------------------------------------------------------------
# Capability probes: the pinned container's jax predates some APIs the
# Pallas/flash and shard_map code paths use, so those suites fail on
# ENVIRONMENT gaps, not code regressions. Probe each capability once; when
# (and only when) the probe confirms the gap, a test failing with that
# gap's exact signature is converted to a clean skip with the probe's
# reason — tier-1 signal becomes failures-mean-regressions. On an
# environment where the probes pass, nothing is converted and any such
# failure stays a failure.

def _probe_pallas_flash():
    """Run a tiny flash-attention call (interpret mode on CPU). Returns
    None when the environment supports it, else the failure reason."""
    try:
        from flexflow_tpu.kernels import flash_attention
        import jax.numpy as jnp

        # shape must clear flash_attention's XLA-fallback gate (seq >= 128,
        # head_dim % 8 == 0) so the probe exercises the real Pallas path
        q = jnp.zeros((1, 1, 128, 32), jnp.float32)
        jax.block_until_ready(flash_attention(q, q, q))
        return None
    except Exception as e:  # noqa: BLE001 - any env failure is the answer
        return f"{type(e).__name__}: {e}"


def _probe_pallas_decode():
    """Run a tiny single-query decode-attention kernel call (interpret
    mode on CPU) — the serving flash path (kernels/flash_attention.
    flash_decode_attention). Returns None when supported, else the
    failure reason."""
    try:
        import jax.numpy as jnp

        from flexflow_tpu.kernels.flash_attention import (
            flash_decode_attention,
        )

        # cache >= 128 rows so the probe clears the einsum-fallback gate
        # and exercises the real Pallas decode kernel
        q = jnp.zeros((1, 1, 32), jnp.float32)
        kv = jnp.zeros((1, 128, 32), jnp.float32)
        jax.block_until_ready(flash_decode_attention(
            q, kv, kv, jnp.ones((1,), jnp.int32), num_heads=1,
            interpret=True))
        return None
    except Exception as e:  # noqa: BLE001 - any env failure is the answer
        return f"{type(e).__name__}: {e}"


def _probe_pallas_paged_decode():
    """Run a tiny PAGED decode-attention kernel call (interpret mode on
    CPU) — the paged serving flash path (kernels/flash_attention.
    paged_flash_decode_attention), whose scalar-prefetched page-table
    BlockSpecs (PrefetchScalarGridSpec) are a separate capability from
    the plain decode kernel. Returns None when supported, else the
    failure reason."""
    try:
        import jax.numpy as jnp

        from flexflow_tpu.kernels.flash_attention import (
            paged_flash_decode_attention,
        )

        # 16 blocks x 8 rows >= the 128-row einsum-fallback gate, so the
        # probe exercises the real Pallas paged kernel
        q = jnp.zeros((1, 1, 32), jnp.float32)
        pool = jnp.zeros((17, 8, 32), jnp.float32)
        tbl = jnp.arange(1, 17, dtype=jnp.int32)[None, :]
        jax.block_until_ready(paged_flash_decode_attention(
            q, pool, pool, tbl, jnp.ones((1,), jnp.int32), num_heads=1,
            interpret=True))
        return None
    except Exception as e:  # noqa: BLE001 - any env failure is the answer
        return f"{type(e).__name__}: {e}"


def _probe_shard_map():
    """The parallel/ modules (ring attention, pipeline) use jax.shard_map,
    which older jax only ships as jax.experimental.shard_map."""
    try:
        jax.shard_map
        return None
    except AttributeError as e:
        return f"{type(e).__name__}: {e}"


# (label, exception-text pre-filter, probe). A failure converts to a skip
# only when BOTH hold: the pre-filter matches AND the probe's own failure
# message appears in the test's exception text — i.e. the test died on
# the exact missing-API error the probe reproduced. A different
# pallas/shard_map-adjacent bug (wrong attribute, in-repo typo) fails the
# message match and stays a failure.
_CAPABILITIES = [
    ("pallas/flash-attention", re.compile(r"pallas|Pallas|CompilerParams"),
     _probe_pallas_flash),
    ("pallas/flash-decode", re.compile(r"pallas|Pallas|CompilerParams"),
     _probe_pallas_decode),
    ("pallas/paged-decode",
     re.compile(r"pallas|Pallas|CompilerParams|PrefetchScalarGridSpec"),
     _probe_pallas_paged_decode),
    ("shard_map", re.compile(r"shard_map"), _probe_shard_map),
]
_probe_results: dict = {}


def _env_gap_reason(excinfo) -> "str | None":
    if not isinstance(excinfo.value,
                      (AttributeError, ImportError, NotImplementedError)):
        return None
    text = f"{excinfo.value}"
    for label, sig, probe in _CAPABILITIES:
        if sig.search(text):
            if label not in _probe_results:
                _probe_results[label] = probe()
            reason = _probe_results[label]
            if reason is None:
                continue
            # "TypeName: message" -> the message the environment gap raises
            core = reason.split(": ", 1)[-1]
            if core and core in text:
                return (f"{label} unavailable in this environment: "
                        f"{reason}")
    return None


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed and call.excinfo is not None:
        reason = _env_gap_reason(call.excinfo)
        if reason is not None:
            rep.outcome = "skipped"
            rep.longrepr = (str(item.fspath), item.location[1] or 0,
                            f"Skipped: {reason}")
