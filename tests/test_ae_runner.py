"""AE runner CI leg (reference scripts/osdi22ae/*.sh +
tests/python_interface_test.sh): the one-command runner trains a zoo model
in both AE modes on the virtual mesh, prints machine-readable results, and
enforces the MNIST accuracy gate."""

import sys


def test_ae_runner_mlp_both_modes():
    sys.path.insert(0, "/root/repo")
    from scripts.run_ae import run_one

    dp = run_one("mlp", "dp", batch=64, epochs=2)
    assert dp["samples_per_sec"] > 0
    assert dp["accuracy"] >= 0.90  # python_interface_test.sh's gate
    assert dp["mesh"]["data"] == 8  # all 8 virtual devices, pure DP

    unity = run_one("mlp", "unity", batch=64, epochs=2)
    n = 1
    for v in unity["mesh"].values():
        n *= v
    assert n == 8  # the searched factorization still uses every device
    assert unity["accuracy"] >= 0.90


def test_ae_runner_rejects_unknown_model():
    import subprocess

    p = subprocess.run(
        [sys.executable, "scripts/run_ae.py", "--models", "nope"],
        capture_output=True, text=True, cwd="/root/repo")
    assert p.returncode != 0
    assert "unknown model" in p.stderr
