"""ffcheck static-analysis tests (analysis/, docs/analysis.md).

The acceptance surface of the compile gate: a plan-mutation fuzzer
injects each corruption class into a real searched plan (axis reuse,
dropped parallel op, oversharded dim, non-bijective ring permutation,
donated-then-reused buffer, coordinator-only collective) and asserts
ffcheck reports exactly that class; clean plans verify with zero errors
on every plan source; the memory-liveness pass fails a predicted OOM
before device allocation with `--no-verify-plan` as the escape hatch;
the fflint rules catch their synthetic hazards AND pass clean over the
repo (the CI invariant); and the donation registry cross-checks against
executor.py's own AST.
"""

import json
import os
import sys

import numpy as np
import pytest


def _config(argv):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.batch_size = 4
    return config


def _lm(config, seq=16, ring=False, layers=1):
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=2, num_layers=layers,
        sequence_length=seq,
        attention_impl="ring" if ring else "xla")
    build_transformer_lm(ff, cfg, batch_size=4)
    return ff, cfg


def _compile(ff, momentum=0.0):
    from flexflow_tpu import LossType, SGDOptimizer

    ff.compile(optimizer=SGDOptimizer(lr=0.01, momentum=momentum),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


@pytest.fixture(scope="module")
def searched():
    """One searched compile shared by the fuzzer tests (mutations always
    restore what they touched)."""
    ff, _ = _lm(_config(["--mesh", "2,4,1,1", "--budget", "6",
                         "--enable-parameter-parallel"]))
    return _compile(ff)


@pytest.fixture(scope="module")
def ring_model():
    """Manual sequence-parallel ring-attention plan on a seq=2 mesh."""
    from flexflow_tpu.parallel.strategies import sequence_parallel_attention

    ff, _ = _lm(_config(["--mesh", "2,1,1,2"]), ring=True)
    ff.set_strategy(sequence_parallel_attention(ff))
    return _compile(ff)


def _analyze(ff):
    from flexflow_tpu.analysis import context_for_model, run_analysis

    return run_analysis(ff.graph, ff.mesh, context_for_model(ff))


# ===================================================== unit: primitives


def test_permutation_checker():
    from flexflow_tpu.analysis.collectives import check_permutation
    from flexflow_tpu.parallel.ops import ring_permutation

    assert check_permutation(ring_permutation(4), 4) == []
    # dropped pair, duplicated destination, out-of-range
    assert [f.code for f in
            check_permutation(ring_permutation(4)[:-1], 4)] \
        == ["bad_permutation"]
    assert check_permutation([(0, 1), (1, 1), (2, 0), (3, 2)], 4)
    assert check_permutation([(0, 1), (1, 2), (2, 3), (3, 9)], 4)


def test_assignment_problems_matrix():
    from flexflow_tpu.analysis.sharding import assignment_problems

    axes = {"data": 2, "model": 4}
    ok = assignment_problems((("data",), ("model",)), (8, 8), axes, "t")
    assert ok == []
    reuse = assignment_problems((("data",), ("data",)), (8, 8), axes, "t")
    assert [f.code for f in reuse] == ["axis_reuse"]
    indiv = assignment_problems((("model",), ()), (6, 8), axes, "t")
    assert [f.code for f in indiv] == ["indivisible_dim"]
    over = assignment_problems(((("data"), ("model")), ()), (2, 8),
                               axes, "t")
    assert "overshard" in [f.code for f in over]
    unknown = assignment_problems((("ghost",),), (8,), axes, "t")
    assert [f.code for f in unknown] == ["unknown_axis"]


def test_validate_rejects_axis_reuse(searched):
    """The satellite regression: Strategy.validate historically accepted
    an assignment using one mesh axis on two different dims — an invalid
    NamedSharding that only exploded at device_put. It now delegates to
    the verifier and rejects it."""
    from flexflow_tpu.parallel.strategies import Strategy

    node = next(n for n in searched.graph.topo_order()
                if n.outputs and len(n.outputs[0].shape.dims) >= 2)
    nd = len(node.outputs[0].shape.dims)
    bad = Strategy()
    bad.set_output(node.name, 0,
                   (("data",), ("data",)) + ((),) * (nd - 2))
    with pytest.raises(ValueError, match="axis_reuse|at most once"):
        bad.validate(searched.graph, searched.mesh)


def test_strategy_json_precheck():
    """The plan cache rejects a poisoned entry from the JSON alone."""
    from flexflow_tpu.analysis.sharding import strategy_json_problems

    clean = {"nodes": {"l": {"outputs": {"0": [["data"], []]},
                             "weights": {}}}}
    assert strategy_json_problems(clean) == []
    poisoned = {"nodes": {"l": {"outputs": {"0": [["data"], ["data"]]},
                                "weights": {"kernel":
                                            ["model", "model"]}}}}
    codes = [f.code for f in strategy_json_problems(poisoned)]
    assert codes == ["axis_reuse", "axis_reuse"]


# ================================================= the corruption fuzzer


def _mutate_and_run(ff, node_pred, new_assign):
    node = next(n for n in ff.graph.topo_order() if node_pred(n))
    pt = node.outputs[0]
    saved = pt.axis_assignment
    pt.axis_assignment = new_assign(pt)
    try:
        return _analyze(ff)
    finally:
        pt.axis_assignment = saved


def test_fuzzer_clean_baseline(searched):
    res = _analyze(searched)
    assert res.ok, [str(f) for f in res.errors()]
    assert res.passes_run == ["sharding_dataflow", "memory_liveness",
                              "collective_uniformity",
                              "donation_aliasing", "dtype_flow",
                              "spmd_uniformity", "rule_verify"]


def test_fuzzer_axis_reuse(searched):
    res = _mutate_and_run(
        searched,
        lambda n: n.outputs and len(n.outputs[0].shape.dims) >= 2,
        lambda pt: (("data",), ("data",))
        + tuple(() for _ in pt.shape.dims[2:]))
    assert [f.code for f in res.errors()] == ["axis_reuse"]


def test_fuzzer_dropped_parallel_op(searched):
    """Stripping a layout-preserving consumer's sharding while its
    producer stays sharded = the reshard a dropped parallel op leaves
    implicit; ffcheck flags the edge."""
    from flexflow_tpu.analysis.sharding import _LAYOUT_PRESERVING

    res = _mutate_and_run(
        searched,
        lambda n: (n.op_type in _LAYOUT_PRESERVING and n.inputs
                   and any(a for a in n.inputs[0].axis_assignment)),
        lambda pt: tuple(() for _ in pt.shape.dims))
    hits = res.by_code("implicit_reshard")
    assert hits, [str(f) for f in res.findings]
    assert res.ok  # a warning, not an error: priced plans may reshard


def test_fuzzer_oversharded_dim(searched):
    res = _mutate_and_run(
        searched,
        lambda n: (n.outputs
                   and not n.outputs[0].shape.dims[0].is_replica_dim
                   and n.outputs[0].shape.dims[0].size < 8),
        lambda pt: (("data", "model"),)
        + tuple(() for _ in pt.shape.dims[1:]))
    assert "overshard" in [f.code for f in res.errors()]


def test_fuzzer_bad_permutation(ring_model, monkeypatch):
    """Corrupting the ONE shared ring-schedule builder is caught for a
    plan that actually runs a ring — and the clean plan passes."""
    from flexflow_tpu.parallel import ops as par_ops

    clean = _analyze(ring_model)
    assert clean.ok, [str(f) for f in clean.errors()]
    assert any("ring attention" in f.message or "ring schedule"
               in f.message for f in clean.findings)

    good = par_ops.ring_permutation
    monkeypatch.setattr(par_ops, "ring_permutation",
                        lambda n: good(n)[:-1])
    res = _analyze(ring_model)
    assert [f.code for f in res.errors()] == ["bad_permutation"]


def test_fuzzer_donated_reuse():
    from flexflow_tpu.analysis.lint import lint_source

    src = (
        "def loop(self, rng, batch):\n"
        "    out = step_fn(self._params, self._state, self._slots,\n"
        "                  self._step, self._counters, rng, batch)\n"
        "    stale = self._params['head']\n"
        "    return out, stale\n")
    codes = [f.code for f in lint_source(src, select=("donated_reuse",))]
    assert codes == ["donated_reuse"]

    # the carry pattern — donated args rebound by the call's own
    # assignment — is clean
    ok = (
        "def loop(self, rng, batch):\n"
        "    (self._params, self._state, self._slots, self._step,\n"
        "     self._counters, loss) = step_fn(\n"
        "        self._params, self._state, self._slots, self._step,\n"
        "        self._counters, rng, batch)\n"
        "    return self._params, loss\n")
    assert lint_source(ok, select=("donated_reuse",)) == []


def test_fuzzer_coordinator_collective():
    from flexflow_tpu.analysis.lint import lint_source

    src = (
        "def commit(payload):\n"
        "    if is_coordinator():\n"
        "        write(payload)\n"
        "        barrier('commit')\n")
    codes = [f.code for f in
             lint_source(src, select=("coordinator_collective",))]
    assert codes == ["coordinator_collective"]

    # the sanctioned idiom: gate the payload, not the collective
    ok = (
        "def commit(payload):\n"
        "    data = broadcast_json(payload if is_coordinator()\n"
        "                          else None)\n"
        "    return data\n")
    assert lint_source(ok, select=("coordinator_collective",)) == []

    # negated guard: the ELSE branch is coordinator-only
    neg = (
        "def commit(payload):\n"
        "    if not is_coordinator():\n"
        "        pass\n"
        "    else:\n"
        "        barrier('commit')\n")
    assert [f.code for f in
            lint_source(neg, select=("coordinator_collective",))] \
        == ["coordinator_collective"]


# ============================================== clean plans, six sources


def test_clean_plan_all_six_sources(tmp_path):
    """Every plan-adoption path funnels through the compile gate and
    verifies with zero errors: search, cache, checkpoint, import,
    manual, default."""
    from flexflow_tpu.parallel.strategies import (
        Strategy,
        megatron_transformer,
    )

    seen = {}

    def record(ff, expect):
        assert ff._plan_source == expect
        res = ff._analysis
        assert res is not None, f"{expect}: gate did not run"
        assert res.ok, (expect, [str(f) for f in res.errors()])
        seen[expect] = res.summary()

    search_argv = ["--mesh", "2,4,1,1", "--budget", "6",
                   "--enable-parameter-parallel"]
    ff = _compile(_lm(_config(search_argv))[0])
    record(ff, "search")
    plan_path = str(tmp_path / "plan.json")
    Strategy(ff._strategy or {}).save(plan_path)

    ws = str(tmp_path / "warmstart")
    _compile(_lm(_config(search_argv + ["--warmstart-dir", ws]))[0])
    record(_compile(_lm(_config(
        search_argv + ["--warmstart-dir", ws]))[0]), "cache")

    ck = str(tmp_path / "ckpt")
    ck_argv = search_argv + ["--checkpoint-dir", ck,
                             "--checkpoint-every", "1", "--auto-resume"]
    ff, cfg = _lm(_config(ck_argv))
    _compile(ff)
    rs = np.random.RandomState(0)
    X = {"tokens": rs.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32),
         "positions": np.tile(np.arange(16, dtype=np.int32), (8, 1))}
    Y = rs.randint(0, cfg.vocab_size, (8, 16, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    record(_compile(_lm(_config(ck_argv))[0]), "checkpoint")

    record(_compile(_lm(_config(
        ["--mesh", "2,4,1,1", "--import-strategy", plan_path]))[0]),
        "import")

    ff, _ = _lm(_config(["--mesh", "2,4,1,1"]))
    ff.set_strategy(megatron_transformer(ff))
    record(_compile(ff), "manual")

    record(_compile(_lm(_config(["--mesh", "2,4,1,1"]))[0]), "default")
    assert sorted(seen) == sorted(
        ["search", "cache", "checkpoint", "import", "manual", "default"])


def test_poisoned_cache_entry_reads_as_miss(tmp_path):
    """A plan-cache entry with an invalid sharding must read as a miss
    (re-search), never crash the compile."""
    from flexflow_tpu.warmstart.plan_cache import PlanCache

    cache = PlanCache(str(tmp_path))
    poisoned = {"version": 1, "nodes": {
        "l": {"outputs": {"0": [["data"], ["data"]]}, "weights": {}}}}
    path = cache.store("f" * 64, poisoned, {"data": 2})
    assert path is not None
    assert cache.lookup("f" * 64) is None  # verifier precheck → miss


# ======================================================= memory liveness


def test_memory_oom_gate_and_escape_hatch():
    from flexflow_tpu.analysis import PlanVerificationError

    argv = ["--mesh", "4,1,1,1", "-ll:fsize", "0.001"]  # ~1 KiB cap
    with pytest.raises(PlanVerificationError) as e:
        _compile(_lm(_config(argv))[0])
    assert "oom_predicted" in [f.code for f in e.value.result.errors()]

    ff = _compile(_lm(_config(argv + ["--no-verify-plan"]))[0])
    assert ff._compiled
    assert "oom_predicted" in [f.code for f in ff._analysis.errors()]


def test_memory_crosscheck_against_cost_model(searched):
    """The liveness estimate and the pricer's Σ agree within the
    transient slack on a real plan (no divergence finding), and the
    timeline attributes the peak to an op."""
    res = _analyze(searched)
    assert not res.by_code("memory_model_divergence"), \
        [str(f) for f in res.findings]
    tl = res.by_code("memory_timeline")
    assert tl and tl[0].details["peak_bytes"] > 0
    assert tl[0].details["peak_at"] != "(weights)"
    assert tl[0].details["cost_model_bytes"] > 0


def test_memory_counts_update_sharding():
    """Under the ZeRO-sharded update the persistent (masters + slots)
    term shrinks: the analysis must price the 1/dp layout, not the
    replicated one — same accounting as the cost model."""
    from flexflow_tpu.analysis.memory import analyze

    argv = ["--mesh", "4,1,1,1"]
    rep = _compile(_lm(_config(argv + ["--no-weight-update-sharding"]),
                       layers=2)[0], momentum=0.9)
    sh = _compile(_lm(_config(argv + ["--weight-update-sharding"]),
                      layers=2)[0], momentum=0.9)
    assert sh.executor.update_specs  # really sharded
    m_rep = analyze(rep.graph, rep.mesh, opt_slots=2,
                    update_specs=rep.executor.update_specs)
    m_sh = analyze(sh.graph, sh.mesh, opt_slots=2,
                   update_specs=sh.executor.update_specs)
    assert m_sh["persistent_bytes"] < m_rep["persistent_bytes"]


def test_memory_inference_accounting(searched):
    """An inference compile (serving decode graphs) carries no grads,
    optimizer slots, or retained activations — the liveness model must
    charge trainable weights 1x and free activations after their last
    consumer, or a trained-then-served model would trip the OOM gate on
    a serving launch that fits."""
    from flexflow_tpu.analysis.memory import analyze

    train = analyze(searched.graph, searched.mesh, opt_slots=2,
                    training=True)
    infer = analyze(searched.graph, searched.mesh, opt_slots=2,
                    training=False)
    assert infer["persistent_bytes"] < train["persistent_bytes"]
    assert infer["peak_bytes"] < train["peak_bytes"]
    assert all(t["phase"] == "fwd" for t in infer["timeline"])


# ========================================================== collectives


def test_bucket_order_determinism(searched):
    """Out-of-order update buckets are a multihost hazard; the pass
    recomputes topological order and flags a mismatch."""
    from flexflow_tpu.analysis import collectives
    from jax.sharding import PartitionSpec as P

    order = [n.name for n in searched.graph.topo_order()
             if n.weight_specs]

    class Ctx:
        update_specs = {
            (order[-1], "kernel"): (P("data"), (32, 32)),
            (order[0], "kernel"): (P("data"), (32, 32)),
        }

    codes = [f.code for f in
             collectives.run(searched.graph, searched.mesh, Ctx())]
    assert "nondeterministic_bucket_order" in codes

    class Ok:
        update_specs = {
            (order[0], "kernel"): (P("data"), (32, 32)),
            (order[-1], "kernel"): (P("data"), (32, 32)),
        }

    codes = [f.code for f in
             collectives.run(searched.graph, searched.mesh, Ok())]
    assert "nondeterministic_bucket_order" not in codes


# ================================================================ lint


def test_lint_host_sync_in_loop():
    from flexflow_tpu.analysis.lint import lint_source

    hot = (
        "def fit(self):\n"
        "    for b in batches:\n"
        "        out = step(b)\n"
        "        loss = float(np.asarray(jax.device_get(out)))\n")
    assert [f.code for f in
            lint_source(hot, select=("host_sync_in_loop",))] \
        == ["host_sync_in_loop"]

    gated = (
        "def fit(self):\n"
        "    for b in batches:\n"
        "        out = step(b)\n"
        "        if tel is not None:\n"
        "            loss = float(jax.device_get(out))\n")
    assert lint_source(gated, select=("host_sync_in_loop",)) == []

    derived_gate = (
        "def fit(self, tel):\n"
        "    need_losses = tel is not None\n"
        "    for b in batches:\n"
        "        out = step(b)\n"
        "        loss = (jax.device_get(out) if need_losses else None)\n")
    assert lint_source(derived_gate, select=("host_sync_in_loop",)) == []

    pragma = (
        "def calibrate(self):\n"
        "    for _ in range(3):\n"
        "        t = float(jax.device_get(run()))  "
        "# fflint: ok host_sync_in_loop\n")
    assert lint_source(pragma, select=("host_sync_in_loop",)) == []


def test_lint_unsorted_dict_hash():
    from flexflow_tpu.analysis.lint import lint_source

    bad = (
        "def calibration_fingerprint(db):\n"
        "    entries = []\n"
        "    for k, v in db.items():\n"
        "        entries.append([k, v])\n"
        "    return _sha(entries)\n")
    assert [f.code for f in
            lint_source(bad, select=("unsorted_dict_hash",))] \
        == ["unsorted_dict_hash"]

    ok = bad.replace("db.items()", "sorted(db.items())")
    assert lint_source(ok, select=("unsorted_dict_hash",)) == []

    # dict iteration outside hash context is not the lint's business
    other = (
        "def render(d):\n"
        "    for k, v in d.items():\n"
        "        print(k, v)\n")
    assert lint_source(other, select=("unsorted_dict_hash",)) == []


def test_lint_global_rng():
    from flexflow_tpu.analysis.lint import lint_source

    assert [f.code for f in lint_source(
        "def f():\n    np.random.seed(0)\n",
        select=("global_rng",))] == ["global_rng"]
    assert lint_source(
        "def f():\n    rs = np.random.RandomState(0)\n    rs.shuffle(x)\n",
        select=("global_rng",)) == []


def test_lint_time_in_trace():
    from flexflow_tpu.analysis.lint import lint_source

    jitted = (
        "@jax.jit\n"
        "def step(x):\n"
        "    t = time.time()\n"
        "    return x + t\n")
    assert [f.code for f in
            lint_source(jitted, select=("time_in_trace",))] \
        == ["time_in_trace"]

    scanned = (
        "def chunk(xs):\n"
        "    def body(carry, x):\n"
        "        return carry + time.perf_counter(), x\n"
        "    return jax.lax.scan(body, 0.0, xs)\n")
    assert [f.code for f in
            lint_source(scanned, select=("time_in_trace",))] \
        == ["time_in_trace"]

    host = (
        "def fit(xs):\n"
        "    t0 = time.perf_counter()\n"
        "    out = step(xs)\n"
        "    return out, time.perf_counter() - t0\n")
    assert lint_source(host, select=("time_in_trace",)) == []


def test_fflint_repo_clean():
    """The CI invariant, enforced in tier-1 too: the repo's own runtime
    + scripts code carries zero fflint findings (hazards are either
    fixed or carry an explicit justified pragma)."""
    from flexflow_tpu.analysis.lint import lint_paths

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, p)
             for p in ("flexflow_tpu", "scripts", "bench.py")]
    findings = lint_paths([p for p in paths if os.path.exists(p)])
    assert findings == [], [str(f) for f in findings]


# ============================================================ donation


def test_donation_registry_matches_executor():
    from flexflow_tpu.analysis.donation import registry_problems

    assert registry_problems() == []


def test_donation_registry_detects_drift(tmp_path):
    """If the executor's donate_argnums change and the registry lags,
    the pass fails loudly instead of scanning with stale argnums."""
    from flexflow_tpu.analysis.donation import registry_problems

    fake = tmp_path / "executor.py"
    fake.write_text(
        "class Executor:\n"
        "    def build_train_step(self):\n"
        "        self._train_step = jax.jit(\n"
        "            self._train_step_body,\n"
        "            donate_argnums=_donate_argnums((0, 1)))\n"
        "        return self._train_step\n")
    codes = [f.code for f in registry_problems(str(fake))]
    assert "donation_registry_mismatch" in codes


# ========================================================= integration


def test_report_carries_analysis_section(tmp_path):
    """strategy_report.json surfaces the compile gate's findings in an
    `analysis` section (summary + per-finding entries)."""
    tdir = str(tmp_path / "tel")
    ff, _ = _lm(_config(["--mesh", "2,4,1,1", "--budget", "6",
                         "--enable-parameter-parallel",
                         "--telemetry-dir", tdir, "--diagnostics"]))
    _compile(ff)
    with open(os.path.join(tdir, "strategy_report.json")) as f:
        report = json.load(f)
    a = report.get("analysis")
    assert a is not None
    assert a["errors"] == 0
    assert a["passes_run"] == ["sharding_dataflow", "memory_liveness",
                               "collective_uniformity",
                               "donation_aliasing", "dtype_flow",
                               "spmd_uniformity", "rule_verify"]
    assert any(f["code"] == "memory_timeline" for f in a["findings"])


def test_verify_telemetry_event(tmp_path):
    """The compile gate emits a plan_verify metrics record with the
    summary counts and its elapsed time."""
    from flexflow_tpu.telemetry import read_jsonl

    tdir = str(tmp_path / "tel")
    ff = _compile(_lm(_config(["--mesh", "2,4,1,1",
                               "--telemetry-dir", tdir]))[0])
    assert ff._analysis is not None
    recs = [r for r in read_jsonl(os.path.join(tdir, "metrics.jsonl"))
            if r.get("kind") == "plan_verify"]
    assert recs and recs[0]["errors"] == 0
    assert recs[0]["plan_source"] == "default"
    assert recs[0]["elapsed_s"] >= 0
