"""Auxiliary subsystem tests: checkpoint/resume, RecompileState, DOT export,
dataloader (SURVEY §5)."""

import sys

import numpy as np
import pytest


def _mlp(batch=8, mesh=(2, 1, 1, 1)):
    sys.argv = ["test"]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def test_checkpoint_roundtrip(tmp_path):
    ff = _mlp()
    rs = np.random.RandomState(0)
    x = rs.randn(16, 16).astype(np.float32)
    y = rs.randint(0, 4, (16, 1)).astype(np.int32)
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    w_before = ff.get_weight("fc1", "kernel")
    step_before = int(np.asarray(ff._step))
    path = str(tmp_path / "ckpt")
    ff.save_checkpoint(path)

    ff2 = _mlp()
    assert not np.allclose(ff2.get_weight("fc1", "kernel"), w_before)
    ff2.load_checkpoint(path)
    np.testing.assert_allclose(ff2.get_weight("fc1", "kernel"), w_before)
    assert int(np.asarray(ff2._step)) == step_before
    # resumed model must continue training from the same state: one more
    # epoch on each gives identical weights
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    np.random.seed(None)
    ff2.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    np.testing.assert_allclose(ff2.get_weight("fc1", "kernel"),
                               ff.get_weight("fc1", "kernel"),
                               rtol=1e-5, atol=1e-6)


def test_recompile_state():
    from flexflow_tpu.recompile import RecompileState

    ff = _mlp()
    calls = {"alter": 0}

    def trigger(model):
        return int(np.asarray(model._step)) >= 0

    def alter(model):
        calls["alter"] += 1

    rs_ = RecompileState(trigger, alter, ff)
    assert rs_.trigger()
    old_step = ff.executor._train_step or ff.executor.build_train_step()
    rs_.alter()
    assert calls["alter"] == 1
    assert ff.executor._train_step is None  # step invalidated → retrace
    rs = np.random.RandomState(0)
    ff.fit(rs.randn(8, 16).astype(np.float32),
           rs.randint(0, 4, (8, 1)).astype(np.int32), epochs=1, batch_size=8)


def test_dot_export(tmp_path):
    ff = _mlp()
    dot = ff.export_dot()
    assert "digraph PCG" in dot and "fc1" in dot and "OP_LINEAR" in dot
    p = str(tmp_path / "g.dot")
    ff.export_dot(p)
    assert "digraph" in open(p).read()


def test_single_dataloader():
    ff = _mlp(batch=4)
    rs = np.random.RandomState(0)
    data = rs.randn(10, 16).astype(np.float32)
    x_tensor = ff._input_tensors[0]
    loader = ff.create_data_loader(x_tensor, data)
    assert loader.num_batches == 2
    b1 = loader.next_batch()
    b2 = loader.next_batch()
    np.testing.assert_array_equal(b1, data[:4])
    np.testing.assert_array_equal(b2, data[4:8])
    loader.reset()
    np.testing.assert_array_equal(loader.next_batch(), data[:4])
    sharded = loader.next_batch_sharded()
    assert sharded.shape == (4, 16)


def test_profiling_prints_per_op_table(capsys):
    """--profiling produces the per-op forward/backward table
    (linear_kernels.cu:95-117 analog)."""
    import sys

    import numpy as np

    sys.argv = ["test", "--profiling"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer

    config = FFConfig()
    config.mesh_axis_sizes = (1, 1, 1, 1)
    config.batch_size = 8
    assert config.profiling
    ff = FFModel(config)
    x = ff.create_tensor((8, 32))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="prof_fc1")
    t = ff.dense(t, 10, name="prof_head")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    ff.fit(rs.randn(16, 32).astype(np.float32),
           rs.randint(0, 10, (16, 1)).astype(np.int32), epochs=1)
    out = capsys.readouterr().out
    assert "prof_fc1 [OP_LINEAR] forward time = " in out
    assert "backward time = " in out
    assert "TOTAL" in out
    # printed once, not per epoch
    ff.fit(rs.randn(16, 32).astype(np.float32),
           rs.randint(0, 10, (16, 1)).astype(np.int32), epochs=1)
    assert "prof_fc1" not in capsys.readouterr().out


def test_ps_sync_rejected():
    """ParameterSyncType.PS raises loudly (hub-and-spoke PS is strictly
    dominated by a psum over ICI; the decision must not be silent —
    optimizer_kernel.cu:48-76 is the reference's PS path)."""
    from flexflow_tpu.fftype import DataType, ParameterSyncType
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    shape = ParallelTensorShape.from_shape((4, 4), DataType.DT_FLOAT)
    with pytest.raises(NotImplementedError, match="psum"):
        ParallelTensor(shape, sync_type=ParameterSyncType.PS)
    # NCCL and NONE still construct
    ParallelTensor(shape, sync_type=ParameterSyncType.NCCL)
    ParallelTensor(shape, sync_type=ParameterSyncType.NONE)


def test_strategy_unknown_node_names_warn():
    """A strategy carrying node names absent from the graph (e.g. rewrite-
    generated names broadcast to a host that didn't rewrite) warns instead
    of silently dropping placements (ADVICE r4)."""
    import warnings

    ff = _mlp()
    ff._strategy = {"no_such_node": {"outputs": {}, "weights": {}}}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ff._assign_strategy()
    assert any("no_such_node" in str(x.message) for x in w)
