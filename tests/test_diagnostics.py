"""Diagnostics subsystem: drift monitor, health rules, strategy explain,
run doctor, and the hardened telemetry satellites.

The drift/health units run on synthetic metric streams (injected NaN loss,
step-time spike, data-wait stall, drifting predictions) asserting the
right alerts/actions fire — and don't fire on clean runs. The e2e tests
cover the acceptance criteria: a tiny --diagnostics fit whose
strategy_report.json per-op costs reproduce the plan's total predicted
cost under the makespan rule, and an injected-NaN run producing the
corresponding alert in alerts.jsonl.
"""

import json
import math
import sys

import numpy as np
import pytest

from flexflow_tpu import telemetry
from flexflow_tpu.diagnostics import (
    Alert,
    CheckpointStalenessRule,
    DataWaitStallRule,
    DriftMonitor,
    HealthAbort,
    HealthMonitor,
    NaNLossRule,
    StepSpikeRule,
    verify_report_total,
)
from flexflow_tpu.telemetry.recorder import MetricsRecorder, read_jsonl


@pytest.fixture(autouse=True)
def _no_session_leak():
    yield
    telemetry.deactivate()


def _step_rec(step, loss=1.0, step_time=0.1, data_wait=0.005, t=None):
    return {"step": step, "epoch": 0, "t": t if t is not None else 1e9 + step,
            "step_time_s": step_time, "data_wait_s": data_wait,
            "save_latency_s": 0.0,
            "device_time_s": max(0.0, step_time - data_wait),
            "loss": loss}


# ---------------------------------------------------------------- drift

@pytest.mark.quick
def test_drift_monitor_clean_run_no_advisory():
    m = DriftMonitor(predicted_s=0.1, threshold=0.5, warmup=3)
    for i in range(50):
        # measured within 10% of predicted: error EMA stays < threshold
        assert m.observe(i, 0.1 * (1.0 + 0.1 * (-1) ** i)) is None
    assert m.advisories == []
    assert m.error_ema < 0.2


@pytest.mark.quick
def test_drift_monitor_fires_once_on_sustained_drift():
    m = DriftMonitor(predicted_s=0.1, threshold=0.5, warmup=3)
    advisories = [m.observe(i, 0.5) for i in range(30)]  # 5x predicted
    fired = [a for a in advisories if a is not None]
    assert len(fired) == 1  # hysteresis: one advisory per excursion
    adv = fired[0]
    assert adv.error_ema > 0.5
    assert adv.predicted_s == 0.1
    assert "drift" in adv.message
    rec = adv.to_record()
    assert rec["rule"] == "costmodel_drift"
    json.dumps(rec)  # serializable as an alerts.jsonl record


@pytest.mark.quick
def test_drift_monitor_rearms_after_recovery_and_reset():
    m = DriftMonitor(predicted_s=0.1, threshold=0.5, warmup=2,
                     ema_alpha=0.5)
    for i in range(10):
        m.observe(i, 0.5)
    assert len(m.advisories) == 1
    # measured returns to predicted: EMA decays under threshold/2, re-arms
    for i in range(10, 40):
        m.observe(i, 0.1)
    assert len(m.advisories) == 1
    for i in range(40, 60):
        m.observe(i, 0.5)
    assert len(m.advisories) == 2
    # a recalibration points the monitor at the new prediction and resets
    m.set_prediction(0.5)
    for i in range(60, 80):
        assert m.observe(i, 0.5) is None
    assert len(m.advisories) == 2


@pytest.mark.quick
def test_drift_monitor_drives_recompile_state():
    from flexflow_tpu.recompile import RecompileState

    calls = []

    class _FakeModel:
        executor = None  # alter() invalidates the compiled step via this

    rs = RecompileState(trigger_func=lambda ff: True,
                        alter_func=lambda ff: calls.append(1),
                        ffmodel=_FakeModel())
    m = DriftMonitor(predicted_s=0.1, threshold=0.5, warmup=2,
                     recompile_state=rs)
    for i in range(20):
        m.observe(i, 1.0)
    assert calls == [1]
    assert rs.recompilations == 1


@pytest.mark.quick
def test_drift_monitor_ignores_nonfinite_measurements():
    m = DriftMonitor(predicted_s=0.1, threshold=0.5, warmup=0)
    assert m.observe(1, float("nan")) is None
    assert m.observe(2, float("inf")) is None
    assert m.observe(3, -1.0) is None
    assert m.samples == 0


# ---------------------------------------------------------------- health

@pytest.mark.quick
def test_nan_loss_rule_fires_once():
    r = NaNLossRule()
    assert r.check(_step_rec(1, loss=0.5)) is None
    a = r.check(_step_rec(2, loss=float("nan")))
    assert a is not None and a.rule == "nan_loss" and a.level == "error"
    assert a.step == 2
    # latched: a dead run gets ONE alert, not one per remaining step
    assert r.check(_step_rec(3, loss=float("inf"))) is None


@pytest.mark.quick
def test_step_spike_rule_warmup_and_fire():
    r = StepSpikeRule(factor=3.0, warmup=3)
    # step 1 is a compile-sized spike but inside warmup: no alert
    assert r.check(_step_rec(1, step_time=5.0)) is None
    for i in range(2, 10):
        assert r.check(_step_rec(i, step_time=0.1)) is None
    a = r.check(_step_rec(10, step_time=1.0))
    assert a is not None and a.rule == "step_spike"
    assert a.value == 1.0
    # the spike did not poison the EMA baseline
    assert r.check(_step_rec(11, step_time=0.1)) is None
    # a sustained incident inside the cooldown window must not creep into
    # the baseline either: after the cooldown expires it re-alerts against
    # the ORIGINAL ~0.1s EMA
    baseline = r._ema
    for i in range(12, 21):  # within cooldown of the step-10 fire
        assert r.check(_step_rec(i, step_time=1.0)) is None  # suppressed
    assert r._ema == baseline
    # cooldown expired: the still-ongoing incident re-alerts against the
    # ORIGINAL baseline, not one inflated by the suppressed samples
    again = r.check(_step_rec(21, step_time=1.0))
    assert again is not None and again.threshold == pytest.approx(
        3.0 * baseline)


@pytest.mark.quick
def test_data_wait_stall_rule():
    r = DataWaitStallRule(ratio=0.5, warmup=3)
    for i in range(1, 20):
        a = r.check(_step_rec(i, step_time=0.1, data_wait=0.08))
        if a is not None:
            assert a.rule == "data_wait_stall"
            assert a.value > 0.5
            break
    else:
        pytest.fail("sustained 80% data-wait never alerted")
    # clean stream: no alert
    r2 = DataWaitStallRule(ratio=0.5, warmup=3)
    for i in range(1, 50):
        assert r2.check(_step_rec(i, step_time=0.1, data_wait=0.01)) is None


@pytest.mark.quick
def test_checkpoint_staleness_rule():
    r = CheckpointStalenessRule(max_age_s=100.0)
    # no commit observed yet: silent (nothing to be stale relative to)
    assert r.check(_step_rec(1, t=1000.0)) is None
    r.note_commit(1000.0)
    assert r.check(_step_rec(2, t=1050.0)) is None
    a = r.check(_step_rec(3, t=1200.0))
    assert a is not None and a.rule == "ckpt_stale" and a.value == 200.0
    r.note_commit(1201.0)
    assert r.check(_step_rec(4, t=1250.0)) is None


@pytest.mark.quick
def test_health_monitor_clean_run_no_alerts():
    sunk = []
    hm = HealthMonitor(sink=sunk.append)
    for i in range(1, 40):
        hm.observe_step(_step_rec(i, loss=1.0 / i, step_time=0.1))
    assert hm.alerts == [] and sunk == []


@pytest.mark.quick
def test_health_monitor_abort_action():
    sunk = []
    hm = HealthMonitor(abort_on=("nan_loss",), sink=sunk.append)
    hm.observe_step(_step_rec(1))
    with pytest.raises(HealthAbort) as ei:
        hm.observe_step(_step_rec(2, loss=float("nan")))
    assert ei.value.alert.action == "abort"
    assert ei.value.alert.level == "error"
    # the alert reached the sink BEFORE the raise (artifacts first)
    assert len(sunk) == 1 and sunk[0].rule == "nan_loss"


@pytest.mark.quick
def test_health_monitor_rejects_unknown_abort_rule():
    with pytest.raises(ValueError, match="unknown rules"):
        HealthMonitor(abort_on=("no_such_rule",))


@pytest.mark.quick
def test_ckpt_stale_abortable_without_checkpointing():
    """ckpt_stale is always a known rule name — --health-abort-on
    ckpt_stale must validate even when this run doesn't checkpoint (the
    rule just stays dormant: no commit clock is ever fed)."""
    hm = HealthMonitor(abort_on=("ckpt_stale",))
    for i in range(1, 20):
        hm.observe_step(_step_rec(i))  # never aborts: rule dormant
    assert hm.alerts == []
    # and set_abort_on re-validates
    with pytest.raises(ValueError, match="unknown rules"):
        hm.set_abort_on(("bogus",))


# ------------------------------------------------------- telemetry satellites

@pytest.mark.quick
def test_read_jsonl_tolerates_truncated_final_line(tmp_path):
    p = tmp_path / "metrics.jsonl"
    p.write_text('{"kind": "manifest", "t": 1.0}\n'
                 '{"kind": "step", "t": 2.0, "step": 1}\n'
                 '{"kind": "step", "t": 3.0, "st')  # mid-write SIGKILL
    recs = read_jsonl(str(p))
    assert [r["kind"] for r in recs] == ["manifest", "step"]
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(str(p), strict=True)
    # corruption that is NOT a torn tail still raises
    p2 = tmp_path / "corrupt.jsonl"
    p2.write_text('{"kind": "manifest"\n{"kind": "step", "t": 2.0}\n')
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(str(p2))


@pytest.mark.quick
def test_recorder_counts_late_writes_after_close(tmp_path):
    rec = MetricsRecorder(str(tmp_path / "m.jsonl"))
    rec.record("step", step=1)
    rec.close()
    rec.record("late", step=2)
    rec.record("late", step=3)
    assert rec.dropped_after_close == 2
    assert len(read_jsonl(str(tmp_path / "m.jsonl"))) == 1


@pytest.mark.quick
def test_session_summary_surfaces_dropped_trace_events(tmp_path, capsys):
    sess = telemetry.TelemetrySession(str(tmp_path / "tel"))
    sess.tracer.max_events = 4
    for i in range(20):
        sess.tracer.instant("spam", i=i)
    sess.record_step(1, 0, 0.1, 0.0, 0.0, batch_size=8)
    sess.write_summary()
    sess.close()
    summary = [r for r in read_jsonl(str(tmp_path / "tel/metrics.jsonl"))
               if r["kind"] == "summary"][-1]
    assert summary["trace_dropped_events"] > 0
    assert "dropped" in capsys.readouterr().err


# ---------------------------------------------------------------- explain

def _compiled_tp_model(tmp_path, extra_argv=()):
    sys.argv = ["test"] + list(extra_argv)
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 16)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _train_data(n=128, in_dim=64, classes=16):
    rs = np.random.RandomState(0)
    return (rs.randn(n, in_dim).astype(np.float32),
            rs.randint(0, classes, (n, 1)).astype(np.int32))


def test_strategy_report_makespan_property_and_runner_ups(tmp_path):
    """Acceptance: per-op predicted costs sum — under the makespan rule —
    to the plan's total predicted cost; runner-up plans carry the margin
    by which they lost."""
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, [
        "--telemetry-dir", str(tdir), "--diagnostics",
        "--budget", "8", "--enable-parameter-parallel",
        "--mesh", "4,2,1,1"])
    rep = json.load(open(tdir / "strategy_report.json"))
    assert rep["mode"] == "searched"
    assert rep["ops"] and rep["edges"]
    recomputed = verify_report_total(rep)
    assert recomputed == pytest.approx(rep["total_predicted_s"], rel=1e-9)
    # attribution splits are internally consistent
    for o in rep["ops"]:
        assert o["compute_s"] == pytest.approx(
            o["forward_s"] + o["backward_s"], rel=1e-9)
    assert rep["sum_compute_s"] == pytest.approx(
        sum(o["compute_s"] for o in rep["ops"]), rel=1e-9)
    # a 4x2 mesh with TP candidates has real runner-ups, ranked by margin
    assert rep["runner_ups"]
    margins = [r["margin_s"] for r in rep["runner_ups"]]
    assert margins == sorted(margins)
    assert all(m >= 0 for m in margins)  # the search picked the winner
    # markdown twin exists and names the winner's total
    md = (tdir / "strategy_report.md").read_text()
    assert "predicted step makespan" in md
    assert "Runner-up plans" in md
    # drift monitor was armed with the report's prediction
    assert ff._predicted_step_s == rep["total_predicted_s"]
    telemetry.deactivate()


def test_strategy_report_identity_with_overlap_sync(tmp_path):
    """--search-overlap-backward-update changes the makespan rule (sync
    overlaps compute but occupies its ICI axis); the report carries the
    flag and verify_report_total reproduces the total under that rule
    too."""
    tdir = tmp_path / "tel"
    _compiled_tp_model(tmp_path, [
        "--telemetry-dir", str(tdir), "--diagnostics",
        "--budget", "8", "--enable-parameter-parallel",
        "--search-overlap-backward-update", "--mesh", "4,2,1,1"])
    rep = json.load(open(tdir / "strategy_report.json"))
    assert rep["overlap_sync"] is True
    assert verify_report_total(rep) == pytest.approx(
        rep["total_predicted_s"], rel=1e-9)
    telemetry.deactivate()


def test_enable_diagnostics_applies_late_settings(tmp_path):
    """A second enable_diagnostics with explicit settings (the keras
    Diagnostics callback after --diagnostics attached a manager at
    compile) must apply them, not silently return the old config."""
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, ["--telemetry-dir", str(tdir),
                                       "--diagnostics"])
    diag = ff.get_diagnostics()
    assert diag.health.abort_on == frozenset()
    same = ff.enable_diagnostics(abort_on=("nan_loss",),
                                 drift_threshold=0.1)
    assert same is diag
    assert diag.health.abort_on == frozenset({"nan_loss"})
    assert diag.drift.threshold == 0.1
    # a later call with everything unset (the keras Diagnostics callback's
    # defaults) inherits — it must NOT reset the explicit settings above
    ff.enable_diagnostics()
    assert diag.health.abort_on == frozenset({"nan_loss"})
    assert diag.drift.threshold == 0.1
    from flexflow_tpu.keras.callbacks import Diagnostics as KDiag

    assert KDiag("x").abort_on is None and KDiag("x").drift_threshold is None
    telemetry.deactivate()


def test_strategy_report_dp_fallback_without_search(tmp_path):
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, ["--telemetry-dir", str(tdir),
                                       "--diagnostics"])
    rep = json.load(open(tdir / "strategy_report.json"))
    assert rep["mode"] == "dp_fallback"
    assert verify_report_total(rep) == pytest.approx(
        rep["total_predicted_s"], rel=1e-9)
    assert ff.get_diagnostics() is not None
    telemetry.deactivate()


# ---------------------------------------------------------------- fit e2e

def test_fit_with_diagnostics_nan_injection_alerts(tmp_path):
    """Acceptance: an injected-NaN run produces the corresponding alert in
    alerts.jsonl (and aborts when the rule is in --health-abort-on)."""
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, [
        "--telemetry-dir", str(tdir), "--diagnostics",
        "--health-abort-on", "nan_loss"])
    x, y = _train_data()
    x[40, 3] = np.nan  # poison one batch
    with pytest.raises(HealthAbort):
        ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    alerts = read_jsonl(tdir / "alerts.jsonl")
    nan_alerts = [a for a in alerts if a.get("rule") == "nan_loss"]
    assert len(nan_alerts) == 1
    assert nan_alerts[0]["action"] == "abort"
    assert nan_alerts[0]["level"] == "error"
    # telemetry artifacts survived the abort (the finally flushed them)
    assert (tdir / "trace.json").exists()
    recs = read_jsonl(tdir / "metrics.jsonl")
    assert [r for r in recs if r["kind"] == "step"]
    telemetry.deactivate()


def test_fit_clean_run_emits_no_health_alerts(tmp_path):
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, ["--telemetry-dir", str(tdir),
                                       "--diagnostics"])
    x, y = _train_data()
    ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    alerts = read_jsonl(tdir / "alerts.jsonl")
    # CPU wall time vs the analytic TPU roofline may legitimately emit a
    # drift advisory; HEALTH alerts (nan/spike/stall) must stay silent
    assert [a for a in alerts if a.get("kind") == "alert"] == []
    diag = ff.get_diagnostics()
    assert diag.health.alerts == []
    recs = read_jsonl(tdir / "metrics.jsonl")
    assert [r for r in recs if r["kind"] == "diagnostics_summary"]
    assert [r for r in recs if r["kind"] == "strategy_report"]
    telemetry.deactivate()


def test_fit_without_diagnostics_unchanged(tmp_path):
    """--telemetry-dir alone must not attach diagnostics (no report, no
    alerts file, no per-step loss fetch)."""
    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, ["--telemetry-dir", str(tdir)])
    x, y = _train_data()
    ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    assert ff.get_diagnostics() is None
    assert not (tdir / "alerts.jsonl").exists()
    assert not (tdir / "strategy_report.json").exists()
    telemetry.deactivate()


def test_keras_diagnostics_callback(tmp_path):
    sys.argv = ["test"]
    from flexflow_tpu.keras.callbacks import Diagnostics
    from flexflow_tpu.keras.layers import Dense, Input
    from flexflow_tpu.keras.models import Model

    tdir = tmp_path / "keras_diag"
    inp = Input(shape=(16,))
    out = Dense(10, activation="softmax")(Dense(32, activation="relu")(inp))
    model = Model(inputs=inp, outputs=out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rs = np.random.RandomState(0)
    x = rs.randn(128, 16).astype(np.float32)
    y = rs.randint(0, 10, (128, 1)).astype(np.int32)
    model.fit(x, y, epochs=2, callbacks=[Diagnostics(str(tdir))])
    rep = json.load(open(tdir / "strategy_report.json"))
    assert verify_report_total(rep) == pytest.approx(
        rep["total_predicted_s"], rel=1e-9)
    assert (tdir / "alerts.jsonl").exists()
    assert model.ffmodel.get_diagnostics() is not None
    telemetry.deactivate()


# ---------------------------------------------------------------- doctor

def test_run_doctor_post_mortem(tmp_path):
    from flexflow_tpu.diagnostics.doctor import diagnose, render

    tdir = tmp_path / "tel"
    ff = _compiled_tp_model(tmp_path, [
        "--telemetry-dir", str(tdir), "--diagnostics",
        "--health-abort-on", "nan_loss"])
    x, y = _train_data()
    x[40, 3] = np.nan
    with pytest.raises(HealthAbort):
        ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    telemetry.deactivate()

    d = diagnose(str(tdir))
    assert d["verdict"] == "dead"
    assert d["steps"] >= 1
    assert any(a["rule"] == "nan_loss" for a in d["alerts"])
    assert d["strategy_report"] is not None
    md = render(d)
    assert "Verdict: DEAD" in md
    assert "nan_loss" in md
    assert "Strategy (top ops by predicted cost)" in md


@pytest.mark.quick
def test_run_doctor_empty_dir_and_corrupt_logs(tmp_path):
    from flexflow_tpu.diagnostics.doctor import diagnose, render

    d = diagnose(str(tmp_path))
    assert d["verdict"] == "no-steps"
    assert d["alerts"] == []
    render(d)  # renders without error on a dir with no artifacts
    # mid-file corruption (not just a torn tail) degrades to the records
    # that still parse — the doctor exists to explain damaged runs
    (tmp_path / "metrics.jsonl").write_text(
        '{"kind": "manifest", "t": 1.0}\n'
        'GARBAGE NOT JSON\n'
        '{"kind": "step", "t": 2.0, "step": 1, "step_time_s": 0.1}\n')
    d = diagnose(str(tmp_path))
    assert d["steps"] == 1
    assert d["manifest"]["kind"] == "manifest"
    render(d)
