"""Disaggregated prefill/decode serving + radix prefix cache tests
(serving/disagg.py, serving/radix.py, docs/serving.md).

The acceptance surface of the split-pool serving path on the 8-device
CPU mesh:

  - the radix cache's LRU eviction can never free a block a live slot's
    page table still maps (eviction only ever takes cached-ONLY blocks);
  - a longest-prefix-match admission is token-identical to the cold
    path — mapped prefix KV reads back exactly what recompute writes;
  - a slot's decode extension never poisons the published prefix
    (registration keys on the prompt extent; the tail block COWs);
  - disaggregated serving is bit-identical to the unified engine, and
    every KV handoff references a verified fftrans transfer program
    whose predicted seconds reproduce from the program alone;
  - a prefix published before a FULL drain is still matched by a
    re-admission after it (the cross-time cache's reason to exist);
  - the prefill:decode ratio trigger produces payoff-gated decision
    records the doctor's elastic gate reproduces arithmetically.
"""

import sys

import numpy as np
import pytest


def _lm_config():
    from flexflow_tpu.models import TransformerLMConfig

    return TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
        sequence_length=32, attention_impl="xla")


def _build_lm(mesh=(8, 1, 1, 1), batch=8, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = mesh
    cfg.batch_size = batch
    ff = FFModel(cfg)
    build_transformer_lm(ff, _lm_config(), batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


# --------------------------------------------------------- radix (host-side)


def test_radix_lru_eviction_never_frees_live_block():
    """Pool pressure evicts cached-ONLY blocks, never a block a live
    slot maps: fill the pool past its budget with distinct published
    prefixes while one resident stays live, and at every step the live
    slot's table entries must stay allocated to it."""
    from flexflow_tpu.serving.paged import BlockManager

    bs = 4
    mgr = BlockManager(num_blocks=12, block_size=bs, table_width=8,
                       cross_time=True)
    live_prompt = list(range(100, 100 + 2 * bs))
    assert mgr.reserve("live", len(live_prompt), bs)
    mgr.bind_reservation("live", 0)
    mgr.admit(0, live_prompt)
    for pos in range(len(live_prompt)):
        mgr.ensure_writable(0, [pos])
    mgr.register_prompt(0, live_prompt)
    live_blocks = set(mgr.table(0)[:2])

    # churn: distinct prompts published then released, until the pool
    # has recycled its whole evictable budget several times over
    for i in range(8):
        p = [200 + 10 * i + j for j in range(2 * bs)]
        assert mgr.reserve(f"r{i}", len(p), bs), \
            f"churn request {i} could not reserve (eviction failed)"
        mgr.bind_reservation(f"r{i}", 1)
        mgr.admit(1, p)
        for pos in range(len(p)):
            mgr.ensure_writable(1, [pos])
        mgr.register_prompt(1, p)
        mgr.release(1)
        # the live slot's mapping survives every eviction round
        assert set(mgr.table(0)[:2]) == live_blocks
        for blk in live_blocks:
            assert mgr.refcount(blk) >= 1, \
                f"live block {blk} lost its slot reference"
            assert blk not in mgr._free, \
                f"live block {blk} returned to the free list"
        mgr.check_invariants()
    assert mgr.stats.radix_evictions > 0, \
        "churn never exercised eviction — test is vacuous"
    mgr.release(0)
    mgr.check_invariants()


def test_radix_eviction_only_takes_cached_only_blocks():
    """The evictable set is exactly `cached_only_blocks`: blocks whose
    only holder is the cache pin. A published prefix whose resident is
    still live contributes zero evictable blocks."""
    from flexflow_tpu.serving.paged import BlockManager

    bs = 4
    mgr = BlockManager(num_blocks=8, block_size=bs, table_width=8,
                       cross_time=True)
    p = list(range(2 * bs))
    assert mgr.reserve("a", len(p), bs)
    mgr.bind_reservation("a", 0)
    mgr.admit(0, p)
    for pos in range(len(p)):
        mgr.ensure_writable(0, [pos])
    mgr.register_prompt(0, p)
    assert mgr.cached_blocks == 2
    assert mgr.cached_only_blocks == 0  # live slot still maps both
    before = mgr.stats.radix_evicted_blocks
    assert mgr._evict_blocks(2) == 0, \
        "eviction freed blocks while their resident was live"
    assert mgr.stats.radix_evicted_blocks == before
    mgr.release(0)
    assert mgr.cached_only_blocks == 2  # now evictable
    assert mgr._evict_blocks(2) == 2
    mgr.check_invariants()


# ---------------------------------------------------------- engine identity


@pytest.fixture(scope="module")
def lm():
    return _build_lm()


SHARED = [1, 2, 3, 4, 5, 6, 7, 8, 9]


def test_longest_prefix_match_token_identity(lm):
    """A radix-matched admission (prompt extends a published prefix)
    decodes the SAME tokens as a cold engine that recomputes every
    prompt position — mapped KV must read back bit-exactly."""
    kw = dict(slots=2, max_new_tokens=6, prefill_chunk=4)
    cold = lm.serve(**kw)
    warm = lm.serve(**kw)
    extended = SHARED + [40, 41, 42]
    want = cold.generate([extended])

    first = warm.submit(SHARED)
    warm.run_until_drained()
    assert first.matched_prefix_len == 0  # nothing published yet
    req = warm.submit(extended)
    warm.run_until_drained()
    assert req.matched_prefix_len and req.matched_prefix_len > 0, \
        "the shared prefix was not matched — cache cold"
    assert [req.generated] == want, \
        "prefix-matched decode diverged from the cold path"


def test_decode_extension_never_poisons_cache(lm):
    """Regression: registration covers the PROMPT extent only, and a
    resident's decode tokens COW off the published tail block — a later
    request matching the same prompt must decode exactly like a cold
    engine, not see request A's generated rows."""
    kw = dict(slots=2, prefill_chunk=4)
    cold = lm.serve(**kw)
    warm = lm.serve(**kw)
    # A generates MANY tokens: they land in (and beyond) the partial
    # tail block of the prompt extent that register_prompt published
    a = warm.submit(SHARED, max_new_tokens=10)
    warm.run_until_drained()
    assert len(a.generated) == 10
    b = warm.submit(SHARED, max_new_tokens=10)
    warm.run_until_drained()
    assert b.matched_prefix_len and b.matched_prefix_len > 0
    want = cold.generate([SHARED], max_new_tokens=10)
    assert [b.generated] == want, \
        "cached prefix was poisoned by the first resident's decode"
    assert b.generated == a.generated  # same prompt, greedy


def test_disagg_token_identity_and_verified_handoffs(lm):
    """Disaggregated serving (two Unity plans on disjoint sub-meshes,
    per-request KV handoff) is bit-identical to the unified engine, and
    every handoff's transfer program re-verifies from its own JSON."""
    from flexflow_tpu.analysis.transition import verify_transition_total

    kw = dict(slots=4, max_new_tokens=6, prefill_chunk=4)
    prompts = [SHARED, SHARED + [40, 41], [20, 21, 22], SHARED]
    want = lm.serve(**kw).generate(prompts)
    dis = lm.serve(disaggregate=True, **kw)
    assert dis.prefill_chips == 4 and dis.decode_chips == 4
    assert dict(dis.prefill.decode_model.mesh.shape)["data"] == 4
    assert dict(dis.decode.decode_model.mesh.shape)["data"] == 4
    got = dis.generate(prompts)
    assert got == want, "disaggregated decode diverged from unified"

    sec = dis.disagg_section()
    assert sec["summary"]["count"] == len(prompts)
    assert not dis._pending and not dis._kv_stash
    for h in sec["handoffs"]:
        if h["injected_blocks"] == 0:
            assert h["predicted_s"] == 0.0
            continue
        prog = sec["programs"][str(h["injected_blocks"])]
        assert prog["analysis"]["errors"] == 0
        total = verify_transition_total(prog)
        assert abs(total - prog["predicted_s"]) < 1e-9
        assert abs(h["predicted_s"] - prog["predicted_s"]) < 1e-9
        kinds = {c["kind"] for t in prog["transfers"]
                 for c in t["collectives"]}
        assert kinds == {"host_hop"}, \
            "handoff rows must be modeled as host hops"
    # the decode side saw the shared prefix arrive more than once: the
    # later handoffs land radix-cached (fewer rows moved than blocks)
    assert any(h["injected_blocks"] < h["prompt_blocks"]
               or h["injected_blocks"] == 0
               for h in sec["handoffs"][1:])


def test_disagg_cross_time_prefix_hit_after_drain(lm):
    """The decode-side radix cache survives a FULL drain: a prompt
    handed off, decoded, completed, and released is matched when the
    same prompt is re-admitted later — zero injection on the re-run."""
    kw = dict(slots=2, max_new_tokens=5, prefill_chunk=4)
    dis = lm.serve(disaggregate=True, **kw)
    first = dis.generate([SHARED])
    assert dis.drained
    assert dis.decode.scheduler.drained  # nothing resident anywhere
    second = dis.generate([SHARED])
    assert second == first
    assert dis.decode.block_manager.stats.cross_time_hits > 0, \
        "the re-admitted prompt missed the cross-time cache"
    # the re-run's handoff moved nothing: its full extent was cached
    assert dis.handoffs[-1]["injected_blocks"] == 0
    assert dis.handoffs[-1]["predicted_s"] == 0.0


def test_disagg_requests_finishing_at_prefill(lm):
    """EOS on the first token and one-token budgets complete on the
    prefill pool without a handoff; the decode side still records the
    completion (the pair's single accounting point)."""
    kw = dict(slots=2, prefill_chunk=4)
    dis = lm.serve(disaggregate=True, **kw)
    uni = lm.serve(**kw)
    want = uni.generate([[5, 6, 7]], max_new_tokens=1)
    req = dis.submit([5, 6, 7], max_new_tokens=1)
    dis.run_until_drained()
    assert [req.generated] == want
    assert req.finish_reason == "max_tokens"
    assert not dis.handoffs, "a one-token request must not hand off"
    assert req in dis.decode.scheduler.completed
    # EOS at prefill: make the first sampled token the eos_id
    eos = want[0][0]
    req2 = dis.submit([5, 6, 7], max_new_tokens=8, eos_id=eos)
    dis.run_until_drained()
    assert req2.finish_reason == "eos"
    assert req2.generated == [eos]
    assert len(dis.handoffs) == 0


def test_disagg_ratio_trigger_payoff_record(lm):
    """maybe_rebalance prices the proposed chip-ratio shift through the
    payoff inequality and records BOTH sides from their factors — the
    exact arithmetic run_doctor's elastic gate recomputes. A declined
    decision moves no chips."""
    kw = dict(slots=4, max_new_tokens=5, prefill_chunk=4)
    dis = lm.serve(disaggregate=True, **kw)
    dis.generate([[i, i + 1, i + 2] for i in range(1, 9)])
    assert dis.maybe_rebalance() is None or True  # thresholds not met OK
    # force a proposal, then make migration unpayable: horizon 0 means
    # rhs == 0, so the inequality can never hold
    dis.rebalance_min_samples = 1
    dis.rebalance_factor = 0.0001
    before = (dis.prefill_chips, dis.decode_chips)
    d = dis.maybe_rebalance(horizon_steps=0)
    assert d is not None and d["decision"] == "declined"
    assert (dis.prefill_chips, dis.decode_chips) == before
    assert d["lhs_s"] == pytest.approx(
        d["predicted_migration_s"] * d["fidelity_ratio"])
    assert d["rhs_s"] == pytest.approx(
        d["benefit_s_per_step"] * d["horizon_steps"])
    assert not d["would_migrate"]
    assert d in lm._elastic_decisions  # rides the doctor's elastic gate
    assert d["new_prefill_chips"] != before[0]
    assert d["predicted_migration_s"] > 0


@pytest.mark.slow
def test_disagg_rebalance_execution_bit_identity(lm):
    """An APPROVED ratio shift replans both sides onto the new disjoint
    windows (shrinking side first) and decode stays bit-identical to
    the unified engine across the move."""
    kw = dict(slots=4, max_new_tokens=6, prefill_chunk=4)
    want = lm.serve(**kw).generate([SHARED, [7, 8, 9]])
    dis = lm.serve(disaggregate=True, **kw)
    dis.generate([[i, i + 1, i + 2] for i in range(1, 9)])
    dis.rebalance_min_samples = 1
    dis.rebalance_factor = 0.0001
    d = dis.maybe_rebalance(horizon_steps=10 ** 6)
    assert d is not None and d["decision"] == "migrated"
    assert dis.prefill_chips == d["new_prefill_chips"]
    assert dis.prefill_chips + dis.decode_chips == 8
    assert dict(dis.prefill.decode_model.mesh.shape)["data"] == \
        dis.prefill_chips
    got = dis.generate([SHARED, [7, 8, 9]])
    assert got == want, "post-rebalance decode diverged"
