"""End-to-end training: the framework's minimum slice.

Mirrors the reference's E2E gate (examples/python/native/mnist_mlp.py:66-73 —
MLP must reach >=90% train accuracy) using a synthetic separable dataset so
the test needs no dataset download.
"""

import sys

import numpy as np
import pytest


def make_model(argv, hidden=64, num_classes=10, in_dim=64, batch=32):
    sys.argv = ["test"] + argv
    from flexflow_tpu import (
        ActiMode,
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((batch, in_dim))
    t = ff.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, hidden, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, num_classes)
    t = ff.softmax(t)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[
            MetricsType.METRICS_ACCURACY,
            MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
        ],
    )
    return ff


def synthetic_classification(n=2048, in_dim=64, num_classes=10, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(num_classes, in_dim) * 3.0
    y = rs.randint(0, num_classes, n)
    x = centers[y] + rs.randn(n, in_dim)
    return x.astype(np.float32), y.astype(np.int32).reshape(n, 1)


def test_mlp_accuracy_gate():
    batch = 32
    ff = make_model([], batch=batch)
    x, y = synthetic_classification()
    ff.fit(x, y, epochs=3, batch_size=batch)
    acc = ff.get_perf_metrics().get_accuracy()
    assert acc >= 0.9, f"accuracy gate failed: {acc}"


def test_mlp_data_parallel_mesh():
    """Same model, 8-way data parallel over the virtual mesh."""
    batch = 32
    ff = make_model(["--mesh", "8,1,1,1"], batch=batch)
    assert ff.mesh.devices.size == 8
    x, y = synthetic_classification()
    ff.fit(x, y, epochs=3, batch_size=batch)
    acc = ff.get_perf_metrics().get_accuracy()
    assert acc >= 0.9, f"accuracy gate failed: {acc}"


def test_granular_train_loop():
    """forward/zero_gradients/backward/update parity loop
    (transformer.cc:183-197 pattern)."""
    batch = 32
    ff = make_model([], batch=batch)
    x, y = synthetic_classification(n=256)
    losses = []
    for it in range(8):
        sl = slice(it * batch, (it + 1) * batch)
        ff.start_batch(x[sl], y[sl])
        ff.forward()
        ff.zero_gradients()
        lval = ff.backward()
        ff.update()
        losses.append(float(lval))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_eval_inference_mode():
    batch = 32
    ff = make_model([], batch=batch)
    x, y = synthetic_classification(n=512)
    ff.fit(x, y, epochs=2, batch_size=batch)
    metrics = ff.eval(x, y, batch_size=batch)
    assert metrics.get_accuracy() >= 0.9
